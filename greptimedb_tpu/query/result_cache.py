"""Frontend result-set cache: completed query payloads served edge-side.

The third leg of the device-resident result path: where the session
registry (query/sessions.py) keeps device RESULT buffers resident and
the grid caches keep input state resident, this cache keeps the
*finished host result* of a statement and serves a repeated poll
without touching the datanode (or the device) at all — the tf.data
"cache at the serving edge instead of recomputing per poll" design.

Keyed on (database, table id, normalized-statement fingerprint);
validated against the table's PHYSICAL version set (storage/region.py
physical_version — write, flush, compact, truncate, ALTER all bump it;
region migration re-anchors it), the same discipline as the datanode
merged-scan cache. Prepared-statement params are substituted into the
text before parsing, so they ride the fingerprint. TTL'd tables bypass
(their scan window is wall-clock-derived); plans containing volatile
functions (now()/random()/...) bypass; EXPLAIN ANALYZE bypasses so its
metrics reflect a real execution.

`since` delta polls serve from the cached FULL result by a host-side
row filter on the time-index output column — zero datanode traffic,
zero device readback. A miss while `since` is bound executes the delta
(sliced device readback) and is NOT cached (only full results are).

Bounded by an LRU byte budget; `gtpu_result_cache_{hits,misses,
evictions}_total` + bytes/entries gauges export through the global
registry, and the active trace span gets `result_cache=hit|miss|bypass`
attribution.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from collections import OrderedDict

from greptimedb_tpu.sql import ast as A
from greptimedb_tpu.telemetry.metrics import global_registry

from greptimedb_tpu import concurrency

_HITS = global_registry.counter(
    "gtpu_result_cache_hits_total",
    "frontend result-set cache hits (served without datanode/device)",
)
_MISSES = global_registry.counter(
    "gtpu_result_cache_misses_total",
    "frontend result-set cache misses",
)
_EVICTIONS = global_registry.counter(
    "gtpu_result_cache_evictions_total",
    "frontend result-set cache entries evicted (budget or staleness)",
)
_BYTES = global_registry.gauge(
    "gtpu_result_cache_bytes",
    "bytes held by the frontend result-set cache",
)
_ENTRIES = global_registry.gauge(
    "gtpu_result_cache_entries",
    "entries held by the frontend result-set cache",
)

_DEFAULT_BYTES = 256 * 1024 * 1024

# functions whose value depends on evaluation time/randomness: caching
# the result would freeze them (the planner folds WHERE-clause time
# bounds to concrete ms before the plan reaches us, so those are safe)
_VOLATILE_FUNCS = frozenset({
    "now", "current_timestamp", "current_time", "current_date",
    "localtime", "localtimestamp", "random", "rand", "uuid",
})


def _expr_has_volatile(e) -> bool:
    if isinstance(e, A.FuncCall):
        if e.name.lower() in _VOLATILE_FUNCS:
            return True
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, A.Expr) and _expr_has_volatile(v):
                return True
            if isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, A.Expr) and _expr_has_volatile(x):
                        return True
                    if (isinstance(x, tuple) and x
                            and isinstance(x[0], A.Expr)
                            and _expr_has_volatile(x[0])):
                        return True
    return False


def plan_volatile(plan) -> bool:
    """True when any expression in the plan is evaluation-time
    dependent (now()/random()/... anywhere in items, keys, aggs,
    range args, having, order_by or the residual filter)."""
    exprs = [e for e, _ in (plan.items or [])]
    exprs += [e for e, _ in (plan.post_items or [])]
    exprs += [k.expr for k in plan.keys]
    exprs += [a.arg for a in plan.aggs if a.arg is not None]
    exprs += [r.arg for r in plan.range_items if r.arg is not None]
    if plan.having is not None:
        exprs.append(plan.having)
    exprs += [o.expr for o in plan.order_by]
    if plan.scan.residual is not None:
        exprs.append(plan.scan.residual)
    return any(_expr_has_volatile(e) for e in exprs)


def plan_fingerprint(plan) -> str:
    """Deterministic identity of a planned statement. The dataclass
    repr is deterministic; full matcher regex patterns are appended
    because re.Pattern repr truncates long patterns (same scheme as
    dist/dist_query._plan_fingerprint)."""
    extra = "".join(
        str(getattr(m[2], "pattern", ""))
        for m in plan.scan.matchers or []
    )
    return repr(plan) + "\x00" + extra


def ts_output_name(plan, table) -> str | None:
    """Name of the time-index output column a `since` delta filter
    applies to, or None when the projection does not carry it."""
    if plan.kind == "range":
        for e, nm in plan.post_items:
            if isinstance(e, A.Column) and e.name == "__ts":
                return nm
        return None
    if plan.kind == "plain" and table is not None:
        ts = table.ts_name
        for e, nm in plan.items:
            if isinstance(e, A.Column) and e.name == ts:
                return nm
    return None


def filter_since(res, ts_name: str | None, since_ms: int):
    """Rows of `res` whose `ts_name` column is strictly greater than
    the watermark; full result when the projection lacks the column
    (the client cannot be delta-served without a time column)."""
    from greptimedb_tpu.query.executor import QueryResult

    if ts_name is None or ts_name not in res.names:
        return res
    col = res.column(ts_name)
    keep = np.asarray(col.values, np.int64) > int(since_ms)
    if keep.all():
        return res
    from greptimedb_tpu.query.executor import _slice_result

    idx = np.flatnonzero(keep)
    out = QueryResult(res.names, _slice_result(res.cols, idx), res.types)
    out.partial = getattr(res, "partial", False)
    if out.partial:
        out.missing_regions = getattr(res, "missing_regions", 0)
    return out


def _result_nbytes(res) -> int:
    n = 0
    for c in res.cols:
        v = c.values
        if v.dtype == object:
            n += len(v) * 64  # strings: conservative estimate
        else:
            n += int(v.nbytes)
        if c.validity is not None:
            n += int(c.validity.nbytes)
    return n


class _Entry:
    __slots__ = ("versions", "result", "ts_name", "exec_path", "nbytes")

    def __init__(self, versions, result, ts_name, exec_path, nbytes):
        self.versions = versions
        self.result = result
        self.ts_name = ts_name
        self.exec_path = exec_path
        self.nbytes = nbytes


class ResultCache:
    """LRU byte-budgeted cache of finished QueryResults, physical-
    version validated."""

    def __init__(self, max_bytes: int = _DEFAULT_BYTES,
                 enabled: bool = False,
                 validate_interval_ms: float = 0.0):
        self.max_bytes = int(max_bytes)
        self.enabled = bool(enabled)
        # > 0: a version snapshot this fresh (monotonic ms) serves
        # without re-validating — for REMOTE tables this is the "serve
        # without touching the datanode" staleness bound; 0 = exact
        # validation every poll (free locally, one cheap data_versions
        # action per datanode for dist tables)
        self.validate_interval_ms = float(validate_interval_ms)
        self._lock = concurrency.Lock()
        self._entries: OrderedDict[tuple, _Entry] = OrderedDict()
        self._bytes = 0
        # table key -> (monotonic_s, versions) snapshot for the
        # validate-interval path
        self._version_snap: dict = {}
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.register_pool(
            "result_cache", "host", self, stats=ResultCache._mem_stats
        )

    @classmethod
    def from_options(cls, options: dict | None) -> "ResultCache":
        o = options or {}
        return cls(
            max_bytes=int(o.get("bytes", _DEFAULT_BYTES)),
            enabled=bool(o.get("enable", False)),
            validate_interval_ms=float(
                o.get("validate_interval_ms", 0.0)
            ),
        )

    # ------------------------------------------------------------------
    def eligible(self, plan, table) -> bool:
        if not self.enabled or table is None:
            return False
        if plan.kind not in ("plain", "aggregate", "range"):
            return False
        if table.info.options.get("ttl"):
            return False  # wall-clock-derived scan window
        if getattr(plan.scan, "volatile_bounds", False):
            # a now()-folded bound re-fingerprints every call: caching
            # would insert one dead never-hit entry per poll
            return False
        return not plan_volatile(plan)

    def current_versions(self, table):
        """The table's physical version set, memoized for
        validate_interval_ms when configured."""
        import time as _time

        tkey = (table.info.database, table.info.table_id)
        if self.validate_interval_ms > 0:
            snap = self._version_snap.get(tkey)
            now = _time.monotonic()
            if (snap is not None
                    and (now - snap[0]) * 1000.0
                    <= self.validate_interval_ms):
                return snap[1]
            versions = table.physical_version()
            self._version_snap[tkey] = (now, versions)
            return versions
        return table.physical_version()

    def get(self, db: str, table, fingerprint: str, versions):
        key = (db, table.info.table_id, fingerprint)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                _MISSES.inc()
                self._misses += 1
                return None
            if e.versions != versions:
                self._drop_locked(key, e)
                _MISSES.inc()
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            _HITS.inc()
            self._hits += 1
            return e

    def put(self, db: str, table, fingerprint: str, versions, result,
            ts_name: str | None, exec_path: str):
        nbytes = _result_nbytes(result)
        if nbytes > self.max_bytes:
            return
        key = (db, table.info.table_id, fingerprint)
        entry = _Entry(versions, result, ts_name, exec_path, nbytes)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                k = next(iter(self._entries))
                self._drop_locked(k, self._entries[k])
            self._publish_locked()

    # ------------------------------------------------------------------
    def purge_table(self, db: str, table_id: int) -> None:
        """Drop every entry for the table (drop/close: a recreated
        table can reuse the id and coincidentally match versions)."""
        with self._lock:
            stale = [k for k in self._entries
                     if k[0] == db and k[1] == table_id]
            for k in stale:
                self._drop_locked(k, self._entries[k])
            self._version_snap.pop((db, table_id), None)
            if stale:
                self._publish_locked()

    def clear(self) -> None:
        with self._lock:
            for k in list(self._entries):
                self._drop_locked(k, self._entries[k])
            self._version_snap.clear()
            self._publish_locked()

    def _drop_locked(self, key, entry) -> None:
        self._entries.pop(key, None)
        self._bytes -= entry.nbytes
        _EVICTIONS.inc()
        self._evictions += 1

    def set_max_bytes(self, v: int) -> None:
        """Runtime budget update (autotune/knobs.py is the sanctioned
        caller — GT021). A shrink trims LRU entries immediately."""
        with self._lock:
            self.max_bytes = int(v)
            while self._bytes > self.max_bytes and self._entries:
                k = next(iter(self._entries))
                self._drop_locked(k, self._entries[k])
            self._publish_locked()

    def _mem_stats(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "entries": len(self._entries),
                "budget_bytes": self.max_bytes if self.enabled else 0,
                "hits": self._hits, "misses": self._misses,
                "evictions": self._evictions,
            }

    def _publish_locked(self) -> None:
        _BYTES.set(float(self._bytes))
        _ENTRIES.set(float(len(self._entries)))

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def byte_count(self) -> int:
        with self._lock:
            return self._bytes
