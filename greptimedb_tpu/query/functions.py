"""Scalar SQL function registry (host, vectorized numpy).

Capability counterpart of the reference's function registry
(/root/reference/src/common/function/src/scalars/ and DataFusion built-ins):
date/time functions, math, string helpers, conditionals. Aggregate functions
are NOT here — the executor lowers those to device kernels (ops/segment.py).
"""

from __future__ import annotations

import datetime as _dt
import time

import numpy as np

from greptimedb_tpu.errors import PlanError, UnsupportedError
from greptimedb_tpu.query.expr import Col, ColumnSource, eval_expr, parse_ts_literal
from greptimedb_tpu.sql import ast as A

_TRUNC_UNIT_MS = {
    "second": 1000,
    "minute": 60_000,
    "hour": 3_600_000,
    "day": 86_400_000,
    "week": 604_800_000,  # aligned to epoch Thursday; see below
}


def _interval_months(arg) -> int | None:
    """Total SIGNED months when `arg` is an INTERVAL literal made ONLY
    of year/month units (calendar arithmetic applies); None otherwise.
    The sign must survive: date_add(ts, INTERVAL '-1 month') subtracts."""
    import re as _re

    if not isinstance(arg, A.IntervalLit):
        return None
    raw = (arg.raw or "").lower()
    parts = _re.findall(r"(-?\s*\d+)\s*([a-z]+)", raw)
    if not parts:
        return None
    months = 0
    for num, unit in parts:
        num = num.replace(" ", "")
        if unit.startswith("year") or unit == "y":
            months += int(num) * 12
        elif unit.startswith("mon"):
            months += int(num)
        else:
            return None  # mixed/time units: fixed-span ms path
    return months


def _add_months(ts_ms: np.ndarray, months: int) -> np.ndarray:
    """Calendar month addition with end-of-month day clamping, fully
    vectorized over numpy datetime64."""
    dt = ts_ms.astype("datetime64[ms]")
    month0 = dt.astype("datetime64[M]")
    intra = (dt - month0).astype("timedelta64[ms]").astype(np.int64)
    new_month = month0 + np.timedelta64(months, "M")
    mlen_ms = ((new_month + np.timedelta64(1, "M")).astype("datetime64[ms]")
               - new_month.astype("datetime64[ms]")
               ).astype("timedelta64[ms]").astype(np.int64)
    day_ms = 86_400_000
    days = np.minimum(intra // day_ms, mlen_ms // day_ms - 1)
    tod = intra % day_ms
    return (new_month.astype("datetime64[ms]").astype(np.int64)
            + days * day_ms + tod)


def _ts_ms(c: Col) -> np.ndarray:
    if c.values.dtype == object:
        return np.asarray([parse_ts_literal(str(v)) for v in c.values], np.int64)
    return c.values.astype(np.int64)


def _date_trunc(unit: str, ts_ms: np.ndarray) -> np.ndarray:
    unit = unit.lower()
    if unit in _TRUNC_UNIT_MS:
        q = _TRUNC_UNIT_MS[unit]
        if unit == "week":
            # ISO weeks start Monday; epoch (1970-01-01) is a Thursday.
            off = 3 * 86_400_000
            return (ts_ms + off) // q * q - off
        return np.floor_divide(ts_ms, q) * q
    # calendar units via numpy datetime64
    dt64 = ts_ms.astype("datetime64[ms]")
    if unit == "month":
        return dt64.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    if unit == "quarter":
        months = dt64.astype("datetime64[M]").astype(np.int64)
        return (
            ((months // 3) * 3).astype("datetime64[M]")
            .astype("datetime64[ms]").astype(np.int64)
        )
    if unit == "year":
        return dt64.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
    raise UnsupportedError(f"date_trunc unit: {unit}")


def _extract_part(part: str, ts_ms: np.ndarray) -> np.ndarray:
    part = part.lower()
    dt64 = ts_ms.astype("datetime64[ms]")
    if part in ("epoch", "unix"):
        return ts_ms / 1000.0
    if part == "millisecond":
        return (ts_ms % 1000).astype(np.float64)
    if part == "second":
        return ((ts_ms // 1000) % 60).astype(np.float64)
    if part == "minute":
        return ((ts_ms // 60_000) % 60).astype(np.float64)
    if part == "hour":
        return ((ts_ms // 3_600_000) % 24).astype(np.float64)
    if part in ("day", "dom"):
        day = dt64.astype("datetime64[D]")
        month = dt64.astype("datetime64[M]")
        return (day - month.astype("datetime64[D]")).astype(np.int64) + 1.0
    if part in ("dow", "dayofweek"):
        days = dt64.astype("datetime64[D]").astype(np.int64)
        return ((days + 4) % 7).astype(np.float64)  # 0=Sunday
    if part in ("doy", "dayofyear"):
        day = dt64.astype("datetime64[D]")
        year = dt64.astype("datetime64[Y]")
        return (day - year.astype("datetime64[D]")).astype(np.int64) + 1.0
    if part == "week":
        days = dt64.astype("datetime64[D]").astype(np.int64)
        return (((days + 3) // 7)).astype(np.float64)
    if part == "month":
        month = dt64.astype("datetime64[M]").astype(np.int64)
        return (month % 12 + 1).astype(np.float64)
    if part == "quarter":
        month = dt64.astype("datetime64[M]").astype(np.int64)
        return ((month % 12) // 3 + 1).astype(np.float64)
    if part == "year":
        return (dt64.astype("datetime64[Y]").astype(np.int64) + 1970).astype(
            np.float64
        )
    raise UnsupportedError(f"extract part: {part}")


def _strftime(ts_ms: np.ndarray, fmt: str) -> np.ndarray:
    out = np.empty(len(ts_ms), dtype=object)
    for i, v in enumerate(ts_ms):
        out[i] = _dt.datetime.fromtimestamp(
            int(v) / 1000.0, _dt.timezone.utc
        ).strftime(fmt)
    return out


def _const_arg(e: A.Expr):
    from greptimedb_tpu.query.expr import eval_const

    return eval_const(e)


def eval_scalar_function(e: A.FuncCall, src: ColumnSource) -> Col:
    name = e.name
    n = src.num_rows
    args = e.args

    # ---- time ---------------------------------------------------------
    if name == "now" or name == "current_timestamp":
        return Col(np.full(n, int(time.time() * 1000), np.int64))
    if name == "date_trunc":
        if len(args) != 2:
            raise PlanError("date_trunc(unit, ts)")
        unit = str(_const_arg(args[0]))
        c = eval_expr(args[1], src)
        return Col(_date_trunc(unit, _ts_ms(c)), c.validity)
    if name == "date_bin":
        # date_bin(interval, ts[, origin])
        if len(args) < 2:
            raise PlanError("date_bin(interval, ts[, origin])")
        iv = _const_arg(args[0])
        iv_ms = int(iv) if not isinstance(iv, str) else _parse_interval(iv)
        c = eval_expr(args[1], src)
        origin = 0
        if len(args) > 2:
            o = _const_arg(args[2])
            origin = parse_ts_literal(str(o)) if isinstance(o, str) else int(o)
        ts = _ts_ms(c)
        return Col((ts - origin) // iv_ms * iv_ms + origin, c.validity)
    if name in ("to_unixtime", "to_unix_timestamp"):
        c = eval_expr(args[0], src)
        return Col(_ts_ms(c) // 1000, c.validity)
    if name == "from_unixtime":
        c = eval_expr(args[0], src)
        return Col(c.values.astype(np.int64) * 1000, c.validity)
    if name in ("to_timestamp", "to_timestamp_seconds"):
        # seconds (or a parsable string) -> timestamp ms (reference:
        # DataFusion to_timestamp family)
        c = eval_expr(args[0], src)
        if c.values.dtype == object:
            vals = _ts_ms(c)  # string parse yields ms directly
        else:
            vals = (c.values.astype(np.float64) * 1000).astype(np.int64)
        return Col(vals, c.validity)
    if name == "to_timestamp_millis":
        c = eval_expr(args[0], src)
        return Col(_ts_ms(c), c.validity)
    if name in ("date_add", "date_sub"):
        # date_add(ts, interval) / date_sub(ts, interval) — the
        # reference's scalars/date.rs pair. Pure month/year intervals
        # use CALENDAR arithmetic with end-of-month clamping (Jan 31 +
        # 1 month = Feb 29), not a fixed 30-day span.
        if len(args) != 2:
            raise PlanError(f"{name}(ts, interval)")
        from greptimedb_tpu.query.expr import _merge_validity

        c = eval_expr(args[0], src)
        sign = 1 if name == "date_add" else -1
        months = _interval_months(args[1])
        if months is not None:
            out = _add_months(_ts_ms(c), sign * months)
            return Col(out, c.validity)
        iv = eval_expr(args[1], src)
        delta = iv.values.astype(np.int64)
        return Col(_ts_ms(c) + sign * delta, _merge_validity(c, iv))
    if name == "date_format":
        c = eval_expr(args[0], src)
        fmt = str(_const_arg(args[1]))
        return Col(_strftime(_ts_ms(c), fmt), c.validity)
    if name == "extract" or name == "date_part":
        part = str(_const_arg(args[0]))
        c = eval_expr(args[1], src)
        return Col(_extract_part(part, _ts_ms(c)), c.validity)

    # ---- math ---------------------------------------------------------
    if name in ("abs", "floor", "ceil", "sqrt", "exp", "sin", "cos", "tan",
                "asin", "acos", "atan", "sinh", "cosh", "tanh", "sign"):
        c = eval_expr(args[0], src)
        fn = {"ceil": np.ceil, "sign": np.sign}.get(name) or getattr(np, name)
        with np.errstate(invalid="ignore", divide="ignore"):
            return Col(fn(c.values.astype(np.float64)), c.validity)
    if name == "ln":
        c = eval_expr(args[0], src)
        with np.errstate(invalid="ignore", divide="ignore"):
            return Col(np.log(c.values.astype(np.float64)), c.validity)
    if name == "log10" or name == "log":
        c = eval_expr(args[-1], src)
        with np.errstate(invalid="ignore", divide="ignore"):
            if name == "log" and len(args) == 2:
                base = float(_const_arg(args[0]))
                return Col(
                    np.log(c.values.astype(np.float64)) / np.log(base),
                    c.validity,
                )
            return Col(np.log10(c.values.astype(np.float64)), c.validity)
    if name == "log2":
        c = eval_expr(args[0], src)
        with np.errstate(invalid="ignore", divide="ignore"):
            return Col(np.log2(c.values.astype(np.float64)), c.validity)
    if name in ("pow", "power"):
        a = eval_expr(args[0], src)
        b = eval_expr(args[1], src)
        from greptimedb_tpu.query.expr import _merge_validity

        return Col(
            np.power(a.values.astype(np.float64), b.values.astype(np.float64)),
            _merge_validity(a, b),
        )
    if name == "round":
        c = eval_expr(args[0], src)
        digits = int(_const_arg(args[1])) if len(args) > 1 else 0
        return Col(np.round(c.values.astype(np.float64), digits), c.validity)
    if name in ("mod",):
        a = eval_expr(args[0], src)
        b = eval_expr(args[1], src)
        from greptimedb_tpu.query.expr import _merge_validity

        return Col(np.mod(a.values, np.where(b.values == 0, 1, b.values)),
                   _merge_validity(a, b))
    if name in ("greatest", "least"):
        cols = [eval_expr(a, src) for a in args]
        out = cols[0].values.astype(np.float64)
        for c in cols[1:]:
            out = (np.maximum if name == "greatest" else np.minimum)(
                out, c.values.astype(np.float64)
            )
        from greptimedb_tpu.query.expr import _merge_validity

        return Col(out, _merge_validity(*cols))
    if name == "clamp":
        c = eval_expr(args[0], src)
        lo = float(_const_arg(args[1]))
        hi = float(_const_arg(args[2]))
        return Col(np.clip(c.values.astype(np.float64), lo, hi), c.validity)

    # ---- conditionals / null handling ---------------------------------
    if name == "coalesce":
        cols = [eval_expr(a, src) for a in args]
        vals = cols[0].values.copy()
        valid = cols[0].valid_mask.copy()
        for c in cols[1:]:
            need = ~valid
            vals = np.where(need, c.values, vals)
            valid = valid | (need & c.valid_mask)
        return Col(vals, None if valid.all() else valid)
    if name == "nullif":
        a = eval_expr(args[0], src)
        b = eval_expr(args[1], src)
        eq = a.values == b.values
        valid = a.valid_mask & ~eq
        return Col(a.values, None if valid.all() else valid)
    if name == "ifnull" or name == "nvl":
        return eval_scalar_function(
            A.FuncCall("coalesce", args), src
        )
    if name == "isnull":
        c = eval_expr(args[0], src)
        return Col(~c.valid_mask)

    # ---- strings ------------------------------------------------------
    if name in ("upper", "lower"):
        c = eval_expr(args[0], src)
        fn = str.upper if name == "upper" else str.lower
        return Col(
            np.asarray([fn(str(v)) for v in c.values], object), c.validity
        )
    if name in ("length", "char_length", "character_length"):
        c = eval_expr(args[0], src)
        return Col(
            np.asarray([len(str(v)) for v in c.values], np.int64), c.validity
        )
    if name == "concat":
        cols = [eval_expr(a, src) for a in args]
        out = np.asarray(
            ["".join(str(c.values[i]) for c in cols) for i in range(n)],
            object,
        )
        return Col(out)
    if name == "substr" or name == "substring":
        c = eval_expr(args[0], src)
        start = int(_const_arg(args[1]))
        ln = int(_const_arg(args[2])) if len(args) > 2 else None
        s0 = max(start - 1, 0)
        out = np.asarray(
            [
                str(v)[s0: s0 + ln] if ln is not None else str(v)[s0:]
                for v in c.values
            ],
            object,
        )
        return Col(out, c.validity)
    if name == "trim":
        c = eval_expr(args[0], src)
        return Col(
            np.asarray([str(v).strip() for v in c.values], object), c.validity
        )
    if name == "regexp_match":
        import re as _re

        c = eval_expr(args[0], src)
        rx = _re.compile(str(_const_arg(args[1])))
        return Col(
            np.asarray([bool(rx.search(str(v))) for v in c.values], bool),
            c.validity,
        )
    if name == "matches":
        # fulltext query over a string column: terms with AND/OR/NOT and
        # "quoted phrases" (reference: common-function scalars matches +
        # the tantivy-backed fulltext index, src/index/src/fulltext_index/)
        from greptimedb_tpu.query.fulltext import eval_matches

        c = eval_expr(args[0], src)
        query = str(_const_arg(args[1]))
        return Col(eval_matches(c.values, query), c.validity)
    if name == "matches_term":
        # literal term occurrence with non-alphanumeric boundaries — the
        # term is NOT parsed as a query
        from greptimedb_tpu.query.fulltext import eval_matches_term

        c = eval_expr(args[0], src)
        term = str(_const_arg(args[1]))
        return Col(eval_matches_term(c.values, term), c.validity)
    if name == "starts_with":
        c = eval_expr(args[0], src)
        prefix = str(_const_arg(args[1]))
        return Col(
            np.asarray([str(v).startswith(prefix) for v in c.values], bool),
            c.validity,
        )
    if name in ("ends_with", "reverse", "repeat", "replace", "lpad",
                "rpad", "split_part", "left", "right", "strpos",
                "position", "instr"):
        return _string_fn(name, args, src)

    # ---- misc ---------------------------------------------------------
    if name == "arrow_typeof" or name == "typeof":
        c = eval_expr(args[0], src)
        return Col(np.full(n, str(c.values.dtype), object))
    if name == "version":
        from greptimedb_tpu.version import __version__

        return Col(np.full(n, f"greptimedb-tpu-{__version__}", object))
    if name == "database" or name == "current_schema":
        return Col(np.full(n, "public", object))

    # ---- json / geo / net families (query/functions_ext.py) -----------
    from greptimedb_tpu.query import functions_ext

    out = functions_ext.try_eval(name, args, src)
    if out is not None:
        return out

    raise UnsupportedError(f"unknown function: {name}")


def _string_fn(name: str, args, src) -> Col:
    """Per-row string transforms sharing one map/validity wrapper."""
    c = eval_expr(args[0], src)
    a = [_const_arg(x) for x in args[1:]]

    if name == "ends_with":
        fn, dtype = (lambda s: s.endswith(str(a[0]))), bool
    elif name == "reverse":
        fn, dtype = (lambda s: s[::-1]), object
    elif name == "repeat":
        k = max(int(a[0]), 0)
        fn, dtype = (lambda s: s * k), object
    elif name == "replace":
        frm, to = str(a[0]), str(a[1])
        fn, dtype = (lambda s: s.replace(frm, to)), object
    elif name in ("lpad", "rpad"):
        width = int(a[0])
        fill = (str(a[1]) if len(a) > 1 else " ") or " "

        def fn(s):  # noqa: E306
            if width <= 0:
                return ""          # postgres: non-positive width -> ''
            if len(s) >= width:
                return s[:width]
            add = (fill * (width - len(s)))[:width - len(s)]
            return add + s if name == "lpad" else s + add

        dtype = object
    elif name == "split_part":
        sep, idx = str(a[0]), int(a[1])   # 1-based, like postgres

        def fn(s):  # noqa: E306
            parts = s.split(sep)
            return parts[idx - 1] if 1 <= idx <= len(parts) else ""

        dtype = object
    elif name in ("left", "right"):
        k = int(a[0])
        if name == "left":
            fn = lambda s: s[:k]          # noqa: E731 - k<0 drops tail
        else:
            fn = lambda s: "" if k == 0 else s[-k:]  # noqa: E731
        dtype = object
    else:  # strpos / position / instr
        needle = str(a[0])
        fn, dtype = (lambda s: s.find(needle) + 1), np.int64

    return Col(
        np.asarray([fn(str(v)) for v in c.values], dtype), c.validity
    )


def _parse_interval(text: str) -> int:
    from greptimedb_tpu.sql.parser import parse_interval_ms

    return parse_interval_ms(text)


AGGREGATE_FUNCS = {
    "count", "sum", "min", "max", "avg", "mean", "median",
    "stddev", "stddev_pop", "stddev_samp", "var", "var_pop", "var_samp",
    "variance", "first_value", "last_value", "count_distinct",
    "approx_distinct", "percentile", "quantile", "approx_percentile_cont",
    "percentile_cont",
}


def contains_aggregate(e: A.Expr) -> bool:
    if isinstance(e, A.FuncCall):
        if e.over is not None:
            # a window function is not a GROUP BY aggregate; its args
            # are row-level values
            return False
        if e.name in AGGREGATE_FUNCS:
            return True
        return any(contains_aggregate(a) for a in e.args)
    if isinstance(e, A.RangeFunc):
        return True
    if isinstance(e, A.BinaryOp):
        return contains_aggregate(e.left) or contains_aggregate(e.right)
    if isinstance(e, A.UnaryOp):
        return contains_aggregate(e.operand)
    if isinstance(e, A.Cast):
        return contains_aggregate(e.operand)
    if isinstance(e, A.Between):
        return any(
            contains_aggregate(x) for x in (e.operand, e.low, e.high)
        )
    if isinstance(e, A.InList):
        return contains_aggregate(e.operand) or any(
            contains_aggregate(x) for x in e.items
        )
    if isinstance(e, A.IsNull):
        return contains_aggregate(e.operand)
    if isinstance(e, A.Case):
        parts = [e.operand, e.else_] if e.operand or e.else_ else []
        for c, t in e.whens:
            parts += [c, t]
        return any(contains_aggregate(p) for p in parts if p is not None)
    return False
