"""Persistent query sessions: device-resident RESULT buffers across polls.

The grid caches (query/device_range.py, promql/fast.py) already keep the
*input* state resident in HBM; this registry keeps the *folded result*
of a query shape resident too, so a repeated dashboard poll skips the
program dispatch round trip entirely — on a tunnel-attached chip each
dispatch is a full RTT — and the `since`-cursor delta path can slice the
resident buffer device-side before reading anything back
(query/readback.read_delta).

Keyed like the scan cache: (table key, version, query-shape key). The
version is the table's data/physical version captured when the buffer
was produced, so write/flush(*)/compact(*)/truncate/ALTER invalidate by
comparison ((*) via the grid-entry version the shape key embeds);
close/drop purge explicitly (catalog/manager.py hooks). Bounded by an
LRU byte budget over HBM ([sessions] hbm_bytes).

The `since` cursor contextvar also lives here: protocol layers bind the
client's watermark (HTTP `since` param / dist ticket `since_ms` field)
and the execution paths slice their result emission to rows whose time
index is strictly greater than it.
"""

from __future__ import annotations

import contextvars

from collections import OrderedDict

from greptimedb_tpu.telemetry.metrics import global_registry

from greptimedb_tpu import concurrency

_HITS = global_registry.counter(
    "gtpu_session_hits_total",
    "query-session registry hits (device result buffer reused)",
)
_MISSES = global_registry.counter(
    "gtpu_session_misses_total",
    "query-session registry misses",
)
_EVICTIONS = global_registry.counter(
    "gtpu_session_evictions_total",
    "query-session entries evicted (budget or staleness)",
)
_BYTES = global_registry.gauge(
    "gtpu_session_bytes",
    "HBM bytes pinned by the query-session registry",
)
_ENTRIES = global_registry.gauge(
    "gtpu_session_entries",
    "entries held by the query-session registry",
)

_DEFAULT_HBM_BYTES = 1 * 1024**3
# entry-count cap on top of the byte budget: result buffers can be
# tiny, and an unbounded stream of distinct query shapes must not pin
# thousands of small HBM buffers under the byte budget's radar
_MAX_ENTRIES = 512


class SessionRegistry:
    """LRU byte-budgeted registry of device result buffers."""

    def __init__(self, max_bytes: int = _DEFAULT_HBM_BYTES,
                 enabled: bool = True):
        self.max_bytes = int(max_bytes)
        self.enabled = bool(enabled)
        self._lock = concurrency.Lock()
        # key -> (version, buffer, nbytes); key[0] is the table key so
        # purge_table can drop a dropped table's buffers eagerly
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0
        # per-instance tallies for the memory accountant (the module
        # metric counters above are process-wide)
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.register_pool(
            "sessions", "device", self,
            stats=SessionRegistry._mem_stats,
            evict=SessionRegistry.evict_bytes,
            buffers=SessionRegistry._device_buffers,
        )

    # ------------------------------------------------------------------
    def get(self, tkey, shape_key, version):
        if not self.enabled:
            return None
        from greptimedb_tpu.telemetry import stmt_stats  # cycle-safe lazy

        key = (tkey, shape_key)
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                _MISSES.inc()
                self._misses += 1
            elif hit[0] != version:
                # the table's data changed since this buffer was folded:
                # it can never be served again — release the HBM now
                self._drop_locked(key)
                _MISSES.inc()
                self._misses += 1
                hit = None
            else:
                self._entries.move_to_end(key)
                _HITS.inc()
                self._hits += 1
        # per-statement attribution OUTSIDE the lock: the row for a
        # polled fingerprint shows its session hit rate
        stmt_stats.add("session_hits" if hit is not None
                       else "session_misses")
        return None if hit is None else hit[1]

    def put(self, tkey, shape_key, version, buf, nbytes: int):
        if not self.enabled or nbytes > self.max_bytes:
            return
        key = (tkey, shape_key)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
            self._entries[key] = (version, buf, int(nbytes))
            self._bytes += int(nbytes)
            while (self._bytes > self.max_bytes
                   or len(self._entries) > _MAX_ENTRIES) \
                    and len(self._entries) > 1:
                self._drop_locked(next(iter(self._entries)))
            self._publish_locked()
        # cross-pool pressure check OUTSIDE the lock: the global
        # watermark may evict from OTHER pools (and re-enter this one)
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.note_device_bytes()

    # ------------------------------------------------------------------
    def purge_table(self, tkey) -> None:
        """Drop every buffer for `tkey` (table drop/close: a recreated
        table could reuse the id and coincidentally match versions)."""
        with self._lock:
            stale = [k for k in self._entries if k[0] == tkey]
            for k in stale:
                self._drop_locked(k)
            if stale:
                self._publish_locked()

    def clear(self) -> None:
        with self._lock:
            for k in list(self._entries):
                self._drop_locked(k)
            self._publish_locked()

    def _drop_locked(self, key) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self._bytes -= ent[2]
            _EVICTIONS.inc()
            self._evictions += 1
        self._publish_locked()

    # ------------------------------------------------------------------
    # memory accountant surface (telemetry/memory.py)
    # ------------------------------------------------------------------
    def _mem_stats(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "entries": len(self._entries),
                "budget_bytes": self.max_bytes if self.enabled else 0,
                "max_entries": _MAX_ENTRIES,
                "hits": self._hits, "misses": self._misses,
                "evictions": self._evictions,
            }

    def evict_bytes(self, target: int) -> int:
        """Shed LRU entries until `target` bytes are freed (cross-pool
        pressure from the global [memory] device_budget_bytes
        watermark). Returns bytes actually freed."""
        freed = 0
        with self._lock:
            while freed < target and self._entries:
                key = next(iter(self._entries))
                freed += self._entries[key][2]
                self._drop_locked(key)
        return freed

    def set_max_bytes(self, v: int) -> None:
        """Runtime budget update (autotune/knobs.py is the sanctioned
        caller — GT021). A shrink trims LRU entries immediately so the
        freed HBM is available to whichever pool the reallocation
        controller is growing."""
        with self._lock:
            self.max_bytes = int(v)
            while self._bytes > self.max_bytes and self._entries:
                self._drop_locked(next(iter(self._entries)))
            self._publish_locked()

    def _device_buffers(self):
        with self._lock:
            return [
                (ent[1], f"sessions:{key[0]!r}")
                for key, ent in self._entries.items()
            ]

    def _publish_locked(self) -> None:
        _BYTES.set(float(self._bytes))
        _ENTRIES.set(float(len(self._entries)))

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def byte_count(self) -> int:
        with self._lock:
            return self._bytes


# process-wide registry (like promql/fast._CACHE): every QueryEngine in
# the process folds into one HBM budget
global_sessions = SessionRegistry()


def configure(options: dict | None) -> None:
    """Apply the [sessions] TOML section to this process."""
    o = options or {}
    global_sessions.enabled = bool(o.get("enable", True))
    global_sessions.max_bytes = int(
        o.get("hbm_bytes", _DEFAULT_HBM_BYTES)
    )
    if not global_sessions.enabled:
        global_sessions.clear()


# ----------------------------------------------------------------------
# `since` delta cursor: a client watermark in DATA time (epoch ms).
# Row-returning queries emit only rows whose time-index output is
# strictly greater than it — applied before ORDER BY / LIMIT, like an
# extra WHERE on the time index.
# ----------------------------------------------------------------------

_since_var: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_since_ms", default=None
)


def bind_since(since_ms):
    """Bind the delta cursor for this execution; returns a reset token.
    None binds explicitly (clearing any outer cursor)."""
    v = None if since_ms is None else int(since_ms)
    return _since_var.set(v)


def reset_since(token) -> None:
    _since_var.reset(token)


def current_since():
    """Active `since` watermark in epoch ms, or None."""
    return _since_var.get()
