"""Correlated-subquery decorrelation.

Counterpart of DataFusion's decorrelation passes the reference plans
through (/root/reference/src/query/src/planner.rs ->
datafusion/optimizer decorrelate_predicate_subquery / scalar_subquery):
correlated EXISTS / IN / scalar subqueries whose correlation is a
conjunction of equalities `inner_expr = outer_expr` rewrite into ONE
inner evaluation grouped by the correlation keys plus a hash lookup
over the outer rows — a semi/anti/left join in effect. The inner side
(scans, aggregation) runs fully columnar; only the final per-row key
lookup is host python, O(outer rows).

Shape restrictions (anything else raises UnsupportedError, matching the
fallback behavior of the reference's optimizer):
- correlation appears only in the inner WHERE, as top-level equality
  conjuncts with one pure-inner side and one pure-outer side;
- the inner FROM is a table / CTE / view the scope analyzer can see
  through; nested subqueries inside the inner query are opaque.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from greptimedb_tpu.errors import (
    ExecutionError,
    PlanError,
    UnsupportedError,
)
from greptimedb_tpu.query.expr import Col, eval_expr
from greptimedb_tpu.query.planner import split_conjuncts
from greptimedb_tpu.sql import ast as A

_NULL = object()


def collect_columns(e, out: set[str] | None = None) -> set[str]:
    """Qualifier-AWARE column collector: `o.cust` stays `o.cust`, never
    collapsing to bare `cust` (the shared collect_columns drops table
    qualifiers, which made outer-qualified refs look like inner
    columns and silently un-correlated self-join subqueries)."""
    if out is None:
        out = set()
    if isinstance(e, A.Column):
        out.add(f"{e.table}.{e.name}" if e.table else e.name)
        return out
    for child in getattr(e, "__dict__", {}).values():
        if isinstance(child, A.Expr):
            collect_columns(child, out)
        elif isinstance(child, (list, tuple)):
            for x in child:
                if isinstance(x, A.Expr):
                    collect_columns(x, out)
                elif isinstance(x, (list, tuple)):
                    for y in x:
                        if isinstance(y, A.Expr):
                            collect_columns(y, out)
    return out


@dataclass
class CorrSpec:
    kind: str                      # exists | in | scalar
    key: str                       # placeholder column name (__corr_i)
    inner: A.Select                # rewritten inner (keys projected)
    outer_exprs: list              # per-key outer-side expressions
    negated: bool = False
    operand: A.Expr | None = None  # IN operand (outer expression)
    # scalar aggregates: the inner evaluated over ZERO rows — SQL's
    # value for outer rows with no matching inner rows (count()->0,
    # sum()->NULL, count(*)+1 -> 1)
    empty_default: A.Select | None = None


# ---------------------------------------------------------------------------
# scope analysis
# ---------------------------------------------------------------------------


def _source_columns(inst, src, ctx, env) -> set[str] | None:
    """Names visible from a FROM source: bare + `qual.name`. None when
    the source is opaque to static analysis."""
    if isinstance(src, A.TableName):
        qual = src.alias or src.name.rsplit(".", 1)[-1]
        if src.name in env:
            names = list(env[src.name].names)
        else:
            db, name = inst._resolve(src.name, ctx)
            if inst.catalog.maybe_view(db, name) is not None:
                return None  # view text: opaque here, treated whole
            table = inst.catalog.maybe_table(db, name)
            if table is None:
                return None
            names = list(table.schema.column_names)
        out = set(names)
        out.update(f"{qual}.{n}" for n in names)
        out.add(qual)  # qualifier itself, for `qual.*`-ish references
        return out
    if isinstance(src, A.JoinSource):
        left = _source_columns(inst, src.left, ctx, env)
        right = _source_columns(inst, src.right, ctx, env)
        if left is None or right is None:
            return None
        return left | right
    return None  # SubquerySource etc.: opaque


def _free_columns(inst, q: A.Select, ctx, env) -> set[str] | None:
    """Columns referenced by q that its own FROM does not provide.
    None = cannot analyze (treat as uncorrelated / opaque)."""
    src = q.source
    if src is None and q.from_table:
        src = A.TableName(q.from_table)
    if src is None:
        return set()
    scope = _source_columns(inst, src, ctx, env)
    if scope is None:
        return None
    refs: set[str] = set()
    for e in _all_exprs(q):
        if _contains_subquery(e):
            return None  # nested subqueries: opaque
        collect_columns(e, refs)
    return {r for r in refs if r not in scope}


def _all_exprs(q: A.Select):
    for it in q.items:
        yield it.expr
    if q.where is not None:
        yield q.where
    for g in q.group_by:
        yield g
    if q.having is not None:
        yield q.having
    for o in q.order_by:
        yield o.expr


def _contains_subquery(e) -> bool:
    from greptimedb_tpu.query.relational import _has_subquery

    return _has_subquery(e)


# ---------------------------------------------------------------------------
# decorrelation
# ---------------------------------------------------------------------------


def try_decorrelate(inst, e, ctx, env, key: str) -> CorrSpec | None:
    """None = the subquery is uncorrelated (caller materializes it).
    Raises UnsupportedError for correlated-but-undecorrelatable."""
    q = e.query
    free = _free_columns(inst, q, ctx, env)
    if not free:  # empty set OR None (opaque): treat as uncorrelated
        return None
    if (q.group_by or q.having is not None or q.order_by
            or q.limit is not None or q.offset is not None or q.distinct):
        # the decorrelated inner re-projects to correlation keys; any of
        # these clauses would be silently dropped (wrong results), so
        # refuse loudly
        raise UnsupportedError(
            "correlated subqueries with GROUP BY / HAVING / ORDER BY / "
            "LIMIT / DISTINCT are not supported"
        )

    scope = _source_columns(
        inst,
        q.source if q.source is not None else A.TableName(q.from_table),
        ctx, env,
    ) or set()

    def side(expr) -> str:
        cols = collect_columns(expr)
        if not cols:
            return "const"
        if cols <= scope:
            return "inner"
        if not (cols & scope):
            return "outer"
        return "mixed"

    # split the inner WHERE into correlation equalities + residual
    pairs: list[tuple[A.Expr, A.Expr]] = []   # (inner_expr, outer_expr)
    residual: list[A.Expr] = []
    for c in split_conjuncts(q.where):
        cols = collect_columns(c)
        if not (cols & free):
            residual.append(c)
            continue
        if not (isinstance(c, A.BinaryOp) and c.op == "="):
            raise UnsupportedError(
                "correlated subqueries support only equality "
                f"correlation (got: {type(c).__name__})"
            )
        ls, rs = side(c.left), side(c.right)
        if ls == "inner" and rs == "outer":
            pairs.append((c.left, c.right))
        elif ls == "outer" and rs == "inner":
            pairs.append((c.right, c.left))
        else:
            raise UnsupportedError(
                "correlated equality must compare a pure-inner "
                "expression with a pure-outer expression"
            )
    # correlation anywhere else (items/group/having) is unsupported
    for expr in _all_exprs(q):
        if expr is q.where:
            continue
        if collect_columns(expr) & free:
            raise UnsupportedError(
                "correlated references outside the inner WHERE are "
                "not supported"
            )
    if not pairs:
        raise UnsupportedError(
            "correlated subquery has no usable correlation equality"
        )

    where = None
    for c in residual:
        where = c if where is None else A.BinaryOp("and", where, c)

    key_items = [
        A.SelectItem(inner_e, f"__ck{i}")
        for i, (inner_e, _) in enumerate(pairs)
    ]
    outer_exprs = [outer_e for _, outer_e in pairs]

    if isinstance(e, A.Exists):
        inner = A.Select(
            items=key_items, from_table=q.from_table, where=where,
            group_by=[], having=None, order_by=[], limit=None,
            offset=None, range_clause=None, distinct=True,
            source=q.source, ctes=list(getattr(q, "ctes", [])),
        )
        return CorrSpec("exists", key, inner, outer_exprs,
                        negated=e.negated)

    if isinstance(e, A.InSubquery):
        if len(q.items) != 1:
            raise PlanError("IN subquery must return one column")
        inner = A.Select(
            items=[A.SelectItem(q.items[0].expr, "__cv")] + key_items,
            from_table=q.from_table, where=where,
            group_by=[], having=None, order_by=[], limit=None,
            offset=None, range_clause=None, distinct=True,
            source=q.source, ctes=list(getattr(q, "ctes", [])),
        )
        return CorrSpec("in", key, inner, outer_exprs,
                        negated=e.negated, operand=e.operand)

    # scalar subquery
    if len(q.items) != 1:
        raise PlanError("scalar subquery must return one column")
    item = q.items[0].expr
    from greptimedb_tpu.query.functions import contains_aggregate

    is_agg = contains_aggregate(item)
    inner = A.Select(
        items=[A.SelectItem(item, "__cv")] + key_items,
        from_table=q.from_table, where=where,
        group_by=[k.expr for k in key_items] if is_agg else [],
        having=None, order_by=[], limit=None, offset=None,
        range_clause=None, distinct=False,
        source=q.source, ctes=list(getattr(q, "ctes", [])),
    )
    empty_default = None
    if is_agg:
        # SQL's zero-matching-rows value = the aggregate over an empty
        # input (count()->0, sum()->NULL, count(*)+1 -> 1): evaluate the
        # ORIGINAL item once with WHERE false
        empty_default = A.Select(
            items=[A.SelectItem(item, "__cv")],
            from_table=q.from_table, where=A.Literal(False),
            group_by=[], having=None, order_by=[], limit=None,
            offset=None, range_clause=None, distinct=False,
            source=q.source, ctes=list(getattr(q, "ctes", [])),
        )
    return CorrSpec("scalar", key, inner, outer_exprs,
                    empty_default=empty_default)


# ---------------------------------------------------------------------------
# vectorized lookup over the outer frame
# ---------------------------------------------------------------------------


def _norm(v):
    return v.item() if hasattr(v, "item") else v


def _key_arrays(qr, start: int, n_keys: int):
    """Per-row key tuples from result columns [start, start+n_keys)."""
    cols = qr.cols[start:start + n_keys]
    keys = []
    for i in range(qr.num_rows):
        parts = []
        dead = False
        for c in cols:
            if not bool(c.valid_mask[i]):
                dead = True   # NULL keys never equal anything
                break
            parts.append(_norm(c.values[i]))
        keys.append(None if dead else tuple(parts))
    return keys


def _outer_keys(spec: CorrSpec, fsrc, qualify) -> list:
    cols = [eval_expr(qualify(e), fsrc) for e in spec.outer_exprs]
    n = fsrc.num_rows
    out = []
    for i in range(n):
        parts = []
        dead = False
        for c in cols:
            if not bool(c.valid_mask[i]):
                dead = True
                break
            parts.append(_norm(c.values[i]))
        out.append(None if dead else tuple(parts))
    return out


def compute_corr_col(inst, spec: CorrSpec, fsrc, ctx, env,
                     qualify) -> Col:
    """Evaluate the decorrelated inner ONCE, then map outer rows."""
    from greptimedb_tpu.query import relational

    qr = relational.execute(inst, spec.inner, ctx, env)
    n = fsrc.num_rows
    okeys = _outer_keys(spec, fsrc, qualify)

    if spec.kind == "exists":
        present = {k for k in _key_arrays(qr, 0, len(spec.outer_exprs))
                   if k is not None}
        vals = np.asarray([
            (k in present) != spec.negated if k is not None
            else spec.negated
            for k in okeys
        ], bool)
        return Col(vals)

    if spec.kind == "in":
        ikeys = _key_arrays(qr, 1, len(spec.outer_exprs))
        vcol = qr.cols[0]
        by_key: dict = {}
        for i, k in enumerate(ikeys):
            if k is None:
                continue
            st = by_key.setdefault(k, [set(), False])
            if bool(vcol.valid_mask[i]):
                st[0].add(_norm(vcol.values[i]))
            else:
                st[1] = True  # inner NULL: three-valued logic below
        op = eval_expr(qualify(spec.operand), fsrc)
        vals = np.zeros(n, bool)
        valid = np.ones(n, bool)
        for i in range(n):
            k = okeys[i]
            st = by_key.get(k) if k is not None else None
            if st is None:               # no inner rows for this key
                vals[i] = spec.negated   # IN -> false, NOT IN -> true
                continue
            if not bool(op.valid_mask[i]):
                valid[i] = False         # NULL operand -> NULL
                continue
            v = _norm(op.values[i])
            if v in st[0]:
                vals[i] = not spec.negated
            elif st[1]:
                valid[i] = False         # maybe-match via inner NULL
            else:
                vals[i] = spec.negated
        return Col(vals, None if valid.all() else valid)

    # scalar
    ikeys = _key_arrays(qr, 1, len(spec.outer_exprs))
    vcol = qr.cols[0]
    by_key = {}
    for i, k in enumerate(ikeys):
        if k is None:
            continue
        if k in by_key:
            raise ExecutionError(
                "scalar subquery returned more than one row for a "
                "correlation key"
            )
        by_key[k] = (
            _norm(vcol.values[i]) if bool(vcol.valid_mask[i]) else _NULL
        )
    default = _NULL
    if spec.empty_default is not None and any(
        k is None or k not in by_key for k in okeys
    ):
        # lazy: only outer rows with NO matching inner rows need the
        # empty-input aggregate value
        dq = relational.execute(inst, spec.empty_default, ctx, env)
        if dq.num_rows == 1:
            dc = dq.cols[0]
            default = (_norm(dc.values[0]) if bool(dc.valid_mask[0])
                       else _NULL)
    picked = [
        by_key.get(k, default) if k is not None else default
        for k in okeys
    ]
    valid = np.asarray([p is not _NULL for p in picked], bool)
    is_str = any(isinstance(p, str) for p in picked if p is not _NULL)
    fill = "" if is_str else 0
    clean = [fill if p is _NULL else p for p in picked]
    arr = (np.asarray(clean, object) if is_str else np.asarray(clean))
    return Col(arr, None if valid.all() else valid)
