"""Relational layer: JOINs, CTEs, set operations, subqueries, views.

Capability counterpart of the reference's DataFusion relational planning
(/root/reference/src/query/src/planner.rs DfLogicalPlanner,
datafusion.rs:64): JOIN/UNION/subquery plans over table scans. The TPU
division of labor mirrors the reference's CPU/storage split: scans and
aggregations — where the data is big — run through the existing
single-table device paths (query/device_range.py, reduce.py); this module
joins their much smaller columnar results host-side with vectorized
sort-merge joins over jointly-factorized key codes (no per-row Python).

Scope: uncorrelated subqueries; equi-joins (inner/left/right/full) with
arbitrary residual ON conditions; cross joins under a size guard; UNION /
INTERSECT / EXCEPT with [ALL]; views re-planned from stored SQL text.
RANGE queries stay single-table.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.errors import (
    ColumnNotFoundError,
    ExecutionError,
    PlanError,
    UnsupportedError,
)
from greptimedb_tpu.query.executor import (
    Col,
    QueryResult,
    _distinct_indices,
    _slice_result,
    _sort_indices,
)
from greptimedb_tpu.query.expr import ColumnSource, collect_columns, eval_expr
from greptimedb_tpu.query.planner import plan_select, split_conjuncts
from greptimedb_tpu.sql import ast as A

_CROSS_JOIN_GUARD = 25_000_000  # max rows a cross join may materialize


# ----------------------------------------------------------------------
# frames
# ----------------------------------------------------------------------

class Frame:
    """Columnar intermediate with qualified names: (qualifier, name) per
    column. Qualifiers are FROM aliases (or base table names)."""

    def __init__(self, quals: list[str | None], names: list[str],
                 cols: list[Col]):
        self.quals = quals
        self.names = names
        self.cols = cols
        self.num_rows = len(cols[0]) if cols else 0

    @staticmethod
    def from_result(qr: QueryResult, qual: str | None) -> "Frame":
        return Frame([qual] * len(qr.names), list(qr.names), list(qr.cols))

    def lookup(self, name: str) -> int:
        """Resolve `q.n` or bare `n`; bare names must be unambiguous."""
        if "." in name:
            q, n = name.rsplit(".", 1)
            hits = [
                i for i, (cq, cn) in enumerate(zip(self.quals, self.names))
                if cn == n and cq == q
            ]
        else:
            hits = [i for i, cn in enumerate(self.names) if cn == name]
        if len(hits) == 1:
            return hits[0]
        if len(hits) > 1:
            raise PlanError(f"ambiguous column reference: {name}")
        raise ColumnNotFoundError(f"column not found: {name}")

    def has(self, name: str) -> bool:
        try:
            self.lookup(name)
            return True
        except (ColumnNotFoundError, PlanError):
            return False

    def take(self, idx: np.ndarray, valid: np.ndarray | None = None,
             cols: list[int] | None = None) -> list[Col]:
        """Gather rows; `valid=False` rows become NULL (outer-join fill)."""
        sel = range(len(self.cols)) if cols is None else cols
        idx = np.asarray(idx, np.int64)
        out = []
        for ci in sel:
            c = self.cols[ci]
            if len(c.values) == 0:
                if c.values.dtype == object:
                    vals = np.full(len(idx), None, object)
                else:
                    vals = np.zeros(len(idx), c.values.dtype)
                v = np.zeros(len(idx), bool)
            else:
                safe = np.clip(idx, 0, len(c.values) - 1)
                vals = c.values[safe]
                v = None if c.validity is None else c.validity[safe]
            if valid is not None:
                v = valid.copy() if v is None else (v & valid)
            out.append(Col(vals, v))
        return out


class FrameSource(ColumnSource):
    """ColumnSource over a Frame for the shared expression evaluator and
    the executor's plain/aggregate paths."""

    def __init__(self, frame: Frame):
        self.frame = frame
        self.num_rows = frame.num_rows

    def col(self, name: str) -> Col:
        return self.frame.cols[self.frame.lookup(name)]


# ----------------------------------------------------------------------
# routing
# ----------------------------------------------------------------------

def _has_subquery(e) -> bool:
    if isinstance(e, (A.ScalarSubquery, A.InSubquery, A.Exists)):
        return True
    for child in getattr(e, "__dict__", {}).values():
        if isinstance(child, A.Expr) and _has_subquery(child):
            return True
        if isinstance(child, list):
            for x in child:
                if isinstance(x, A.Expr) and _has_subquery(x):
                    return True
                if isinstance(x, tuple) and any(
                    isinstance(y, A.Expr) and _has_subquery(y) for y in x
                ):
                    return True
    return False


def _select_exprs(stmt: A.Select):
    for it in stmt.items:
        yield it.expr
    if stmt.where is not None:
        yield stmt.where
    yield from stmt.group_by
    if stmt.having is not None:
        yield stmt.having
    for o in stmt.order_by:
        yield o.expr


def needs_relational(inst, stmt, ctx) -> bool:
    """True when the statement can't run on the single-table fast path."""
    if isinstance(stmt, A.SetOp):
        return True
    if stmt.ctes:
        return True
    if isinstance(stmt.source, (A.JoinSource, A.SubquerySource)):
        return True
    if any(_has_subquery(e) for e in _select_exprs(stmt)):
        return True
    if stmt.from_table:
        db, name = inst._resolve(stmt.from_table, ctx)
        if inst.catalog.maybe_view(db, name) is not None:
            return True
    return False


# ----------------------------------------------------------------------
# execution
# ----------------------------------------------------------------------

def execute(inst, stmt, ctx, env: dict | None = None) -> QueryResult:
    env = dict(env or {})
    for name, q in getattr(stmt, "ctes", []):
        env[name] = execute(inst, q, ctx, env)
    if isinstance(stmt, A.SetOp):
        return _execute_setop(inst, stmt, ctx, env)
    return _execute_select(inst, stmt, ctx, env)


def _subselect(inst, q, ctx, env) -> QueryResult:
    """Evaluate a nested select/compound under the current CTE env."""
    if isinstance(q, A.SetOp) or getattr(q, "ctes", None) or env:
        return execute(inst, q, ctx, env)
    return execute(inst, q, ctx, {})


def _rewrite_subqueries(inst, e, ctx, env, corr: list | None = None):
    """Replace uncorrelated subquery expressions with literal values.
    Correlated ones (when `corr` is given) decorrelate into placeholder
    columns computed over the outer frame (query/decorrelate.py)."""
    if corr is not None and isinstance(
        e, (A.ScalarSubquery, A.InSubquery, A.Exists)
    ):
        from greptimedb_tpu.query.decorrelate import try_decorrelate

        spec = try_decorrelate(inst, e, ctx, env,
                               key=f"__corr_{len(corr)}")
        if spec is not None:
            corr.append(spec)
            return A.Column(spec.key)
    if isinstance(e, A.ScalarSubquery):
        qr = _subselect(inst, e.query, ctx, env)
        if len(qr.names) != 1:
            raise PlanError("scalar subquery must return one column")
        if qr.num_rows == 0:
            return A.Literal(None)
        if qr.num_rows > 1:
            raise ExecutionError("scalar subquery returned more than one row")
        c = qr.cols[0]
        if not bool(c.valid_mask[0]):
            return A.Literal(None)
        v = c.values[0]
        return A.Literal(v.item() if hasattr(v, "item") else v)
    if isinstance(e, A.InSubquery):
        qr = _subselect(inst, e.query, ctx, env)
        if len(qr.names) != 1:
            raise PlanError("IN subquery must return one column")
        c = qr.cols[0]
        vals = c.values[c.valid_mask]
        uniq = np.unique(vals) if len(vals) else vals
        items = [
            A.Literal(v.item() if hasattr(v, "item") else v) for v in uniq
        ]
        return A.InList(
            _rewrite_subqueries(inst, e.operand, ctx, env, corr),
            items, e.negated
        )
    if isinstance(e, A.Exists):
        qr = _subselect(inst, e.query, ctx, env)
        return A.Literal((qr.num_rows == 0) if e.negated else (qr.num_rows > 0))
    rec = lambda x: _rewrite_subqueries(inst, x, ctx, env, corr)  # noqa: E731
    if isinstance(e, A.BinaryOp):
        return A.BinaryOp(e.op, rec(e.left), rec(e.right))
    if isinstance(e, A.UnaryOp):
        return A.UnaryOp(e.op, rec(e.operand))
    if isinstance(e, A.FuncCall):
        return A.FuncCall(e.name, [rec(a) for a in e.args], e.distinct,
                          e.order_by)
    if isinstance(e, A.RangeFunc):
        return A.RangeFunc(rec(e.func), e.range_ms, e.fill)
    if isinstance(e, A.Cast):
        return A.Cast(rec(e.operand), e.to)
    if isinstance(e, A.Between):
        return A.Between(rec(e.operand), rec(e.low), rec(e.high), e.negated)
    if isinstance(e, A.InList):
        return A.InList(rec(e.operand), [rec(i) for i in e.items], e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(rec(e.operand), e.negated)
    if isinstance(e, A.Case):
        return A.Case(
            rec(e.operand) if e.operand else None,
            [(rec(c), rec(t)) for c, t in e.whens],
            rec(e.else_) if e.else_ else None,
        )
    return e


def _map_columns(e, col_fn):
    """Rebuild an expression tree applying col_fn to every Column leaf."""
    if isinstance(e, A.Column):
        return col_fn(e)
    rec = lambda x: _map_columns(x, col_fn)  # noqa: E731
    if isinstance(e, A.BinaryOp):
        return A.BinaryOp(e.op, rec(e.left), rec(e.right))
    if isinstance(e, A.UnaryOp):
        return A.UnaryOp(e.op, rec(e.operand))
    if isinstance(e, A.FuncCall):
        return A.FuncCall(e.name, [rec(a) for a in e.args], e.distinct,
                          e.order_by)
    if isinstance(e, A.RangeFunc):
        return A.RangeFunc(rec(e.func), e.range_ms, e.fill)
    if isinstance(e, A.Cast):
        return A.Cast(rec(e.operand), e.to)
    if isinstance(e, A.Between):
        return A.Between(rec(e.operand), rec(e.low), rec(e.high), e.negated)
    if isinstance(e, A.InList):
        return A.InList(rec(e.operand), [rec(i) for i in e.items], e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(rec(e.operand), e.negated)
    if isinstance(e, A.Case):
        return A.Case(
            rec(e.operand) if e.operand else None,
            [(rec(c), rec(t)) for c, t in e.whens],
            rec(e.else_) if e.else_ else None,
        )
    return e


def _qualify(e):
    """Fold table qualifiers into flat `q.n` column names the Frame
    resolves (the shared evaluator only sees Column.name)."""
    return _map_columns(
        e,
        lambda c: A.Column(f"{c.table}.{c.name}") if c.table else c,
    )


def _execute_select(inst, stmt: A.Select, ctx, env) -> QueryResult:
    # 1. materialize uncorrelated subquery expressions; correlated ones
    # decorrelate into __corr_i placeholder columns (computed over the
    # outer frame in step 3b)
    corr: list = []
    rw = lambda e: _rewrite_subqueries(inst, e, ctx, env, corr)  # noqa: E731
    stmt = A.Select(
        items=[A.SelectItem(rw(it.expr), it.alias) for it in stmt.items],
        from_table=stmt.from_table,
        where=rw(stmt.where) if stmt.where is not None else None,
        group_by=[rw(g) for g in stmt.group_by],
        having=rw(stmt.having) if stmt.having is not None else None,
        order_by=[
            A.OrderItem(rw(o.expr), o.asc, o.nulls_first)
            for o in stmt.order_by
        ],
        limit=stmt.limit, offset=stmt.offset,
        range_clause=stmt.range_clause, distinct=stmt.distinct,
        source=stmt.source, ctes=[],
    )

    # 2. single base table (not a CTE/view)? delegate to the fast path —
    # unless correlated placeholders need the frame machinery
    src = stmt.source
    if src is None and corr and stmt.from_table:
        src = A.TableName(stmt.from_table)
    if src is None:
        return inst._select_single(stmt, ctx)
    if isinstance(src, A.TableName) and not corr:
        if src.name not in env:
            db, name = inst._resolve(src.name, ctx)
            if inst.catalog.maybe_view(db, name) is None:
                return inst._select_single(stmt, ctx)

    if stmt.range_clause is not None:
        raise UnsupportedError(
            "RANGE queries run on a single table; wrap the join in a CTE"
        )

    # 3. build the frame, pushing per-leaf WHERE conjuncts down
    conjuncts = [_qualify(c) for c in split_conjuncts(stmt.where)]
    frame, remaining = _eval_source(inst, src, ctx, env, conjuncts)
    fsrc = FrameSource(frame)

    # 3b. correlated placeholders: one inner evaluation each, then a
    # vectorized lookup keyed by the outer rows (semi/anti/left join)
    if corr:
        from greptimedb_tpu.query.decorrelate import compute_corr_col

        for spec in corr:
            col = compute_corr_col(inst, spec, fsrc, ctx, env, _qualify)
            frame = Frame(
                frame.quals + [None], frame.names + [spec.key],
                frame.cols + [col],
            )
            fsrc = FrameSource(frame)

    if remaining:
        cond = remaining[0]
        for c in remaining[1:]:
            cond = A.BinaryOp("and", cond, c)
        m = eval_expr(cond, fsrc)
        mask = m.values.astype(bool) & m.valid_mask
        if not mask.all():
            idx = np.nonzero(mask)[0]
            frame = Frame(frame.quals, frame.names, frame.take(idx))
            fsrc = FrameSource(frame)

    # 4. plan the remainder as a tableless select over the frame
    sel = A.Select(
        items=[A.SelectItem(_qualify(it.expr), it.alias)
               for it in stmt.items],
        from_table=None, where=None,
        group_by=[_qualify(g) for g in stmt.group_by],
        having=_qualify(stmt.having) if stmt.having is not None else None,
        order_by=[
            A.OrderItem(_qualify(o.expr), o.asc, o.nulls_first)
            for o in stmt.order_by
        ],
        limit=stmt.limit, offset=stmt.offset, distinct=stmt.distinct,
    )
    star_columns = [
        n if q is None else f"{q}.{n}"
        for q, n in zip(frame.quals, frame.names)
        if not n.startswith("__corr_")  # decorrelation internals
    ]
    plan = plan_select(sel, ts_name=None, tag_names=[],
                       all_columns=star_columns)

    # output shows bare names; qualifiers are resolution-only
    quals = {q for q in frame.quals if q}

    def bare(n: str) -> str:
        if "." in n and n.rsplit(".", 1)[0] in quals:
            return n.rsplit(".", 1)[-1]
        return n

    plan.items = [(e, bare(n)) for e, n in plan.items]
    plan.post_items = [(e, bare(n)) for e, n in plan.post_items]
    engine = inst.query_engine
    if plan.kind == "plain":
        return engine._execute_plain(plan, fsrc, None)
    if corr:
        # placeholder columns are ROW-level; post-aggregate expressions
        # (HAVING, select exprs over groups) evaluate at GROUP level
        from greptimedb_tpu.query.expr import collect_columns

        refs: set = set()
        for e, _ in plan.post_items:
            collect_columns(e, refs)
        if plan.having is not None:
            collect_columns(plan.having, refs)
        for o in plan.order_by:
            collect_columns(o.expr, refs)
        if any(r.startswith("__corr_") for r in refs):
            raise UnsupportedError(
                "correlated subqueries in HAVING or post-aggregate "
                "select expressions are not supported yet"
            )
    return engine._execute_aggregate(plan, fsrc, None)


# ----------------------------------------------------------------------
# FROM-source evaluation
# ----------------------------------------------------------------------

def _eval_source(inst, src, ctx, env, conjuncts):
    """Returns (frame, unconsumed conjuncts). Conjuncts whose columns all
    resolve against one base-table leaf are pushed into that leaf's scan
    (predicate pushdown through the join)."""
    if isinstance(src, A.TableName):
        return _frame_for_table(inst, src, ctx, env, conjuncts)
    if isinstance(src, A.SubquerySource):
        qr = _subselect(inst, src.query, ctx, env)
        return Frame.from_result(qr, src.alias), conjuncts
    if isinstance(src, A.JoinSource):
        from greptimedb_tpu.query import stats

        # WHERE pushdown must not cross into a null-supplying side: a
        # filter below the outer side would silently convert filtered-out
        # matches into NULL-padded rows
        push_left = src.kind not in ("right", "full")
        push_right = src.kind not in ("left", "full")
        if push_left:
            lf, conjuncts = _eval_source(inst, src.left, ctx, env, conjuncts)
        else:
            lf, _ = _eval_source(inst, src.left, ctx, env, [])
        if push_right:
            rf, conjuncts = _eval_source(inst, src.right, ctx, env, conjuncts)
        else:
            rf, _ = _eval_source(inst, src.right, ctx, env, [])
        with stats.timed("join_ms"):
            joined = _join(lf, rf, src)
        stats.add("join_rows", joined.num_rows)
        return joined, conjuncts
    raise PlanError(f"unsupported FROM source: {src!r}")


def _frame_for_table(inst, src: A.TableName, ctx, env, conjuncts):
    qual = src.alias or src.name.rsplit(".", 1)[-1]
    if src.name in env:
        return Frame.from_result(env[src.name], qual), conjuncts
    if (inst._is_information_schema(src.name, ctx)
            or inst._is_pg_catalog(src.name, ctx)):
        # system virtual tables join like any other relation (psql's
        # \dt runs pg_class JOIN pg_namespace)
        leaf = A.Select(items=[A.SelectItem(A.Star())],
                        from_table=src.name)
        return (
            Frame.from_result(inst._select_single(leaf, ctx), qual),
            conjuncts,
        )
    db, name = inst._resolve(src.name, ctx)
    view_sql = inst.catalog.maybe_view(db, name)
    if view_sql is not None:
        from greptimedb_tpu.sql.parser import parse_sql

        q = parse_sql(view_sql)[0]
        return Frame.from_result(_subselect(inst, q, ctx, env), qual), conjuncts
    table = inst.catalog.table(db, name)
    cols = set(table.schema.column_names)
    pushed, remaining = [], []
    for c in conjuncts:
        if _conjunct_binds(c, qual, cols):
            pushed.append(_strip_qual(c, qual))
        else:
            remaining.append(c)
    where = None
    for p in pushed:
        where = p if where is None else A.BinaryOp("and", where, p)
    leaf = A.Select(
        items=[A.SelectItem(A.Star())], from_table=src.name, where=where,
    )
    qr = inst._select_single(leaf, ctx)
    return Frame.from_result(qr, qual), remaining


def _conjunct_binds(c, qual: str, cols: set) -> bool:
    refs = collect_columns(c)
    if not refs:
        return False
    for r in refs:
        if "." in r:
            q, n = r.rsplit(".", 1)
            if q != qual or n not in cols:
                return False
        elif r not in cols:
            return False
    return True


def _strip_qual(e, qual: str):
    def strip(c: A.Column):
        if "." in c.name:
            q, n = c.name.rsplit(".", 1)
            if q == qual:
                return A.Column(n)
        return c

    return _map_columns(e, strip)


# ----------------------------------------------------------------------
# joins
# ----------------------------------------------------------------------

def _join(lf: Frame, rf: Frame, js: A.JoinSource) -> Frame:
    kind = js.kind
    pairs: list[tuple[A.Expr, A.Expr]] = []
    residual: list[A.Expr] = []
    drop_right: list[int] = []
    if js.using:
        for c in js.using:
            pairs.append((A.Column(c), A.Column(c)))
        # USING outputs the key once: hide the right copy
        drop_right = [rf.lookup(c) for c in js.using]
    elif js.on is not None:
        for c in split_conjuncts(_qualify(js.on)):
            pair = _equi_pair(c, lf, rf)
            if pair is not None:
                pairs.append(pair)
            else:
                residual.append(c)
    if kind == "cross":
        if lf.num_rows * rf.num_rows > _CROSS_JOIN_GUARD:
            raise ExecutionError(
                f"cross join would materialize "
                f"{lf.num_rows * rf.num_rows} rows"
            )
        li = np.repeat(np.arange(lf.num_rows), rf.num_rows)
        ri = np.tile(np.arange(rf.num_rows), lf.num_rows)
        return _emit_join(lf, rf, li, ri, None, None, drop_right)
    if not pairs:
        raise UnsupportedError(
            f"{kind.upper()} JOIN needs at least one equality condition "
            "(use CROSS JOIN for a cartesian product)"
        )

    lsrc, rsrc = FrameSource(lf), FrameSource(rf)
    lcodes = _key_codes([eval_expr(a, lsrc) for a, _ in pairs],
                        [eval_expr(b, rsrc) for _, b in pairs])
    lkeys, rkeys = lcodes

    order = np.argsort(rkeys, kind="stable")
    sorted_r = rkeys[order]
    start = np.searchsorted(sorted_r, lkeys, "left")
    end = np.searchsorted(sorted_r, lkeys, "right")
    counts = end - start
    li = np.repeat(np.arange(lf.num_rows), counts)
    total = int(counts.sum())
    base = np.repeat(start, counts)
    offsets = np.arange(total) - np.repeat(
        np.cumsum(counts) - counts, counts
    )
    ri = order[base + offsets]

    if residual and total:
        cand = _emit_join(lf, rf, li, ri, None, None, [])
        csrc = FrameSource(cand)
        cond = residual[0]
        for c in residual[1:]:
            cond = A.BinaryOp("and", cond, c)
        m = eval_expr(cond, csrc)
        keep = m.values.astype(bool) & m.valid_mask
        li, ri = li[keep], ri[keep]

    # extend matched pairs with the unmatched side(s); the other side's
    # columns read as NULL on those rows
    li0, ri0 = li, ri
    lextra = np.zeros(0, np.int64)
    rextra = np.zeros(0, np.int64)
    if kind in ("left", "full"):
        matched = np.zeros(lf.num_rows, bool)
        matched[li0] = True
        lextra = np.nonzero(~matched)[0]
    if kind in ("right", "full"):
        matched = np.zeros(rf.num_rows, bool)
        matched[ri0] = True
        rextra = np.nonzero(~matched)[0]
    l_valid = r_valid = None
    if len(lextra) or len(rextra):
        nm = len(li0)
        li = np.concatenate([li0, lextra, np.zeros(len(rextra), np.int64)])
        ri = np.concatenate([ri0, np.zeros(len(lextra), np.int64), rextra])
        l_valid = np.ones(len(li), bool)
        l_valid[nm + len(lextra):] = False
        r_valid = np.ones(len(ri), bool)
        r_valid[nm: nm + len(lextra)] = False
    out = _emit_join(lf, rf, li, ri, l_valid, r_valid, drop_right)
    if js.using and l_valid is not None:
        # USING outputs ONE key column, coalesced across sides (standard
        # SQL): right-only rows carry the right side's key value
        rtaken = rf.take(ri, r_valid, drop_right)
        for c, rcol in zip(js.using, rtaken):
            oi = out.lookup(c) if out.has(c) else None
            if oi is None:
                continue
            lcol = out.cols[oi]
            lv = lcol.valid_mask
            vals = np.where(lv, lcol.values, rcol.values)
            valid = lv | rcol.valid_mask
            out.cols[oi] = Col(
                vals, None if valid.all() else valid
            )
    return out


def _equi_pair(c, lf: Frame, rf: Frame):
    """(left_expr, right_expr) when `c` is an equality whose sides bind
    exclusively to opposite frames."""
    if not (isinstance(c, A.BinaryOp) and c.op == "="):
        return None

    def binds(frame, expr):
        refs = collect_columns(expr)
        return bool(refs) and all(frame.has(x) for x in refs)

    a, b = c.left, c.right
    a_l, a_r = binds(lf, a), binds(rf, a)
    b_l, b_r = binds(lf, b), binds(rf, b)
    if a_l and b_r and not a_r and not b_l:
        return (a, b)
    if b_l and a_r and not b_r and not a_l:
        return (b, a)
    return None


def _key_codes(lcols: list[Col], rcols: list[Col], *,
               null_equal: bool = False):
    """Jointly factorize join keys of both sides into int64 codes. JOIN
    semantics (default): NULL keys get a side-unique negative code so they
    never match. Set-operation semantics (null_equal): NULLs compare equal
    (IS NOT DISTINCT FROM)."""
    lparts, rparts = [], []
    cards = []
    for lc, rc in zip(lcols, rcols):
        lv, rv = lc.values, rc.values
        if lv.dtype == object or rv.dtype == object or \
                lv.dtype.kind in "US" or rv.dtype.kind in "US":
            both = np.concatenate([lv.astype(str), rv.astype(str)])
        else:
            dt = np.result_type(lv.dtype, rv.dtype)
            both = np.concatenate([lv.astype(dt), rv.astype(dt)])
        _, inv = np.unique(both, return_inverse=True)
        codes = inv.astype(np.int64) + 1  # 0 reserved for NULL
        lcode = codes[: len(lv)]
        rcode = codes[len(lv):]
        lcode = np.where(lc.valid_mask, lcode, 0)
        rcode = np.where(rc.valid_mask, rcode, 0)
        lparts.append(lcode)
        rparts.append(rcode)
        cards.append(int(codes.max(initial=0)) + 1)
    lkey = lparts[0]
    rkey = rparts[0]
    lnull = lparts[0] == 0
    rnull = rparts[0] == 0
    for lp, rp, card in zip(lparts[1:], rparts[1:], cards[1:]):
        lkey = lkey * card + lp
        rkey = rkey * card + rp
        lnull |= lp == 0
        rnull |= rp == 0
    if not null_equal:
        # NULL anywhere in the key never matches (per-side sentinels)
        lkey = np.where(lnull, np.int64(-1), lkey)
        rkey = np.where(rnull, np.int64(-2), rkey)
    return lkey, rkey


def _emit_join(lf: Frame, rf: Frame, li, ri, l_valid, r_valid,
               drop_right: list[int]) -> Frame:
    keep_r = [i for i in range(len(rf.cols)) if i not in set(drop_right)]
    quals = list(lf.quals) + [rf.quals[i] for i in keep_r]
    names = list(lf.names) + [rf.names[i] for i in keep_r]
    cols = lf.take(li, l_valid) + rf.take(ri, r_valid, keep_r)
    return Frame(quals, names, cols)


# ----------------------------------------------------------------------
# set operations
# ----------------------------------------------------------------------

def _execute_setop(inst, stmt: A.SetOp, ctx, env) -> QueryResult:
    left = _subselect(inst, stmt.left, ctx, env)
    right = _subselect(inst, stmt.right, ctx, env)
    if len(left.names) != len(right.names):
        raise PlanError(
            f"{stmt.op.upper()} requires equal column counts "
            f"({len(left.names)} vs {len(right.names)})"
        )
    names = list(left.names)
    if stmt.op == "union":
        cols = _concat_cols(left.cols, right.cols)
        if not stmt.all:
            cols = _slice_result(cols, _distinct_indices(cols))
    else:
        lkeys, rkeys = _key_codes(left.cols, right.cols, null_equal=True)
        if stmt.all:
            # bag semantics: INTERSECT ALL keeps min(count_l, count_r)
            # copies; EXCEPT ALL removes one left copy per right row
            occ = _occurrence_rank(lkeys)
            rvals, rcounts = np.unique(rkeys, return_counts=True)
            if len(rvals):
                pos = np.clip(
                    np.searchsorted(rvals, lkeys), 0, len(rvals) - 1
                )
                cnt = np.where(rvals[pos] == lkeys, rcounts[pos], 0)
            else:
                cnt = np.zeros(len(lkeys), np.int64)
            if stmt.op == "intersect":
                mask = occ < cnt
            else:  # except all
                mask = occ >= cnt
        else:
            if stmt.op == "intersect":
                mask = np.isin(lkeys, rkeys)
            else:  # except
                mask = ~np.isin(lkeys, rkeys)
        cols = _slice_result(left.cols, np.nonzero(mask)[0])
        if not stmt.all:
            cols = _slice_result(cols, _distinct_indices(cols))
    n = len(cols[0]) if cols else 0
    if stmt.order_by:
        from greptimedb_tpu.query.executor import DictSource

        out_src = DictSource(dict(zip(names, cols)), n)
        order_cols = [eval_expr(o.expr, out_src) for o in stmt.order_by]
        idx = _sort_indices(
            order_cols, [o.asc for o in stmt.order_by],
            [o.nulls_first for o in stmt.order_by],
        )
        cols = _slice_result(cols, idx)
    off = stmt.offset or 0
    if off or stmt.limit is not None:
        end = None if stmt.limit is None else off + stmt.limit
        cols = _slice_result(cols, slice(off, end))
    return QueryResult(names, cols)


def _occurrence_rank(keys: np.ndarray) -> np.ndarray:
    """rank[i] = how many earlier rows share keys[i] (0-based, original
    order)."""
    n = len(keys)
    if n == 0:
        return np.zeros(0, np.int64)
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    new_group = np.r_[True, sk[1:] != sk[:-1]]
    starts = np.nonzero(new_group)[0]
    sizes = np.diff(np.r_[starts, n])
    group_start = np.repeat(starts, sizes)
    ranks_sorted = np.arange(n) - group_start
    ranks = np.empty(n, np.int64)
    ranks[order] = ranks_sorted
    return ranks


def _concat_cols(a: list[Col], b: list[Col]) -> list[Col]:
    out = []
    for ca, cb in zip(a, b):
        va, vb = ca.values, cb.values
        if va.dtype == object or vb.dtype == object:
            vals = np.concatenate([va.astype(object), vb.astype(object)])
        else:
            dt = np.result_type(va.dtype, vb.dtype)
            vals = np.concatenate([va.astype(dt), vb.astype(dt)])
        if ca.validity is None and cb.validity is None:
            v = None
        else:
            v = np.concatenate([ca.valid_mask, cb.valid_mask])
        out.append(Col(vals, v))
    return out
