"""SELECT planner: AST -> executable plan.

Capability counterpart of the reference's logical planning + optimizer stack
(/root/reference/src/query/src/planner.rs, optimizer/, range_select/plan.rs):

- predicate split: WHERE conjuncts become (time-range bounds, tag matchers,
  residual filter) — the pushdown order of src/table/src/predicate.rs plus
  inverted-index-style series pruning (matchers run against the series
  registry before any row is materialized);
- aggregate extraction: aggregates inside select items are pulled out and
  replaced by references, so post-aggregation arithmetic is a host-side
  projection over the (small) aggregated result;
- RANGE select: per-item `agg(x) RANGE 'r'` windows over ALIGN steps with
  the reference's [t, t + range) window semantics (plan.rs:1068).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field

from greptimedb_tpu.errors import PlanError, UnsupportedError
from greptimedb_tpu.query.expr import (
    eval_const,
    format_expr,
    like_to_regex,
    parse_ts_literal,
)
from greptimedb_tpu.query.functions import AGGREGATE_FUNCS, contains_aggregate
from greptimedb_tpu.sql import ast as A


@dataclass
class ScanSpec:
    ts_min: int | None = None
    ts_max: int | None = None
    matchers: list = dc_field(default_factory=list)
    residual: A.Expr | None = None
    # (column, query) pairs from top-level matches() conjuncts — used
    # for flush-time fulltext row-group pruning; rows are STILL filtered
    # exactly by the residual, this only skips row groups that cannot
    # contain a match
    fulltext: list = dc_field(default_factory=list)
    # a ts bound (or RANGE ... TO now) was folded from a volatile
    # expression (now()/current_timestamp): the concrete value differs
    # on every plan, so caches keyed on the plan fingerprint must
    # bypass — each invocation would insert a dead never-hit entry
    volatile_bounds: bool = False


@dataclass
class AggSpec:
    key: str                      # internal column name "__agg_i"
    op: str                       # normalized aggregate op
    arg: A.Expr | None            # None == count(*)
    distinct: bool = False
    q: float | None = None        # quantile for percentile/median


@dataclass
class KeySpec:
    key: str                      # internal column name "__key_i"
    expr: A.Expr
    name: str                     # output display name


@dataclass
class RangeItemSpec:
    key: str
    op: str
    arg: A.Expr | None
    range_ms: int
    fill: str | None              # per-item fill override
    q: float | None = None        # quantile


@dataclass
class SelectPlan:
    kind: str                     # plain | aggregate | range
    table_name: str | None
    scan: ScanSpec
    items: list = dc_field(default_factory=list)        # (expr, name) plain
    keys: list[KeySpec] = dc_field(default_factory=list)
    aggs: list[AggSpec] = dc_field(default_factory=list)
    range_items: list[RangeItemSpec] = dc_field(default_factory=list)
    post_items: list = dc_field(default_factory=list)   # (expr, name)
    having: A.Expr | None = None
    order_by: list[A.OrderItem] = dc_field(default_factory=list)
    limit: int | None = None
    offset: int | None = None
    distinct: bool = False
    align_ms: int | None = None
    align_to: int = 0
    fill: str | None = None
    ts_out_name: str | None = None
    # explicit RANGE grid extent override (ms): set by the distributed
    # planner so every datanode builds the same fill grid (the global
    # scanned-ts extent, negotiated in dist/dist_query.py); None = derive
    # from the scanned data as usual
    grid_ts_min: int | None = None
    grid_ts_max: int | None = None

    def explain_lines(self) -> list[str]:
        out = [f"SelectPlan[{self.kind}] table={self.table_name}"]
        s = self.scan
        out.append(
            f"  Scan: ts=[{s.ts_min}, {s.ts_max}] "
            f"matchers={[(m[0], m[1]) for m in s.matchers]} "
            f"residual={format_expr(s.residual) if s.residual else None}"
        )
        if self.kind == "aggregate":
            out.append(
                "  Aggregate: keys="
                + str([format_expr(k.expr) for k in self.keys])
                + " aggs="
                + str([f"{a.op}({format_expr(a.arg) if a.arg else '*'})"
                       for a in self.aggs])
            )
        if self.kind == "range":
            out.append(
                f"  Range: align={self.align_ms}ms to={self.align_to} "
                f"by={[format_expr(k.expr) for k in self.keys]} "
                f"items={[f'{r.op} RANGE {r.range_ms}ms' for r in self.range_items]}"
            )
        if self.order_by:
            out.append(
                "  Sort: "
                + ", ".join(
                    f"{format_expr(o.expr)} {'ASC' if o.asc else 'DESC'}"
                    for o in self.order_by
                )
            )
        if self.limit is not None:
            out.append(f"  Limit: {self.limit} offset={self.offset or 0}")
        return out


# ----------------------------------------------------------------------
# replicate-vs-shard planning (multi-chip mesh execution)
# ----------------------------------------------------------------------
#
# Every device execution site with a mesh consults decide_mesh_execution
# before placing state: small grids replicate (single-device — launch +
# collective latency dominates), large decomposable reductions shard the
# series/row axis across the mesh and run the shard_map programs in
# parallel/dist.py / query/reduce.py / query/device_range.py /
# promql/fast.py. The decision (mode + reason + device count) lands in
# EXPLAIN ANALYZE and the gtpu_mesh_* metrics.

# aggregate shapes whose sharded fold is exact (blocked partials +
# psum/pmin/pmax/staged selection reproduce the unsharded result
# bit-for-bit; see parallel/mesh.FOLD_BLOCKS)
SHARDABLE_ROW_OPS = frozenset({
    "count", "sum", "mean", "min", "max", "first_value", "last_value",
})
# grid paths additionally shard var/stddev: their s/s2 folds ride the
# same 8-block exact combine (the row path's per-block on-device mean
# does not, so it stays replicated there)
SHARDABLE_GRID_OPS = frozenset(SHARDABLE_ROW_OPS | {
    "var_pop", "var_samp", "stddev_pop", "stddev_samp",
})


@dataclass(frozen=True)
class MeshDecision:
    mode: str            # "shard" | "replicate"
    reason: str          # why (threshold, op shape, mesh geometry, ...)
    devices: int = 1     # shard-axis devices the query will use
    # program-variant dimension for sharded executions: "pallas" when
    # the ring/merge kernels (parallel/kernels) carry the cross-shard
    # combine, "xla" for the gather_blocks collective path. Recorded
    # additively (kernel_label) — label() is unchanged so the existing
    # mode/reason surfaces stay stable.
    kernel: str = "xla"
    kernel_reason: str = ""

    @property
    def shard(self) -> bool:
        return self.mode == "shard"

    def label(self) -> str:
        return f"{self.mode}({self.reason})"

    def kernel_label(self) -> str:
        return f"{self.kernel}({self.kernel_reason})"


def decide_mesh_execution(
    mesh, *, kind: str, series: int | None = None, rows: int | None = None,
    ops=(), opts=None,
) -> MeshDecision:
    """Choose replicate vs shard for one query execution site.

    kind: "range" | "aggregate" | "promql" | "topk" | "window" — grid
    kinds gate on `series` (shard_min_series), row kinds on `rows`
    (shard_min_rows). `ops` are normalized aggregate op names; a single
    non-decomposable op forces replicate (the whole query runs as one
    program)."""
    from greptimedb_tpu.parallel.mesh import (
        FOLD_BLOCKS, MeshOptions, shard_count,
    )

    n_dev = shard_count(mesh)
    if mesh is None or n_dev <= 1:
        return MeshDecision("replicate", "no_mesh")
    opts = opts or MeshOptions()
    shardable = (SHARDABLE_GRID_OPS if kind in ("range", "promql")
                 else SHARDABLE_ROW_OPS)
    bad = [op for op in ops if op not in shardable]
    if bad:
        return MeshDecision("replicate", f"non_decomposable:{bad[0]}",
                            devices=n_dev)
    if FOLD_BLOCKS % n_dev != 0:
        # blocked exact folds need the shard count to divide the fixed
        # block count; other geometries run replicated (still correct)
        return MeshDecision("replicate", "mesh_indivisible", devices=n_dev)
    if kind in ("range", "promql"):
        if series is not None and series < max(opts.shard_min_series, 1):
            return MeshDecision("replicate", "small_grid", devices=n_dev)
    else:
        if rows is not None and rows < max(opts.shard_min_rows, 1):
            return MeshDecision("replicate", "small_rowset", devices=n_dev)
    kernel, kreason = decide_kernel(kind, series=series, rows=rows,
                                    opts=opts)
    return MeshDecision("shard", "large_grid" if kind in ("range", "promql")
                        else "large_rowset", devices=n_dev,
                        kernel=kernel, kernel_reason=kreason)


def decide_kernel(
    kind: str, *, series: int | None = None, rows: int | None = None,
    k: int | None = None, opts=None,
) -> tuple[str, str]:
    """Choose the program variant for one (already sharded, or — for
    "merge" — single-device compaction) execution site: "pallas" runs
    the parallel/kernels ring/merge kernels, "xla" the collective
    gather paths. Deterministic in its inputs, so execution sites may
    re-ask with the same arguments without a planner round-trip. `k`
    caps the topk merge kernel (O(k^2) ranks per hop)."""
    from greptimedb_tpu.parallel import kernels as pk
    from greptimedb_tpu.parallel.mesh import MeshOptions

    opts = opts or MeshOptions()
    mode = pk.kernel_mode(opts)
    if mode == "off":
        return "xla", "kernels_off"
    if mode == "auto" and not pk.native_available():
        return "xla", "no_tpu"
    if k is not None and k > max(getattr(opts, "pallas_max_k", 128), 1):
        return "xla", "k_too_large"
    if kind in ("range", "promql", "topk"):
        if series is not None and \
                series < max(getattr(opts, "pallas_min_series", 4096), 1):
            return "xla", "small_grid"
        return "pallas", ("ring_topk" if kind == "topk" or k is not None
                          else "ring_fold")
    if rows is not None and \
            rows < max(getattr(opts, "pallas_min_rows", 262144), 1):
        return "xla", "small_rowset"
    return "pallas", "fused_gather" if kind == "merge" else "ring_fold"


def record_kernel_decision(kind: str, kernel: str, reason: str) -> None:
    """Surface one kernel-variant choice in EXPLAIN ANALYZE + metrics.
    Rides the existing gtpu_mesh_queries_total counter under the
    "<kind>_kernel" site label so the established mode/reason series
    are untouched. stats.note no-ops outside a query context, so
    standalone sites (compaction merge) can call this unguarded."""
    from greptimedb_tpu.query import stats
    from greptimedb_tpu.telemetry import stmt_stats, tracing
    from greptimedb_tpu.telemetry.metrics import global_registry

    label = f"{kernel}({reason})"
    stats.note(f"mesh_kernel_{kind}", label)
    tracing.set_attr(**{f"mesh_kernel_{kind}": label})
    stmt_stats.note("mesh_kernel", label)
    global_registry.counter(
        "gtpu_mesh_queries_total",
        "Mesh execution decisions by mode/reason/site",
        labels=("kind", "mode", "reason"),
    ).labels(f"{kind}_kernel", kernel, reason).inc()


def record_scan_path(pruned: bool) -> None:
    """Surface whether a statement's scan rode the secondary tag index
    (matched-sid set threaded down to SST/row-group pruning) or read
    the full table, in EXPLAIN ANALYZE, the statement-statistics row,
    and gtpu_index_scans_total."""
    from greptimedb_tpu.query import stats
    from greptimedb_tpu.telemetry import stmt_stats
    from greptimedb_tpu.telemetry.metrics import global_registry

    path = "index_pruned" if pruned else "full_scan"
    stats.note("scan_path", path)
    stmt_stats.note("scan_path", path)
    global_registry.counter(
        "gtpu_index_scans_total",
        "Statement scans by path (index_pruned | full_scan)",
        labels=("path",),
    ).labels(path).inc()


def record_mesh_decision(decision: MeshDecision, kind: str) -> None:
    """Surface one decision in EXPLAIN ANALYZE + gtpu_mesh_* metrics.
    No-op counters-wise when no mesh is configured (devices == 1) so the
    single-device deployment's metric surface stays unchanged."""
    from greptimedb_tpu.query import stats
    from greptimedb_tpu.telemetry import stmt_stats, tracing

    stats.note(f"mesh_decision_{kind}", decision.label())
    # the same decision rides the active trace span, so a trace shows
    # replicate-vs-shard next to the device.execute spans it produced —
    # and the statement's statistics row, so an operator can ask which
    # fingerprints actually shard across the mesh
    tracing.set_attr(**{f"mesh_decision_{kind}": decision.label()})
    stmt_stats.note("mesh_decision", decision.label())
    if decision.devices <= 1:
        return
    if decision.shard:
        # only sharded executions spread over the mesh; a replicated
        # query on a meshed process still runs on one device
        active = stats.active()
        if active is not None:
            active.counters["mesh_devices"] = decision.devices
    from greptimedb_tpu.telemetry.metrics import global_registry

    global_registry.counter(
        "gtpu_mesh_queries_total",
        "Mesh execution decisions by mode/reason/site",
        labels=("kind", "mode", "reason"),
    ).labels(kind, decision.mode, decision.reason).inc()
    if decision.shard and decision.kernel_reason:
        record_kernel_decision(kind, decision.kernel,
                               decision.kernel_reason)


_NORMALIZE_AGG = {
    "avg": "mean", "mean": "mean", "sum": "sum", "min": "min", "max": "max",
    "count": "count", "stddev": "stddev_samp", "stddev_pop": "stddev_pop",
    "stddev_samp": "stddev_samp", "var": "var_samp", "var_pop": "var_pop",
    "var_samp": "var_samp", "variance": "var_samp",
    "first_value": "first_value", "last_value": "last_value",
    "median": "quantile", "percentile": "quantile", "quantile": "quantile",
    "approx_percentile_cont": "quantile", "percentile_cont": "quantile",
    "count_distinct": "count_distinct", "approx_distinct": "count_distinct",
}


def split_conjuncts(e: A.Expr | None) -> list[A.Expr]:
    if e is None:
        return []
    if isinstance(e, A.BinaryOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


_VOLATILE_CALLS = frozenset({
    "now", "current_timestamp", "current_time", "current_date",
    "localtime", "localtimestamp", "random", "rand", "uuid",
})


def _has_volatile_call(e) -> bool:
    """Does the expression tree contain an evaluation-time-dependent
    function call (the fold would freeze a different value per plan)?"""
    if isinstance(e, A.FuncCall) and e.name.lower() in _VOLATILE_CALLS:
        return True
    import dataclasses as _dc

    if _dc.is_dataclass(e) and not isinstance(e, type):
        for f in _dc.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, A.Expr) and _has_volatile_call(v):
                return True
            if isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, A.Expr) and _has_volatile_call(x):
                        return True
    return False


def _try_const(e: A.Expr):
    """Constant-fold an expression with no column refs; None on failure."""
    from greptimedb_tpu.query.expr import collect_columns

    if collect_columns(e):
        return None
    try:
        return eval_const(e)
    except Exception:
        return None


def _const_ts(e: A.Expr):
    v = _try_const(e)
    if v is None:
        return None
    if isinstance(v, str):
        try:
            return parse_ts_literal(v)
        except Exception:
            return None
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return int(v)
    return None


def analyze_where(
    where: A.Expr | None, *, ts_name: str, tag_names: list[str]
) -> ScanSpec:
    """Split WHERE into scan-time pruning (time bounds + tag matchers) and a
    residual row filter."""
    spec = ScanSpec()
    residual: list[A.Expr] = []
    for c in split_conjuncts(where):
        if _absorb_time(c, ts_name, spec):
            if _has_volatile_call(c):
                spec.volatile_bounds = True
            continue
        if _absorb_matcher(c, tag_names, spec):
            continue
        if (isinstance(c, A.FuncCall) and c.name == "matches"
                and len(c.args) == 2
                and isinstance(c.args[0], A.Column)
                and isinstance(c.args[1], A.Literal)):
            # stays in the residual for exact row filtering; recorded
            # for index pruning too
            spec.fulltext.append((c.args[0].name, str(c.args[1].value)))
        residual.append(c)
    if residual:
        e = residual[0]
        for r in residual[1:]:
            e = A.BinaryOp("and", e, r)
        spec.residual = e
    return spec


def _absorb_time(c: A.Expr, ts_name: str, spec: ScanSpec) -> bool:
    def tighten(lo=None, hi=None):
        if lo is not None:
            spec.ts_min = lo if spec.ts_min is None else max(spec.ts_min, lo)
        if hi is not None:
            spec.ts_max = hi if spec.ts_max is None else min(spec.ts_max, hi)

    if isinstance(c, A.Between) and not c.negated and isinstance(
        c.operand, A.Column
    ) and c.operand.name == ts_name:
        lo = _const_ts(c.low)
        hi = _const_ts(c.high)
        if lo is None or hi is None:
            return False
        tighten(lo, hi)
        return True
    if not isinstance(c, A.BinaryOp):
        return False
    left, right, op = c.left, c.right, c.op
    if isinstance(right, A.Column) and right.name == ts_name:
        left, right = right, left
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    if not (isinstance(left, A.Column) and left.name == ts_name):
        return False
    v = _const_ts(right)
    if v is None:
        return False
    if op == ">=":
        tighten(lo=v)
    elif op == ">":
        tighten(lo=v + 1)
    elif op == "<=":
        tighten(hi=v)
    elif op == "<":
        tighten(hi=v - 1)
    elif op == "=":
        tighten(lo=v, hi=v)
    else:
        return False
    return True


def _absorb_matcher(c: A.Expr, tag_names: list[str], spec: ScanSpec) -> bool:
    if isinstance(c, A.InList) and isinstance(c.operand, A.Column) and (
        c.operand.name in tag_names
    ):
        vals = []
        for item in c.items:
            v = _try_const(item)
            if not isinstance(v, str):
                return False
            vals.append(v)
        spec.matchers.append(
            (c.operand.name, "nin" if c.negated else "in", vals)
        )
        return True
    if not isinstance(c, A.BinaryOp):
        return False
    left, right = c.left, c.right
    if isinstance(right, A.Column) and right.name in tag_names and c.op == "=":
        left, right = right, left
    if not (isinstance(left, A.Column) and left.name in tag_names):
        return False
    v = _try_const(right)
    if not isinstance(v, str):
        return False
    if c.op == "=":
        spec.matchers.append((left.name, "eq", v))
    elif c.op == "!=":
        spec.matchers.append((left.name, "ne", v))
    elif c.op == "like":
        spec.matchers.append((left.name, "re", like_to_regex(v)))
    else:
        return False
    return True


class _Rewriter:
    """Pulls aggregates (and matched group keys) out of item expressions,
    replacing them with internal column refs."""

    def __init__(self, keys: list[KeySpec]):
        self.keys = keys
        self.aggs: list[AggSpec] = []
        self._agg_index: dict[str, str] = {}

    def _key_for(self, e: A.Expr) -> str | None:
        for k in self.keys:
            if k.expr == e:
                return k.key
        return None

    def _add_agg(self, fc: A.FuncCall) -> str:
        sig = repr(fc)
        if sig in self._agg_index:
            return self._agg_index[sig]
        if fc.filter is not None:
            # agg(x) FILTER (WHERE c) == agg(CASE WHEN c THEN x END):
            # aggregates skip NULLs, so the CASE's NULL else-arm drops
            # exactly the filtered-out rows (count(*) counts a literal)
            args = list(fc.args)
            if not args or isinstance(args[0], A.Star):
                # count(*): count a filtered literal instead
                args = [A.Case(None, [(fc.filter, A.Literal(1))], None)]
            else:
                # the VALUE argument is the last one (two-arg aggs like
                # quantile(q, x) carry the fraction first)
                arg_pos = len(args) - 1
                args[arg_pos] = A.Case(
                    None, [(fc.filter, args[arg_pos])], None
                )
            fc = A.FuncCall(fc.name, args, distinct=fc.distinct,
                            order_by=fc.order_by)
        name = _NORMALIZE_AGG.get(fc.name)
        if name is None:
            raise UnsupportedError(f"unknown aggregate: {fc.name}")
        q = None
        arg: A.Expr | None
        if fc.name == "median":
            q = 0.5
            arg = fc.args[0]
        elif name == "quantile":
            if len(fc.args) != 2:
                raise PlanError(f"{fc.name}(q, expr) takes 2 arguments")
            q = float(eval_const(fc.args[0]))
            arg = fc.args[1]
        elif fc.name == "count" and (
            not fc.args or isinstance(fc.args[0], A.Star)
        ):
            arg = None
        else:
            if len(fc.args) != 1:
                raise PlanError(f"{fc.name} takes 1 argument")
            arg = fc.args[0]
        distinct = fc.distinct or name == "count_distinct"
        if fc.name == "count" and fc.distinct:
            name = "count_distinct"
        key = f"__agg_{len(self.aggs)}"
        self.aggs.append(AggSpec(key=key, op=name, arg=arg, distinct=distinct, q=q))
        self._agg_index[sig] = key
        return key

    def rewrite(self, e: A.Expr) -> A.Expr:
        if isinstance(e, A.FuncCall) and e.over is not None:
            raise PlanError(
                "window functions combined with GROUP BY/aggregates are "
                "not supported yet"
            )
        k = self._key_for(e)
        if k is not None:
            return A.Column(k)
        if isinstance(e, A.RangeFunc):
            raise PlanError(
                "`agg(x) RANGE '...'` requires an ALIGN clause "
                "(e.g. ... FROM t ALIGN '5s' BY (host))"
            )
        if isinstance(e, A.FuncCall) and e.name in AGGREGATE_FUNCS:
            return A.Column(self._add_agg(e))
        if isinstance(e, A.FuncCall):
            return A.FuncCall(
                e.name, [self.rewrite(a) for a in e.args], e.distinct,
                e.order_by,
            )
        if isinstance(e, A.BinaryOp):
            return A.BinaryOp(e.op, self.rewrite(e.left), self.rewrite(e.right))
        if isinstance(e, A.UnaryOp):
            return A.UnaryOp(e.op, self.rewrite(e.operand))
        if isinstance(e, A.Cast):
            return A.Cast(self.rewrite(e.operand), e.to)
        if isinstance(e, A.Between):
            return A.Between(
                self.rewrite(e.operand), self.rewrite(e.low),
                self.rewrite(e.high), e.negated,
            )
        if isinstance(e, A.InList):
            return A.InList(
                self.rewrite(e.operand), [self.rewrite(i) for i in e.items],
                e.negated,
            )
        if isinstance(e, A.IsNull):
            return A.IsNull(self.rewrite(e.operand), e.negated)
        if isinstance(e, A.Case):
            return A.Case(
                self.rewrite(e.operand) if e.operand else None,
                [(self.rewrite(c), self.rewrite(t)) for c, t in e.whens],
                self.rewrite(e.else_) if e.else_ else None,
            )
        return e


def _resolve_alias(e: A.Expr, items: list[A.SelectItem]) -> A.Expr:
    """GROUP BY / ORDER BY / HAVING may reference select aliases (anywhere
    in the expression) or 1-based positions (top level only)."""
    if isinstance(e, A.Literal) and isinstance(e.value, int) and not (
        isinstance(e.value, bool)
    ):
        idx = e.value - 1
        if 0 <= idx < len(items):
            return items[idx].expr
        raise PlanError(f"position {e.value} is out of range")
    return _resolve_alias_deep(e, items)


def _resolve_alias_deep(e: A.Expr, items: list[A.SelectItem]) -> A.Expr:
    if isinstance(e, A.Column):
        for item in items:
            if item.alias == e.name:
                return item.expr
        return e
    rec = lambda x: _resolve_alias_deep(x, items)
    if isinstance(e, A.BinaryOp):
        return A.BinaryOp(e.op, rec(e.left), rec(e.right))
    if isinstance(e, A.UnaryOp):
        return A.UnaryOp(e.op, rec(e.operand))
    if isinstance(e, A.Cast):
        return A.Cast(rec(e.operand), e.to)
    if isinstance(e, A.Between):
        return A.Between(rec(e.operand), rec(e.low), rec(e.high), e.negated)
    if isinstance(e, A.InList):
        return A.InList(rec(e.operand), [rec(i) for i in e.items], e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(rec(e.operand), e.negated)
    if isinstance(e, A.FuncCall):
        return A.FuncCall(e.name, [rec(a) for a in e.args], e.distinct,
                          e.order_by, over=e.over)
    return e


def item_name(item: A.SelectItem) -> str:
    if item.alias:
        return item.alias
    return format_expr(item.expr)


def plan_select(
    stmt: A.Select, *, ts_name: str | None, tag_names: list[str],
    all_columns: list[str] | None,
) -> SelectPlan:
    scan = analyze_where(
        stmt.where, ts_name=ts_name or "", tag_names=tag_names
    )

    # expand * for plain selects
    items = []
    for it in stmt.items:
        if isinstance(it.expr, A.Star):
            if all_columns is None:
                raise PlanError("SELECT * without a table")
            items.extend(A.SelectItem(A.Column(c)) for c in all_columns)
        else:
            items.append(it)

    if stmt.range_clause is not None:
        return _plan_range(stmt, items, scan, ts_name, tag_names)

    group_exprs = [_resolve_alias(g, items) for g in stmt.group_by]
    has_agg = bool(group_exprs) or any(
        contains_aggregate(it.expr) for it in items
    ) or (stmt.having is not None and contains_aggregate(stmt.having))

    if has_agg:
        from greptimedb_tpu.query.window_fns import collect_window_calls

        wins: list = []
        for it in items:
            collect_window_calls(it.expr, wins)
        for o in stmt.order_by:
            collect_window_calls(o.expr, wins)
        if stmt.having is not None:
            collect_window_calls(stmt.having, wins)
        if wins:
            raise PlanError(
                "window functions combined with GROUP BY/aggregates are "
                "not supported yet"
            )

    if not has_agg:
        plan = SelectPlan(
            kind="plain", table_name=stmt.from_table, scan=scan,
            items=[(it.expr, item_name(it)) for it in items],
            order_by=[
                A.OrderItem(_resolve_alias(o.expr, items), o.asc, o.nulls_first)
                for o in stmt.order_by
            ],
            limit=stmt.limit, offset=stmt.offset, distinct=stmt.distinct,
        )
        if stmt.having is not None:
            raise PlanError("HAVING requires GROUP BY or aggregates")
        return plan

    keys = [
        KeySpec(key=f"__key_{i}", expr=g, name=format_expr(g))
        for i, g in enumerate(group_exprs)
    ]
    rw = _Rewriter(keys)
    post_items = []
    for it in items:
        rewritten = rw.rewrite(it.expr)
        _check_group_refs(rewritten, keys, rw.aggs, it.expr)
        post_items.append((rewritten, item_name(it)))
    having = None
    if stmt.having is not None:
        having = rw.rewrite(_resolve_alias(stmt.having, items))
    order_by = []
    for o in stmt.order_by:
        oe = _resolve_alias(o.expr, items)
        # order-by may reference an output column name directly
        order_by.append(A.OrderItem(rw.rewrite(oe), o.asc, o.nulls_first))
    return SelectPlan(
        kind="aggregate", table_name=stmt.from_table, scan=scan,
        keys=keys, aggs=rw.aggs, post_items=post_items, having=having,
        order_by=order_by, limit=stmt.limit, offset=stmt.offset,
        distinct=stmt.distinct,
    )


def _check_group_refs(e: A.Expr, keys, aggs, original):
    """Every bare column in a rewritten post-agg expr must be an internal
    ref; anything else references a non-grouped column."""
    from greptimedb_tpu.query.expr import collect_columns

    internal = {k.key for k in keys} | {a.key for a in aggs}
    bad = [
        c for c in collect_columns(e)
        if c not in internal and not c.startswith("__")
    ]
    if bad:
        raise PlanError(
            f"column {bad[0]!r} must appear in GROUP BY or an aggregate "
            f"(in {format_expr(original)})"
        )


def _plan_range(
    stmt: A.Select, items: list[A.SelectItem], scan: ScanSpec,
    ts_name: str | None, tag_names: list[str],
) -> SelectPlan:
    rc = stmt.range_clause
    align_to = 0
    if rc.to:
        t = rc.to.strip().lower()
        if t in ("now",):
            import time as _time

            align_to = int(_time.time() * 1000)
            # folded wall clock: the plan re-fingerprints every call
            scan.volatile_bounds = True
        elif t in ("", "calendar"):
            align_to = 0
        else:
            align_to = parse_ts_literal(rc.to)

    by_exprs = rc.by if rc.by is not None else [A.Column(t) for t in tag_names]
    # BY () means a single global group
    keys = [
        KeySpec(key=f"__key_{i}", expr=e, name=format_expr(e))
        for i, e in enumerate(by_exprs)
    ]

    range_items: list[RangeItemSpec] = []
    post_items = []
    ts_out = None

    def rewrite_range(e: A.Expr) -> A.Expr:
        nonlocal ts_out
        if isinstance(e, A.Column) and ts_name and e.name == ts_name:
            ts_out = "__ts"
            return A.Column("__ts")
        for k in keys:
            if k.expr == e:
                return A.Column(k.key)
        if isinstance(e, A.RangeFunc):
            fc = e.func
            op = _NORMALIZE_AGG.get(fc.name)
            if op is None:
                raise UnsupportedError(f"unknown range aggregate: {fc.name}")
            if op == "quantile":
                # needs raw per-window values (not an associative partial
                # state); the sliding sparse-table combine cannot express it
                raise UnsupportedError(
                    f"{fc.name} is not supported in RANGE queries yet"
                )
            arg = None
            if fc.args and not isinstance(fc.args[0], A.Star):
                arg = fc.args[-1]
            key = f"__r_{len(range_items)}"
            range_items.append(RangeItemSpec(
                key=key, op=op, arg=arg, range_ms=e.range_ms, fill=e.fill,
            ))
            return A.Column(key)
        if isinstance(e, A.FuncCall):
            if e.name in AGGREGATE_FUNCS:
                raise PlanError(
                    f"aggregate {e.name} in a RANGE query needs RANGE "
                    "'<interval>'"
                )
            return A.FuncCall(
                e.name, [rewrite_range(a) for a in e.args], e.distinct,
                e.order_by,
            )
        if isinstance(e, A.BinaryOp):
            return A.BinaryOp(e.op, rewrite_range(e.left), rewrite_range(e.right))
        if isinstance(e, A.UnaryOp):
            return A.UnaryOp(e.op, rewrite_range(e.operand))
        if isinstance(e, A.Cast):
            return A.Cast(rewrite_range(e.operand), e.to)
        return e

    for it in items:
        post_items.append((rewrite_range(it.expr), item_name(it)))
    order_by = [
        A.OrderItem(rewrite_range(_resolve_alias(o.expr, items)), o.asc,
                    o.nulls_first)
        for o in stmt.order_by
    ]
    having = None
    if stmt.having is not None:
        having = rewrite_range(_resolve_alias(stmt.having, items))
    if not range_items:
        raise PlanError("RANGE query has no `agg(x) RANGE '...'` items")
    return SelectPlan(
        kind="range", table_name=stmt.from_table, scan=scan, keys=keys,
        range_items=range_items, post_items=post_items, having=having,
        order_by=order_by, limit=stmt.limit, offset=stmt.offset,
        distinct=stmt.distinct,
        align_ms=rc.align_ms, align_to=align_to, fill=rc.fill,
        ts_out_name=ts_out,
    )
