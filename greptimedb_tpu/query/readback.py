"""Device->host readback helpers: the one blessed tunnel crossing.

Every query-path device->host result transfer goes through this module
so that (a) the bytes are attributed on /metrics
(`gtpu_readback_bytes_total{mode=full|delta}` — BENCH_r05 showed the
tunnel, not the kernels, is the user-visible latency), and (b) delta
polls can slice ON DEVICE before materializing, shipping only the rows/
steps a `since` cursor has not seen instead of the whole buffer.

gtlint GT015 enforces the contract: a raw `np.asarray(...)` /
`jax.device_get(...)` on a device result buffer (a name
`.block_until_ready()` was called on) in query-path code is a finding —
it would read the full buffer back unattributed where these helpers
exist.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.telemetry import stmt_stats
from greptimedb_tpu.telemetry.metrics import global_registry

_READBACK_BYTES = global_registry.counter(
    "gtpu_readback_bytes_total",
    "device->host result readback bytes by mode "
    "(full buffer vs since-cursor delta slice)",
    labels=("mode",),
)


def _materialize(arr, dtype=None) -> np.ndarray:
    out = np.asarray(arr)
    if dtype is not None:
        out = out.astype(dtype, copy=False)
    return out


def read_full(arr, dtype=None) -> np.ndarray:
    """Materialize a whole device buffer on host (mode=full)."""
    out = _materialize(arr, dtype)
    _READBACK_BYTES.labels("full").inc(int(out.nbytes))
    stmt_stats.add("readback_full_bytes", int(out.nbytes))
    return out


def read_delta(arr, lo: int, *, axis: int = -1, dtype=None) -> np.ndarray:
    """Materialize only `arr[..., lo:]` along `axis` (mode=delta).

    The slice happens on the device array BEFORE np.asarray, so only the
    delta bytes cross the host<->device tunnel — the point of the
    incremental-readback path (a dashboard poll with a `since` cursor
    reads back only the steps it has not seen)."""
    if lo <= 0:
        return read_full(arr, dtype)
    idx = [slice(None)] * arr.ndim
    idx[axis] = slice(lo, None)
    out = _materialize(arr[tuple(idx)], dtype)
    _READBACK_BYTES.labels("delta").inc(int(out.nbytes))
    stmt_stats.add("readback_delta_bytes", int(out.nbytes))
    return out


def readback_bytes(mode: str) -> float:
    """Current counter value (tests, bench)."""
    return _READBACK_BYTES.labels(mode).value
