"""Host-side (numpy) expression evaluation over columnar batches.

This is the scalar-expression layer of the query engine — the capability
counterpart of DataFusion's PhysicalExpr evaluation reached from
/root/reference/src/query/src/datafusion.rs. Vectorized numpy on the host
handles projections/filters/post-aggregation arithmetic; the *hot* reductions
(group-by aggregates, range windows) are lowered to device kernels by the
executor instead of being evaluated here.

Nulls are explicit validity masks (None == all valid); comparison with null
yields null, and filters treat null as false — SQL three-valued logic.
"""

from __future__ import annotations

import datetime as _dt
import functools
import re
from dataclasses import dataclass

import numpy as np

from greptimedb_tpu.errors import (
    ColumnNotFoundError,
    ExecutionError,
    PlanError,
    UnsupportedError,
)
from greptimedb_tpu.sql import ast as A


@dataclass
class Col:
    """One evaluated column: values + validity (None == all valid)."""

    values: np.ndarray
    validity: np.ndarray | None = None

    def __len__(self):
        return len(self.values)

    @property
    def valid_mask(self) -> np.ndarray:
        if self.validity is None:
            return np.ones(len(self.values), dtype=bool)
        return self.validity

    def is_all_valid(self) -> bool:
        return self.validity is None or bool(self.validity.all())


class ColumnSource:
    """Resolves column names to Cols; implemented by the executor over scan
    output (fields direct, tags decoded lazily via the series registry).
    rows/tag_names default to the no-raw-rows shape so any source can
    feed the plain/aggregate executor paths (RowsSource overrides)."""

    num_rows: int = 0
    rows = None
    tag_names: list[str] = []

    def col(self, name: str) -> Col:  # pragma: no cover - interface
        raise ColumnNotFoundError(name)


class EmptySource(ColumnSource):
    """For evaluating constant expressions."""

    num_rows = 1

    def col(self, name: str) -> Col:
        raise ColumnNotFoundError(f"column not found: {name}")


@functools.lru_cache(maxsize=1024)
def compile_matcher(pattern: str, flags: int = 0) -> re.Pattern:
    """Memoized regex compile for tag matchers: dashboards repeat the
    same =~ patterns every poll, and re.compile per matcher per query
    was measurable at fleet query rates. Keyed on (pattern, flags) —
    compiled patterns are immutable, so sharing is safe."""
    return re.compile(pattern, flags)


@functools.lru_cache(maxsize=1024)
def like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.DOTALL)


def parse_ts_literal(text: str) -> int:
    """Timestamp string -> epoch ms. Accepts 'YYYY-MM-DD[ HH:MM:SS[.fff]]',
    ISO-8601 with T/Z, and '+HH:MM' offsets; naive times are UTC."""
    t = text.strip()
    if re.fullmatch(r"[+-]?\d+", t):
        return int(t)
    norm = t.replace("T", " ").replace("Z", "+00:00")
    for fmt in (
        "%Y-%m-%d %H:%M:%S.%f%z", "%Y-%m-%d %H:%M:%S%z",
        "%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S",
        "%Y-%m-%d %H:%M", "%Y-%m-%d%z", "%Y-%m-%d",
    ):
        try:
            dt = _dt.datetime.strptime(norm, fmt)
        except ValueError:
            continue
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_dt.timezone.utc)
        return int(dt.timestamp() * 1000)
    raise ExecutionError(f"cannot parse timestamp literal: {text!r}")


def _is_string_col(c: Col) -> bool:
    return c.values.dtype == object or c.values.dtype.kind in ("U", "S")


def _coerce_pair(a: Col, b: Col) -> tuple[np.ndarray, np.ndarray]:
    av, bv = a.values, b.values
    if _is_string_col(a) != _is_string_col(b):
        # comparing a string column against a parsed number etc.
        av = av.astype(str) if not _is_string_col(a) else av
        bv = bv.astype(str) if not _is_string_col(b) else bv
    return av, bv


def _merge_validity(*cols: Col) -> np.ndarray | None:
    out = None
    for c in cols:
        if c.validity is not None:
            out = c.validity if out is None else (out & c.validity)
    return out


def eval_expr(e: A.Expr, src: ColumnSource) -> Col:
    n = src.num_rows
    if isinstance(e, A.Literal):
        if e.value is None:
            return Col(np.zeros(n), np.zeros(n, dtype=bool))
        if isinstance(e.value, bool):
            return Col(np.full(n, e.value, dtype=bool))
        if isinstance(e.value, int):
            return Col(np.full(n, e.value, dtype=np.int64))
        if isinstance(e.value, float):
            return Col(np.full(n, e.value, dtype=np.float64))
        return Col(np.full(n, e.value, dtype=object))
    if isinstance(e, A.IntervalLit):
        return Col(np.full(n, e.ms, dtype=np.int64))
    if isinstance(e, A.Column):
        return src.col(e.name)
    if isinstance(e, A.BinaryOp):
        return _eval_binary(e, src)
    if isinstance(e, A.UnaryOp):
        c = eval_expr(e.operand, src)
        if e.op == "-":
            return Col(-c.values, c.validity)
        if e.op == "not":
            return Col(~c.values.astype(bool), c.validity)
        raise UnsupportedError(f"unary op {e.op}")
    if isinstance(e, A.Cast):
        return _eval_cast(e, src)
    if isinstance(e, A.Between):
        v = eval_expr(e.operand, src)
        lo = eval_expr(e.low, src)
        hi = eval_expr(e.high, src)
        out = (v.values >= lo.values) & (v.values <= hi.values)
        if e.negated:
            out = ~out
        return Col(out, _merge_validity(v, lo, hi))
    if isinstance(e, A.InList):
        v = eval_expr(e.operand, src)
        hits = np.zeros(n, dtype=bool)
        for item in e.items:
            iv = eval_expr(item, src)
            a, b = _coerce_pair(v, iv)
            hits |= a == b
        if e.negated:
            hits = ~hits
        return Col(hits, v.validity)
    if isinstance(e, A.IsNull):
        valid = eval_expr(e.operand, src).valid_mask
        return Col(valid if e.negated else ~valid)
    if isinstance(e, A.Case):
        return _eval_case(e, src)
    if isinstance(e, A.FuncCall):
        if e.filter is not None:
            # aggregates consume .filter in the planner; a FuncCall
            # reaching scalar evaluation with one would silently drop it
            raise UnsupportedError(
                "FILTER is only supported on aggregate functions"
            )
        from greptimedb_tpu.query.functions import eval_scalar_function

        return eval_scalar_function(e, src)
    if isinstance(e, A.Star):
        raise PlanError("'*' is only valid as a select item or in count(*)")
    raise UnsupportedError(f"cannot evaluate expression: {e!r}")


def _eval_binary(e: A.BinaryOp, src: ColumnSource) -> Col:
    op = e.op
    a = eval_expr(e.left, src)
    b = eval_expr(e.right, src)
    if op in ("and", "or"):
        av = a.values.astype(bool)
        bv = b.values.astype(bool)
        if op == "and":
            vals = av & bv
            # Kleene: false AND null == false
            if a.validity is None and b.validity is None:
                return Col(vals)
            valid = (a.valid_mask & b.valid_mask) | (a.valid_mask & ~av) | (
                b.valid_mask & ~bv
            )
            return Col(vals & valid, valid)
        vals = av | bv
        if a.validity is None and b.validity is None:
            return Col(vals)
        valid = (a.valid_mask & b.valid_mask) | (a.valid_mask & av) | (
            b.valid_mask & bv
        )
        return Col(vals, valid)

    validity = _merge_validity(a, b)
    if op in ("=", "!=", "<", "<=", ">", ">="):
        av, bv = _coerce_pair(a, b)
        with np.errstate(invalid="ignore"):
            if op == "=":
                out = av == bv
            elif op == "!=":
                out = av != bv
            elif op == "<":
                out = av < bv
            elif op == "<=":
                out = av <= bv
            elif op == ">":
                out = av > bv
            else:
                out = av >= bv
        return Col(np.asarray(out, dtype=bool), validity)
    if op == "like":
        if isinstance(e.right, A.Literal) and isinstance(e.right.value, str):
            rx = like_to_regex(e.right.value)
            vals = np.asarray(
                [bool(rx.fullmatch(str(v))) for v in a.values], dtype=bool
            )
        else:
            # per-row pattern (LIKE against a column)
            vals = np.asarray(
                [
                    bool(like_to_regex(str(p)).fullmatch(str(v)))
                    for v, p in zip(a.values, b.values)
                ],
                dtype=bool,
            )
        return Col(vals, validity)
    if op == "||":
        av, bv = a.values.astype(object), b.values.astype(object)
        return Col(
            np.asarray([str(x) + str(y) for x, y in zip(av, bv)], object),
            validity,
        )
    # arithmetic — a string combined with an INTERVAL is a timestamp
    # literal ('2024-01-01' - interval '1 hour'), matching the
    # reference's implicit timestamp coercion
    if op in ("+", "-"):
        def _as_ts(col: Col) -> Col:
            valid = col.valid_mask
            out = np.zeros(len(col.values), np.int64)
            for k, v in enumerate(col.values):
                if valid[k]:  # null slots stay 0 and propagate as NULL
                    out[k] = parse_ts_literal(str(v))
            return Col(out, col.validity)

        if isinstance(e.right, A.IntervalLit) and a.values.dtype == object:
            a = _as_ts(a)
        if isinstance(e.left, A.IntervalLit) and b.values.dtype == object:
            b = _as_ts(b)
    av, bv = a.values, b.values
    with np.errstate(divide="ignore", invalid="ignore"):
        if op == "+":
            out = av + bv
        elif op == "-":
            out = av - bv
        elif op == "*":
            out = av * bv
        elif op == "/":
            if np.issubdtype(np.asarray(av).dtype, np.integer) and np.issubdtype(
                np.asarray(bv).dtype, np.integer
            ):
                safe = np.where(bv == 0, 1, bv)
                out = av // safe
                bad = bv == 0
            else:
                out = av / np.where(bv == 0, np.nan, bv)
                bad = bv == 0
            if bad.any():
                validity = (
                    ~bad if validity is None else (validity & ~bad)
                )
        elif op == "%":
            safe = np.where(bv == 0, 1, bv)
            out = np.mod(av, safe)
            bad = bv == 0
            if bad.any():
                validity = ~bad if validity is None else (validity & ~bad)
        else:
            raise UnsupportedError(f"binary op {op}")
    return Col(out, validity)


def _eval_cast(e: A.Cast, src: ColumnSource) -> Col:
    c = eval_expr(e.operand, src)
    to = e.to
    if to.is_timestamp():
        if _is_string_col(c):
            vals = np.asarray(
                [parse_ts_literal(str(v)) for v in c.values], np.int64
            )
        else:
            vals = c.values.astype(np.int64)
        return Col(vals, c.validity)
    if to.is_string():
        return Col(c.values.astype(str).astype(object), c.validity)
    if _is_string_col(c) and to.is_numeric():
        np_t = to.to_numpy()
        out = np.zeros(len(c.values), np_t)
        valid = c.valid_mask.copy()
        for i, v in enumerate(c.values):
            try:
                out[i] = np_t.type(float(v))
            except (TypeError, ValueError):
                valid[i] = False
        return Col(out, valid)
    return Col(c.values.astype(to.to_numpy()), c.validity)


def _eval_case(e: A.Case, src: ColumnSource) -> Col:
    n = src.num_rows
    if e.operand is not None:
        op = eval_expr(e.operand, src)
    result = None
    validity = None
    decided = np.zeros(n, dtype=bool)
    for cond_e, then_e in e.whens:
        if e.operand is not None:
            cv = eval_expr(cond_e, src)
            a, b = _coerce_pair(op, cv)
            cond = (a == b) & op.valid_mask & cv.valid_mask
        else:
            cc = eval_expr(cond_e, src)
            cond = cc.values.astype(bool) & cc.valid_mask
        pick = cond & ~decided
        tv = eval_expr(then_e, src)
        if result is None:
            result = np.zeros(n, dtype=tv.values.dtype)
            validity = np.zeros(n, dtype=bool)
        result = np.where(pick, tv.values, result)
        validity = np.where(pick, tv.valid_mask, validity)
        decided |= cond
    if e.else_ is not None:
        ev = eval_expr(e.else_, src)
        if result is None:
            result = ev.values.copy()
            validity = ev.valid_mask.copy()
        else:
            result = np.where(decided, result, ev.values)
            validity = np.where(decided, validity, ev.valid_mask)
    elif result is None:
        return Col(np.zeros(n), np.zeros(n, dtype=bool))
    else:
        validity = validity & decided
    return Col(result, None if validity.all() else validity)


def eval_const(e: A.Expr):
    """Evaluate a constant expression to a python scalar (None if null)."""
    c = eval_expr(e, EmptySource())
    if c.validity is not None and not c.validity[0]:
        return None
    v = c.values[0]
    if isinstance(v, np.generic):
        return v.item()
    return v


def collect_columns(e: A.Expr, out: set[str] | None = None) -> set[str]:
    """All column names referenced by an expression."""
    if out is None:
        out = set()
    if isinstance(e, A.Column):
        out.add(e.name)
    elif isinstance(e, A.BinaryOp):
        collect_columns(e.left, out)
        collect_columns(e.right, out)
    elif isinstance(e, A.UnaryOp):
        collect_columns(e.operand, out)
    elif isinstance(e, A.Cast):
        collect_columns(e.operand, out)
    elif isinstance(e, A.Between):
        for x in (e.operand, e.low, e.high):
            collect_columns(x, out)
    elif isinstance(e, A.InList):
        collect_columns(e.operand, out)
        for x in e.items:
            collect_columns(x, out)
    elif isinstance(e, A.IsNull):
        collect_columns(e.operand, out)
    elif isinstance(e, A.Case):
        if e.operand:
            collect_columns(e.operand, out)
        for c, t in e.whens:
            collect_columns(c, out)
            collect_columns(t, out)
        if e.else_:
            collect_columns(e.else_, out)
    elif isinstance(e, A.FuncCall):
        for x in e.args:
            collect_columns(x, out)
        if e.over is not None:
            for p in e.over.partition_by:
                collect_columns(p, out)
            for o in e.over.order_by:
                collect_columns(o.expr, out)
    elif isinstance(e, A.RangeFunc):
        collect_columns(e.func, out)
    return out


def format_expr(e: A.Expr) -> str:
    """Render an expression back to SQL-ish text (output column naming)."""
    if isinstance(e, A.Literal):
        if isinstance(e.value, str):
            escaped = e.value.replace("'", "''")
            return f"'{escaped}'"
        if e.value is None:
            return "NULL"
        return str(e.value)
    if isinstance(e, A.IntervalLit):
        # INTERVAL form round-trips compound intervals ('1 hour 30 minutes')
        return f"INTERVAL '{e.raw}'"
    if isinstance(e, A.Column):
        return e.name
    if isinstance(e, A.Star):
        return "*"
    if isinstance(e, A.BinaryOp):
        op = {"and": "AND", "or": "OR", "like": "LIKE"}.get(e.op, e.op)
        return f"{format_expr(e.left)} {op} {format_expr(e.right)}"
    if isinstance(e, A.UnaryOp):
        return f"{'-' if e.op == '-' else 'NOT '}{format_expr(e.operand)}"
    if isinstance(e, A.FuncCall):
        inner = ", ".join(format_expr(a) for a in e.args)
        if e.distinct:
            inner = "DISTINCT " + inner
        return f"{e.name}({inner})"
    if isinstance(e, A.RangeFunc):
        return f"{format_expr(e.func)} RANGE {e.range_ms}ms"
    if isinstance(e, A.Cast):
        return f"CAST({format_expr(e.operand)} AS {e.to.name})"
    if isinstance(e, A.Between):
        neg = " NOT" if e.negated else ""
        return (
            f"{format_expr(e.operand)}{neg} BETWEEN "
            f"{format_expr(e.low)} AND {format_expr(e.high)}"
        )
    if isinstance(e, A.InList):
        neg = " NOT" if e.negated else ""
        items = ", ".join(format_expr(i) for i in e.items)
        return f"{format_expr(e.operand)}{neg} IN ({items})"
    if isinstance(e, A.IsNull):
        return f"{format_expr(e.operand)} IS{' NOT' if e.negated else ''} NULL"
    if isinstance(e, A.Case):
        return "CASE ..."
    return repr(e)
