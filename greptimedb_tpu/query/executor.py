"""Plan executor: scans regions, prunes, and runs the hot reductions on
device.

Capability counterpart of the reference's physical execution
(/root/reference/src/query/src/datafusion.rs exec_query_plan +
range_select/plan.rs RangeSelectStream), restructured TPU-first:

- scan output is already columnar (sid, ts, fields) — zero transform into
  the device feed;
- tag group-bys never touch strings: per-row group codes come from the
  series registry's per-sid tag codes (host int gather), the reduction is a
  device segment kernel (query/reduce.py);
- RANGE queries build per-(group, bucket) partial states then combine
  windows by stride-doubling (sparse table) — O(log W) vectorized passes
  instead of the reference's per-window accumulator walk (plan.rs:1068).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from greptimedb_tpu.errors import (
    ColumnNotFoundError,
    ExecutionError,
    PlanError,
    UnsupportedError,
)
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.query.expr import (
    Col,
    ColumnSource,
    collect_columns,
    eval_expr,
)
from greptimedb_tpu.query.planner import SelectPlan
from greptimedb_tpu.query.reduce import grouped_reduce
from greptimedb_tpu.sql import ast as A


class QueryResult:
    """Columnar query output."""

    def __init__(self, names: list[str], cols: list[Col],
                 types: dict[str, ConcreteDataType] | None = None):
        self.names = names
        self.cols = cols
        self.types = types or {}
        self.num_rows = len(cols[0]) if cols else 0

    def rows(self) -> list[list]:
        """Row-major python values (None for nulls) — protocol output.
        Decimal columns render as scale-fixed strings (the exact wire
        form; they compute as float64 internally, datatypes/types.py).

        COLUMN-wise materialization: ndarray.tolist() converts a whole
        column at C speed (numpy scalars become native python values),
        then one zip transposes — the per-row-per-cell python loop this
        replaces dominated large result serving (the tsbs_high_cpu_all
        shape returns ~1.7M rows x 12 columns)."""
        pycols = []
        for j, c in enumerate(self.cols):
            vals = c.values
            dt = self.types.get(self.names[j])
            scale = (
                dt.scale if dt is not None and dt.is_decimal() else None
            )
            if scale is not None:
                lst = [f"{float(v):.{scale}f}" for v in vals.tolist()]
            elif vals.dtype == object:
                # object cells may hold numpy scalars: unwrap like the
                # per-cell .item() path did
                lst = [
                    v.item() if isinstance(v, np.generic) else v
                    for v in vals
                ]
            else:
                lst = vals.tolist()
            if c.validity is not None and not c.validity.all():
                invalid = np.flatnonzero(~c.validity)
                for i in invalid.tolist():
                    lst[i] = None
            pycols.append(lst)
        if not pycols:
            return [[] for _ in range(self.num_rows)]
        return [list(r) for r in zip(*pycols)]

    def column(self, name: str) -> Col:
        return self.cols[self.names.index(name)]

    def type_name(self, i: int) -> str:
        name = self.names[i]
        if name in self.types:
            return self.types[name].name
        dt = self.cols[i].values.dtype
        if dt == object:
            return "string"
        if dt == np.bool_:
            return "bool"
        return str(dt)


class _WindowOverlay(ColumnSource):
    """A row source plus computed window-function columns (__win_k)."""

    def __init__(self, base, extra: dict):
        self.base = base
        self.extra = extra
        self.num_rows = base.num_rows

    def col(self, name: str) -> Col:
        hit = self.extra.get(name)
        return hit if hit is not None else self.base.col(name)

    def __getattr__(self, name):
        return getattr(self.base, name)


class RowsSource(ColumnSource):
    """Column resolution over a table scan: fields and ts direct, tags
    decoded lazily through the series registry (strings never ship to
    device)."""

    def __init__(self, rows, registry, tag_names: list[str], ts_name: str):
        self.rows = rows
        self.registry = registry
        self.tag_names = tag_names
        self.ts_name = ts_name
        self.num_rows = 0 if rows is None else len(rows)
        self._tag_cache: dict[str, np.ndarray] = {}

    def col(self, name: str) -> Col:
        rows = self.rows
        if rows is None:
            raise ExecutionError("empty scan")
        if name == self.ts_name:
            return Col(rows.ts)
        if name in rows.fields:
            validity = None
            if rows.field_valid is not None and name in rows.field_valid:
                v = rows.field_valid[name]
                validity = None if v.all() else v
            return Col(rows.fields[name], validity)
        if name in self.tag_names:
            if name not in self._tag_cache:
                per_sid = self.registry.tag_values(name)
                self._tag_cache[name] = per_sid[rows.sid]
            return Col(self._tag_cache[name])
        raise ColumnNotFoundError(f"column not found: {name}")

    def tag_codes_per_row(self, name: str) -> tuple[np.ndarray, np.ndarray]:
        """(per-row int32 code, per-code string values) for a tag column —
        the no-strings group-by path."""
        per_sid = self.registry.tag_codes(name)
        codes = per_sid[self.rows.sid]
        values = np.asarray(self.registry.dicts[
            self.registry.tag_names.index(name)
        ].values, dtype=object)
        return codes, values


class DictSource(ColumnSource):
    """Column source over a plain name -> Col mapping (post-agg eval,
    system virtual tables)."""

    def __init__(self, cols: dict[str, Col], num_rows: int):
        self.cols = cols
        self.num_rows = num_rows

    def col(self, name: str) -> Col:
        try:
            return self.cols[name]
        except KeyError:
            raise ColumnNotFoundError(f"column not found: {name}") from None


def _sort_indices(cols: list[Col], ascs: list[bool],
                  nulls_first: list[bool | None],
                  primary: np.ndarray | None = None) -> np.ndarray:
    """Stable multi-key sort. Numeric keys via lexsort; object keys ranked
    first. SQL default null placement: last for ASC, first for DESC.
    `primary` (e.g. a window partition id) sorts most-significant."""
    n = len(cols[0]) if cols else (
        len(primary) if primary is not None else 0
    )
    keys = []
    for c, asc, nf in zip(reversed(cols), reversed(ascs), reversed(nulls_first)):
        vals = c.values
        if vals.dtype == object or vals.dtype.kind in ("U", "S"):
            # rank-encode strings so lexsort can handle them
            _, inv = np.unique(vals.astype(str), return_inverse=True)
            vals = inv.astype(np.int64)
        elif vals.dtype == np.bool_:
            vals = vals.astype(np.int8)
        elif vals.dtype.kind == "u":
            vals = vals.astype(np.int64)
        vals = vals.astype(np.float64) if vals.dtype.kind not in "if" else vals
        if not asc:
            # negate in the key's own dtype: int64 keys keep exact order
            # above 2^53 (float negation would merge distinct BIGINTs)
            vals = -vals
        null_last = nf is False or (nf is None and asc)
        nullkey = (~c.valid_mask).astype(np.int8)
        if not null_last:
            nullkey = -nullkey
        keys.append(vals)
        keys.append(nullkey)
    if primary is not None:
        keys.append(primary)
    if not keys:
        return np.arange(n)
    return np.lexsort(keys)


def _slice_result(cols: list[Col], idx) -> list[Col]:
    return [
        Col(c.values[idx],
            None if c.validity is None else c.validity[idx])
        for c in cols
    ]


def _group_order(key_cols: dict, keys: list[str], g: int) -> np.ndarray:
    """Permutation sorting groups by their key values (ascending, the
    default RANGE output order)."""
    if not keys:
        return np.arange(g)
    rank_keys = []
    for k in reversed(keys):
        c = key_cols[k]
        v = c.values
        if v.dtype == object or v.dtype.kind in ("U", "S"):
            _, inv = np.unique(v.astype(str), return_inverse=True)
            rank_keys.append(inv.astype(np.int64))
        else:
            rank_keys.append(v)
        # null keys sort last (ASC default), matching _sort_indices
        rank_keys.append((~c.valid_mask).astype(np.int8))
    return np.lexsort(rank_keys)


def _distinct_indices(cols: list[Col]) -> np.ndarray:
    if not cols:
        return np.arange(0)
    parts = []
    for c in cols:
        v = c.values
        if v.dtype == object:
            _, inv = np.unique(v.astype(str), return_inverse=True)
            parts.append(inv.astype(np.int64))
        else:
            _, inv = np.unique(v, return_inverse=True)
            parts.append(inv.astype(np.int64))
        parts.append((~c.valid_mask).astype(np.int64))
    stacked = np.stack(parts)
    _, first = np.unique(stacked, axis=1, return_index=True)
    return np.sort(first)


class QueryEngine:
    """Executes SelectPlans against catalog tables.

    `mesh` (a jax.sharding.Mesh with a "shard" axis) makes the device
    RANGE path shard its cell-state grids over the series axis; XLA
    inserts the cross-device collectives for group folds (SURVEY.md §2.7
    #1-2 — the region-partition + merge-scan analog over ICI)."""

    def __init__(self, *, prefer_device: bool | None = None, mesh=None,
                 mesh_opts=None):
        self.prefer_device = prefer_device
        # write/restore device grid snapshots across restarts
        self.persist_device_cache = True
        self.mesh = mesh
        self.mesh_opts = mesh_opts
        from greptimedb_tpu.query.device_range import DeviceRangeCache

        self.range_cache = DeviceRangeCache()
        self.last_exec_path = "host"  # observability: host | device

    def _record_path(self, kind: str, path: str):
        """Observability: device/host execution counts with fallback
        reasons (/metrics gtpu_query_exec_path_total)."""
        self.last_exec_path = "device" if path == "device" else "host"
        from greptimedb_tpu.query import stats
        from greptimedb_tpu.telemetry import stmt_stats
        from greptimedb_tpu.telemetry.metrics import global_registry

        stats.note(f"exec_path_{kind}", path)
        stmt_stats.note_exec_path(path)
        global_registry.counter(
            "gtpu_query_exec_path_total",
            "Query executions by path (device | host:<fallback reason>)",
            labels=("kind", "path"),
        ).labels(kind, path).inc()

    # ------------------------------------------------------------------
    def execute(self, plan: SelectPlan, table) -> QueryResult:
        if table is None:
            return self._execute_tableless(plan)
        self.last_exec_path = "host"
        if plan.kind == "range":
            from greptimedb_tpu.query import device_range

            res = device_range.execute_range_device(self, plan, table)
            if res is not None:
                self._record_path("range", "device")
                return res
            self._record_path("range", "host:shape")
        src = self._scan(plan, table)
        if plan.kind == "plain":
            return self._execute_plain(plan, src, table)
        if plan.kind == "aggregate":
            return self._execute_aggregate(plan, src, table)
        if plan.kind == "range":
            return self._execute_range(plan, src, table)
        raise PlanError(f"unknown plan kind: {plan.kind}")

    def _empty_result(self, names: list[str]) -> QueryResult:
        return QueryResult(names, [Col(np.zeros(0)) for _ in names])

    # ------------------------------------------------------------------
    def _scan(self, plan: SelectPlan, table) -> RowsSource:
        needed = set()
        for e, _ in plan.items:
            collect_columns(e, needed)
        for k in plan.keys:
            collect_columns(k.expr, needed)
        for a in plan.aggs:
            if a.arg is not None:
                collect_columns(a.arg, needed)
        for r in plan.range_items:
            if r.arg is not None:
                collect_columns(r.arg, needed)
        if plan.scan.residual is not None:
            collect_columns(plan.scan.residual, needed)
        for o in plan.order_by:
            collect_columns(o.expr, needed)
        if plan.having is not None:
            collect_columns(plan.having, needed)
        field_names = [f for f in table.field_names if f in needed]
        from greptimedb_tpu.query import stats

        with stats.timed("scan_ms"):
            ft = None
            if getattr(plan.scan, "fulltext", None):
                from greptimedb_tpu.query.fulltext import required_terms

                ft = [
                    (col, terms) for col, q in plan.scan.fulltext
                    if (terms := required_terms(q))
                ] or None
            from greptimedb_tpu.telemetry import tracing

            ts_min = plan.scan.ts_min
            if plan.kind == "plain":
                # delta-poll cursor: for row-returning plain selects the
                # `since` watermark IS a ts lower bound, applied at scan
                # time (before ORDER BY/LIMIT, like an extra WHERE).
                # Aggregate/range states must fold the FULL row set —
                # range emission filters at assembly instead.
                from greptimedb_tpu.query import sessions

                since = sessions.current_since()
                if since is not None:
                    ts_min = (since + 1 if ts_min is None
                              else max(ts_min, since + 1))
            with tracing.span("query.scan", table=table.name):
                data = table.scan(
                    ts_min=ts_min,
                    ts_max=plan.scan.ts_max,
                    field_names=field_names,
                    matchers=plan.scan.matchers or None,
                    fulltext=ft,
                )
        from greptimedb_tpu import index as _index
        from greptimedb_tpu.query.planner import record_scan_path

        record_scan_path(bool(plan.scan.matchers) and _index.enabled())
        stats.add("rows_scanned", data.num_rows)
        stats.add("series_total", data.registry.num_series)
        if stats.active() is not None and plan.scan.matchers:
            # selectivity is worth a re-match under EXPLAIN ANALYZE only
            # (the index result cache makes this a dict hit, not a scan)
            stats.add("series_matched", sum(
                len(r.match_sids(plan.scan.matchers))
                for r in table.regions
                if not getattr(r, "remote", False)
            ))
        src = RowsSource(data.rows, data.registry, table.tag_names,
                         table.ts_name)
        if plan.scan.residual is not None and src.num_rows:
            cond = eval_expr(plan.scan.residual, src)
            mask = cond.values.astype(bool) & cond.valid_mask
            if not mask.all():
                from greptimedb_tpu.storage.memtable import _slice_rows

                # one flatnonzero + integer takes beats re-scanning the
                # boolean mask once per column at low selectivity
                idx = np.flatnonzero(mask)
                stats.add("rows_filtered_residual",
                          int(src.num_rows - len(idx)))
                src = RowsSource(
                    _slice_rows(src.rows, idx), data.registry,
                    table.tag_names, table.ts_name,
                )
        return src

    # ------------------------------------------------------------------
    def _execute_tableless(self, plan: SelectPlan) -> QueryResult:
        if plan.kind != "plain":
            raise PlanError("aggregates need a FROM table")
        from greptimedb_tpu.query.expr import EmptySource

        src = EmptySource()
        names = [n for _, n in plan.items]
        cols = [eval_expr(e, src) for e, _ in plan.items]
        return QueryResult(names, cols)

    # ------------------------------------------------------------------
    def _execute_plain(self, plan, src: RowsSource, table) -> QueryResult:
        names = [n for _, n in plan.items]
        if src.num_rows == 0:
            cols = [Col(np.zeros(0)) for _ in plan.items]
            return QueryResult(names, cols, self._types_hint(plan, table))
        # window functions: compute each OVER() call over the full row
        # set, then project with the results spliced in as columns
        from greptimedb_tpu.query import window_fns as W

        win_calls = []
        for e, _ in plan.items:
            W.collect_window_calls(e, win_calls)
        for o in plan.order_by:
            W.collect_window_calls(o.expr, win_calls)
        # alias resolution can splice the SAME FuncCall object into
        # order_by — dedupe by identity so it's evaluated once
        win_calls = list({id(fc): fc for fc in win_calls}.values())
        if win_calls:
            extra: dict[str, Col] = {}
            mapping: dict[int, str] = {}
            for k, fc in enumerate(win_calls):
                cname = f"__win_{k}"
                mapping[id(fc)] = cname
                extra[cname] = W.eval_window(fc, src)
            src = _WindowOverlay(src, extra)
            plan = dataclasses.replace(
                plan,
                items=[(W.replace_window_calls(e, mapping), n)
                       for e, n in plan.items],
                order_by=[
                    A.OrderItem(W.replace_window_calls(o.expr, mapping),
                                o.asc, o.nulls_first)
                    for o in plan.order_by
                ],
            )
        cols = [eval_expr(e, src) for e, _ in plan.items]
        if plan.distinct:
            idx = _distinct_indices(cols)
            cols = _slice_result(cols, idx)
        cols = self._order_limit(plan, cols, names, extra_src=src)
        return QueryResult(names, cols, self._types_hint(plan, table))

    def _types_hint(self, plan, table) -> dict:
        hints = {}
        for e, n in (plan.items or plan.post_items):
            if isinstance(e, A.Column) and table is not None:
                c = table.schema.maybe_column(e.name)
                if c is not None:
                    hints[n] = c.data_type
        return hints

    # ------------------------------------------------------------------
    def _group_ids(self, plan, src: RowsSource):
        """Per-row group ids + per-group key output columns.

        Fast path: bare tag columns group through per-sid codes (no string
        materialization). Returns (gid, g, {key: Col})."""
        n = src.num_rows
        if not plan.keys:
            return np.zeros(n, dtype=np.int64), 1, {}
        code_cols = []
        decoders = []  # (vocab array, null_code | None)
        for k in plan.keys:
            e = k.expr
            if isinstance(e, A.Column) and e.name in src.tag_names:
                codes, vocab = src.tag_codes_per_row(e.name)
                code_cols.append(codes.astype(np.int64))
                decoders.append((vocab, None))
                continue
            c = eval_expr(e, src)
            v = c.values
            if v.dtype == object or v.dtype.kind in ("U", "S"):
                uniq, inv = np.unique(v.astype(str), return_inverse=True)
                codes = inv.astype(np.int64)
                vocab = uniq.astype(object)
                null_fill = ""
            else:
                uniq, inv = np.unique(v, return_inverse=True)
                codes = inv.astype(np.int64)
                vocab = uniq
                null_fill = uniq[0] if len(uniq) else 0
            null_code = None
            if c.validity is not None and not c.validity.all():
                # NULL is its own group, distinct from every value
                null_code = len(vocab)
                codes = np.where(c.validity, codes, null_code)
                vocab = np.append(vocab, null_fill)
            code_cols.append(codes)
            decoders.append((vocab, null_code))
        combined = code_cols[0]
        cards = [int(cc.max()) + 1 if len(cc) else 1 for cc in code_cols]
        for cc, card in zip(code_cols[1:], cards[1:]):
            combined = combined * card + cc
        uniq_comb, gid = np.unique(combined, return_inverse=True)
        g = len(uniq_comb)
        # decode group keys from the combined code
        key_cols = {}
        rem = uniq_comb
        for i in range(len(code_cols) - 1, -1, -1):
            card = cards[i]
            code_i = rem % card
            rem = rem // card
            vocab, null_code = decoders[i]
            vals = (vocab[code_i] if isinstance(vocab, np.ndarray)
                    else vocab.values[code_i])
            validity = None
            if null_code is not None:
                validity = code_i != null_code
                if validity.all():
                    validity = None
            key_cols[plan.keys[i].key] = Col(np.asarray(vals), validity)
        return gid.astype(np.int64), g, key_cols

    def _execute_aggregate(self, plan, src: RowsSource, table) -> QueryResult:
        n = src.num_rows
        if n == 0 and plan.keys:
            names = [nm for _, nm in plan.post_items]
            return self._empty_result(names)
        if n == 0:
            # global aggregate over empty input: one row
            agg_cols = {}
            for a in plan.aggs:
                if a.op in ("count", "count_distinct"):
                    agg_cols[a.key] = Col(np.zeros(1, np.int64))
                else:
                    agg_cols[a.key] = Col(np.zeros(1), np.zeros(1, bool))
            return self._post_project(plan, agg_cols, 1)

        gid, g, key_cols = self._group_ids(plan, src)

        values = {}
        valid_map = {}
        specs = []
        for a in plan.aggs:
            vk = None
            if a.arg is not None:
                vk = f"v{len(values)}"
                c = eval_expr(a.arg, src)
                values[vk] = c.values
                if c.validity is not None:
                    valid_map[vk] = c.validity
            if a.distinct and a.op not in ("count_distinct",):
                raise UnsupportedError(f"DISTINCT {a.op} is not supported")
            specs.append((a.key, a.op, vk, a.q))
        ts = src.rows.ts if src.rows is not None else None
        from greptimedb_tpu.query import stats

        with stats.timed("reduce_ms"):
            results, path = grouped_reduce(
                specs, values, gid, valid_map, g, ts=ts,
                prefer_device=self.prefer_device, mesh=self.mesh,
                mesh_opts=self.mesh_opts,
            )
        stats.add("agg_groups", g)
        self._record_path("aggregate", path)
        agg_cols = dict(key_cols)
        for name, (vals, valid) in results.items():
            agg_cols[name] = Col(
                vals, None if valid is None or valid.all() else valid
            )
        return self._post_project(plan, agg_cols, g)

    def _post_project(self, plan, agg_cols: dict, g: int) -> QueryResult:
        gsrc = DictSource(agg_cols, g)
        if plan.having is not None:
            cond = eval_expr(plan.having, gsrc)
            mask = cond.values.astype(bool) & cond.valid_mask
            agg_cols = {
                k: Col(c.values[mask],
                       None if c.validity is None else c.validity[mask])
                for k, c in agg_cols.items()
            }
            g = int(mask.sum())
            gsrc = DictSource(agg_cols, g)
        names = [nm for _, nm in plan.post_items]
        cols = [eval_expr(e, gsrc) for e, _ in plan.post_items]
        if plan.distinct:
            idx = _distinct_indices(cols)
            cols = _slice_result(cols, idx)
            gsrc = None
        cols = self._order_limit(plan, cols, names, extra_src=gsrc)
        return QueryResult(names, cols)

    def _order_limit(self, plan, cols: list[Col], names: list[str],
                     *, extra_src: ColumnSource | None) -> list[Col]:
        if plan.order_by:
            out_src = DictSource(dict(zip(names, cols)),
                                 len(cols[0]) if cols else 0)
            order_cols = []
            for o in plan.order_by:
                if isinstance(o.expr, A.Column) and o.expr.name in names:
                    order_cols.append(out_src.col(o.expr.name))
                else:
                    src2 = extra_src if extra_src is not None else out_src
                    try:
                        order_cols.append(eval_expr(o.expr, src2))
                    except ColumnNotFoundError:
                        order_cols.append(eval_expr(o.expr, out_src))
            if order_cols and len(order_cols[0]) != (len(cols[0]) if cols else 0):
                raise ExecutionError("ORDER BY length mismatch")
            idx = _sort_indices(
                order_cols, [o.asc for o in plan.order_by],
                [o.nulls_first for o in plan.order_by],
            )
            cols = _slice_result(cols, idx)
        off = plan.offset or 0
        if off or plan.limit is not None:
            end = None if plan.limit is None else off + plan.limit
            cols = _slice_result(cols, slice(off, end))
        return cols

    # ------------------------------------------------------------------
    # RANGE select
    # ------------------------------------------------------------------
    def _execute_range(self, plan, src: RowsSource, table) -> QueryResult:
        ts_type = table.schema.time_index.data_type
        names = [nm for _, nm in plan.post_items]
        if src.num_rows == 0:
            return self._empty_result(names)
        rows = src.rows
        align = plan.align_ms
        if align is None or align <= 0:
            raise PlanError("ALIGN interval must be positive")
        align_to = plan.align_to % align if plan.align_to else 0

        gid, g, key_cols = self._group_ids(plan, src)

        ts = rows.ts
        # distributed fill-grid override: the frontend negotiated the
        # global scanned extent so every datanode's grid is identical
        ts_min = (plan.grid_ts_min if plan.grid_ts_min is not None
                  else int(ts.min()))
        ts_max = (plan.grid_ts_max if plan.grid_ts_max is not None
                  else int(ts.max()))
        max_range = max(r.range_ms for r in plan.range_items)
        # steps t with (t, t+range) ∩ data ≠ ∅:  t > ts_min - range, t <= ts_max
        j_first = -((-(ts_min - max_range + 1 - align_to)) // align)
        j_last = (ts_max - align_to) // align
        n_steps = int(j_last - j_first + 1)
        if n_steps <= 0:
            return self._empty_result(names)
        for item in plan.range_items:
            # the real allocation is g * nb buckets at res = gcd(align,
            # range) — guard that, not just g * n_steps (a '1h1ms' range
            # against a '1m' align explodes the bucket count).
            res_i = int(np.gcd(align, item.range_ms))
            nb_i = (n_steps - 1) * (align // res_i) + item.range_ms // res_i
            if g * nb_i > 64_000_000:
                raise ExecutionError(
                    f"RANGE query too large: {g} groups x {nb_i} buckets "
                    f"(align={align}ms range={item.range_ms}ms gcd={res_i}ms)"
                )
        step_ts = (align_to + (j_first + np.arange(n_steps)) * align).astype(
            np.int64
        )

        item_vals = {}
        item_present = {}
        for item in plan.range_items:
            vals, present = self._range_item(
                item, src, gid, g, ts, align, align_to, j_first, n_steps,
            )
            item_vals[item.key] = vals
            item_present[item.key] = present
        from greptimedb_tpu.query import sessions

        return self._assemble_range_result(
            plan, table, item_vals, item_present, key_cols, step_ts,
            g, n_steps, since_ms=sessions.current_since(),
        )

    def _assemble_range_result(self, plan, table, item_vals, item_present,
                               key_cols, step_ts, g, n_steps,
                               since_ms: int | None = None) -> QueryResult:
        """Fill + output assembly over (g, n_steps) per-item grids — shared
        by the host path and the device grid-cache path
        (query/device_range.py). `since_ms` is the delta-poll cursor:
        only cells whose step ts is strictly greater are EMITTED (the
        fill math still runs over the full grid first, so PREV/LINEAR
        carry from pre-cursor steps stays identical to the full
        result)."""
        ts_type = table.schema.time_index.data_type
        names = [nm for _, nm in plan.post_items]
        any_present = np.zeros((g, n_steps), dtype=bool)
        for item in plan.range_items:
            fill = item.fill if item.fill is not None else plan.fill
            vals, present = _apply_fill(
                item_vals[item.key], item_present[item.key], fill, step_ts
            )
            item_vals[item.key] = vals
            item_present[item.key] = present
            any_present |= present

        # emit (group, step) cells: all cells when filling, else non-empty
        global_fill = plan.fill is not None or any(
            r.fill is not None for r in plan.range_items
        )
        if global_fill:
            cell_mask = np.ones((g, n_steps), dtype=bool)
        else:
            cell_mask = any_present
        if since_ms is not None:
            cell_mask = cell_mask & (step_ts > since_ms)[None, :]
        if not plan.order_by:
            # construct rows already in the default (ts, group keys) order:
            # rank groups once (g keys, not g*steps rows), then emit
            # ts-major — skips the output sort entirely
            perm = _group_order(key_cols, [k.key for k in plan.keys], g)
            nz_s, nz_g = np.nonzero(cell_mask[perm].T)
            gidx = perm[nz_g]
            sidx = nz_s
        else:
            gidx, sidx = np.nonzero(cell_mask)

        out_cols: dict[str, Col] = {}
        out_cols["__ts"] = Col(step_ts[sidx])
        for k, c in key_cols.items():
            out_cols[k] = Col(c.values[gidx],
                              None if c.validity is None else c.validity[gidx])
        for item in plan.range_items:
            v = item_vals[item.key][gidx, sidx]
            p = item_present[item.key][gidx, sidx]
            out_cols[item.key] = Col(v, None if p.all() else p)

        nrows = len(gidx)
        gsrc = DictSource(out_cols, nrows)
        if plan.having is not None:
            cond = eval_expr(plan.having, gsrc)
            hmask = cond.values.astype(bool) & cond.valid_mask
            out_cols = {
                k: Col(c.values[hmask],
                       None if c.validity is None else c.validity[hmask])
                for k, c in out_cols.items()
            }
            nrows = int(hmask.sum())
            gsrc = DictSource(out_cols, nrows)
        cols = [eval_expr(e, gsrc) for e, _ in plan.post_items]
        if plan.distinct:
            didx = _distinct_indices(cols)
            cols = _slice_result(cols, didx)
            out_cols = {
                k: Col(c.values[didx],
                       None if c.validity is None else c.validity[didx])
                for k, c in out_cols.items()
            }
            nrows = len(didx)
            gsrc = DictSource(out_cols, nrows)
        if not plan.order_by:
            # rows were constructed in (ts, group keys) order already
            off = plan.offset or 0
            if off or plan.limit is not None:
                end = None if plan.limit is None else off + plan.limit
                cols = _slice_result(cols, slice(off, end))
        else:
            cols = self._order_limit(plan, cols, names, extra_src=gsrc)
        types = {}
        if plan.ts_out_name:
            for (e, nm) in plan.post_items:
                if isinstance(e, A.Column) and e.name == "__ts":
                    types[nm] = ts_type
        return QueryResult(names, cols, types)

    def _range_item(self, item, src, gid, g, ts, align, align_to,
                    j_first, n_steps):
        """One `agg(x) RANGE 'r'` item -> (vals, present) shaped
        (g, n_steps). Partial per-bucket states at res = gcd(align, range),
        then sparse-table window combine."""
        res = int(np.gcd(align, item.range_ms))
        w = item.range_ms // res          # window width in buckets
        stride = align // res             # step stride in buckets
        t0 = align_to + j_first * align   # first window start
        nb = (n_steps - 1) * stride + w   # buckets covering all windows
        bucket = (ts - t0) // res
        in_range = (bucket >= 0) & (bucket < nb)

        if item.arg is not None:
            c = eval_expr(item.arg, src)
            vals = c.values.astype(np.float64, copy=False)
            valid = c.valid_mask & in_range
        else:
            vals = None
            valid = in_range.copy()

        seg = gid * nb + np.clip(bucket, 0, nb - 1)
        nseg = g * nb
        sid = src.rows.sid if src.rows is not None else None
        state = _bucket_partials(item.op, vals, valid, seg, nseg, ts, item.q,
                                 sid=sid)
        state = {k: v.reshape(g, nb) for k, v in state.items()}
        combined = _window_combine(item.op, state, w)
        # sample window starts at stride offsets
        starts = (np.arange(n_steps) * stride).astype(np.int64)
        sampled = {k: v[:, starts] for k, v in combined.items()}
        return _finalize_window(item.op, sampled, item.q)


# ----------------------------------------------------------------------
# range window machinery
# ----------------------------------------------------------------------

def _bucket_partials(op, vals, valid, seg, nseg, ts, q, *, sid=None):
    """Associative partial state per (group, bucket)."""
    cnt = np.bincount(seg[valid], minlength=nseg).astype(np.float64)
    if op in ("count",):
        return {"n": cnt}
    if vals is None:
        raise PlanError(f"{op} needs an argument")
    vm = np.where(valid, vals, 0.0)
    if op in ("sum", "mean"):
        s = np.bincount(seg, weights=vm, minlength=nseg)
        return {"s": s, "n": cnt}
    if op in ("min", "max"):
        fill = np.inf if op == "min" else -np.inf
        m = np.full(nseg, fill)
        (np.minimum if op == "min" else np.maximum).at(m, seg[valid], vals[valid])
        return {"m": m, "n": cnt}
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        s = np.bincount(seg, weights=vm, minlength=nseg)
        s2 = np.bincount(seg, weights=vm * vm, minlength=nseg)
        return {"s": s, "s2": s2, "n": cnt}
    if op in ("first_value", "last_value"):
        # deterministic tie-break: (ts, sid) lexicographic — last = max ts
        # then max sid, first = min ts then min sid. Identical on the
        # device grid path (device_range._fold_groups), independent of
        # scan order.
        idx = np.arange(len(seg))
        tiebreak = sid if sid is not None else idx
        order = np.lexsort((tiebreak, ts))
        order = order[valid[order]]
        v_last = np.zeros(nseg)
        t_last = np.full(nseg, -(2**62), np.int64)
        v_last[seg[order]] = vals[order]
        t_last[seg[order]] = ts[order]
        v_first = np.zeros(nseg)
        t_first = np.full(nseg, 2**62, np.int64)
        ro = order[::-1]
        v_first[seg[ro]] = vals[ro]
        t_first[seg[ro]] = ts[ro]
        return {"vl": v_last, "tl": t_last.astype(np.float64),
                "vf": v_first, "tf": t_first.astype(np.float64), "n": cnt}
    raise UnsupportedError(f"RANGE aggregate: {op}")


def _combine_states(op, a: dict, b: dict) -> dict:
    """b is the later window half."""
    if op == "count":
        return {"n": a["n"] + b["n"]}
    if op in ("sum", "mean"):
        return {"s": a["s"] + b["s"], "n": a["n"] + b["n"]}
    if op in ("min", "max"):
        f = np.minimum if op == "min" else np.maximum
        return {"m": f(a["m"], b["m"]), "n": a["n"] + b["n"]}
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        return {"s": a["s"] + b["s"], "s2": a["s2"] + b["s2"],
                "n": a["n"] + b["n"]}
    if op in ("first_value", "last_value"):
        pick_b_last = b["tl"] > a["tl"]
        pick_a_first = a["tf"] <= b["tf"]
        return {
            "vl": np.where(pick_b_last, b["vl"], a["vl"]),
            "tl": np.maximum(a["tl"], b["tl"]),
            "vf": np.where(pick_a_first, a["vf"], b["vf"]),
            "tf": np.minimum(a["tf"], b["tf"]),
            "n": a["n"] + b["n"],
        }
    raise UnsupportedError(op)


def _shift_left(state: dict, k: int, op) -> dict:
    """State array shifted left by k buckets (identity-padded)."""
    out = {}
    for key, v in state.items():
        pad_shape = list(v.shape)
        pad_shape[1] = k
        if key == "m":
            fill = np.inf if op == "min" else -np.inf
        elif key == "tl":
            fill = -(2.0**62)
        elif key == "tf":
            fill = 2.0**62
        else:
            fill = 0.0
        pad = np.full(pad_shape, fill)
        out[key] = np.concatenate([v[:, k:], pad], axis=1)
    return out


def _window_combine(op, state: dict, w: int) -> dict:
    """Sliding combine over w consecutive buckets via stride doubling:
    result[:, i] = combine(buckets i .. i+w-1)."""
    if w == 1:
        return state
    # sparse table: level sizes are powers of two
    levels = []
    size = 1
    cur = state
    while size < w:
        nxt = _combine_states(op, cur, _shift_left(cur, size, op))
        levels.append((size * 2, nxt))
        cur = nxt
        size *= 2
    # decompose w into binary, combining from offset 0
    result = None
    offset = 0
    remaining = w
    tables = {1: state}
    for sz, st in levels:
        tables[sz] = st
    bit = 1
    parts = []
    while remaining:
        if remaining & bit:
            parts.append((offset, bit))
            offset += bit
            remaining &= ~bit
        bit <<= 1
    for off, sz in parts:
        st = tables[sz]
        piece = _shift_left(st, off, op) if off else st
        result = piece if result is None else _combine_states(op, result, piece)
    return result


def _finalize_window(op, state: dict, q):
    n = state["n"]
    present = n > 0
    if op == "count":
        return n, present
    if op == "sum":
        return np.where(present, state["s"], 0.0), present
    if op == "mean":
        return state["s"] / np.maximum(n, 1), present
    if op in ("min", "max"):
        return np.where(present, state["m"], 0.0), present
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        ddof = 1 if op.endswith("_samp") else 0
        mean = state["s"] / np.maximum(n, 1)
        var = state["s2"] / np.maximum(n, 1) - mean * mean
        var = np.maximum(var, 0.0)
        if ddof:
            var = var * n / np.maximum(n - 1, 1)
            present = n > 1
        if op.startswith("stddev"):
            return np.sqrt(var), present
        return var, present
    if op == "last_value":
        return np.where(present, state["vl"], 0.0), present
    if op == "first_value":
        return np.where(present, state["vf"], 0.0), present
    raise UnsupportedError(op)


def _apply_fill(vals, present, fill, step_ts):
    """FILL NULL|PREV|LINEAR|<const> along the step axis per group
    (reference: src/query/src/range_select/plan.rs fill semantics)."""
    if fill is None or fill == "null":
        return vals, present
    if fill == "prev":
        g, s = vals.shape
        idx = np.where(present, np.arange(s)[None, :], -1)
        idx = np.maximum.accumulate(idx, axis=1)
        ok = idx >= 0
        safe = np.maximum(idx, 0)
        out = np.take_along_axis(vals, safe, axis=1)
        return np.where(ok, out, 0.0), ok
    if fill == "linear":
        g, s = vals.shape
        out = vals.copy()
        ok = present.copy()
        x = np.arange(s, dtype=np.float64)
        for gi in range(g):
            p = present[gi]
            if p.sum() >= 2:
                out[gi] = np.interp(x, x[p], vals[gi][p])
                ok[gi] = True
            # fewer than 2 points: leave as-is (cannot interpolate)
        return out, ok
    try:
        const = float(fill)
    except ValueError:
        raise PlanError(f"unknown FILL: {fill}") from None
    return np.where(present, vals, const), np.ones_like(present, dtype=bool)
