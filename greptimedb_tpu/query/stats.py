"""Per-query execution statistics for EXPLAIN ANALYZE.

Capability counterpart of the reference's analyze plan + merge-scan
metrics (/root/reference/src/query/src/analyze.rs DistAnalyzeExec,
src/query/src/dist_plan/merge_scan.rs:262-276 ready_time/first_consume/
finish_time per partition): execution sites record stage metrics into a
context-local collector; EXPLAIN ANALYZE activates it around the query
and renders one line per stage.

Collection is contextvar-based so concurrent server threads never mix
stats, and every record() call is a no-op when no collector is active
(zero overhead on the hot path beyond one ContextVar.get)."""

from __future__ import annotations

import contextlib
import contextvars
import time

_current: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_exec_stats", default=None
)


class ExecStats:
    def __init__(self):
        self.counters: dict[str, float] = {}
        self.notes: dict[str, str] = {}

    def add(self, key: str, n: float = 1):
        self.counters[key] = self.counters.get(key, 0) + n

    def note(self, key: str, value: str):
        self.notes[key] = value

    def lines(self) -> list[str]:
        out = []
        for k in sorted(set(self.counters) | set(self.notes)):
            if k in self.notes:
                out.append(f"    {k}: {self.notes[k]}")
            else:
                v = self.counters[k]
                s = f"{v:.3f}" if isinstance(v, float) and v % 1 else str(int(v))
                out.append(f"    {k}: {s}")
        return out


@contextlib.contextmanager
def collect():
    stats = ExecStats()
    token = _current.set(stats)
    try:
        yield stats
    finally:
        _current.reset(token)


def active() -> ExecStats | None:
    return _current.get()


def add(key: str, n: float = 1):
    s = _current.get()
    if s is not None:
        s.add(key, n)


def note(key: str, value: str):
    s = _current.get()
    if s is not None:
        s.note(key, value)


@contextlib.contextmanager
def timed(key: str):
    """Accumulate wall ms under `key` (no-op when not collecting)."""
    s = _current.get()
    if s is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        s.add(key, (time.perf_counter() - t0) * 1000.0)
