"""SQL window functions over the host row source.

Capability counterpart of the reference's DataFusion window execution
(/root/reference/src/query/ executes OVER() through DataFusion's
WindowAggExec; sqlness window cases under tests/cases/standalone/common/).

Semantics implemented (SQL default frames):
- no ORDER BY in the spec  -> whole-partition value broadcast
- ORDER BY present         -> RANGE UNBOUNDED PRECEDING..CURRENT ROW
  (running aggregate; peer rows — ties on the order keys — share the
  frame end, so they share the value)
- explicit frames: "... UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING"
  (whole partition), "RANGE ... UNBOUNDED PRECEDING AND CURRENT ROW"
  (peer-shared running), "ROWS ... UNBOUNDED PRECEDING AND CURRENT ROW"
  (strictly per-row running); anything else raises.

Ranking (row_number/rank/dense_rank) and offset (lag/lead,
first_value/last_value) functions follow the standard definitions.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.errors import PlanError, UnsupportedError
from greptimedb_tpu.program_cache import ProgramCache
from greptimedb_tpu.query.expr import Col, eval_expr
from greptimedb_tpu.sql import ast as A

WINDOW_ONLY_FUNCS = {
    "row_number", "rank", "dense_rank", "ntile", "lag", "lead",
    "first_value", "last_value", "nth_value", "percent_rank",
    "cume_dist",
}
_AGG_OVER = {"sum", "count", "avg", "mean", "min", "max"}


def collect_window_calls(e: A.Expr, out: list | None = None) -> list:
    """All FuncCall nodes with an OVER clause, in depth-first order."""
    if out is None:
        out = []
    if isinstance(e, A.FuncCall):
        if e.over is not None:
            out.append(e)
        for a in e.args:
            collect_window_calls(a, out)
    elif isinstance(e, A.BinaryOp):
        collect_window_calls(e.left, out)
        collect_window_calls(e.right, out)
    elif isinstance(e, (A.UnaryOp, A.Cast)):
        collect_window_calls(e.operand, out)
    elif isinstance(e, A.Between):
        for x in (e.operand, e.low, e.high):
            collect_window_calls(x, out)
    elif isinstance(e, A.InList):
        collect_window_calls(e.operand, out)
        for x in e.items:
            collect_window_calls(x, out)
    elif isinstance(e, A.IsNull):
        collect_window_calls(e.operand, out)
    elif isinstance(e, A.Case):
        if e.operand:
            collect_window_calls(e.operand, out)
        for c, t in e.whens:
            collect_window_calls(c, out)
            collect_window_calls(t, out)
        if e.else_:
            collect_window_calls(e.else_, out)
    return out


def replace_window_calls(e: A.Expr, mapping: dict) -> A.Expr:
    """Structurally replace window FuncCalls (by identity) with Columns."""
    if id(e) in mapping:
        return A.Column(mapping[id(e)])
    if isinstance(e, A.FuncCall):
        return A.FuncCall(
            e.name, [replace_window_calls(a, mapping) for a in e.args],
            distinct=e.distinct, order_by=e.order_by,
        )
    if isinstance(e, A.BinaryOp):
        return A.BinaryOp(e.op, replace_window_calls(e.left, mapping),
                          replace_window_calls(e.right, mapping))
    if isinstance(e, A.UnaryOp):
        return A.UnaryOp(e.op, replace_window_calls(e.operand, mapping))
    if isinstance(e, A.Cast):
        return A.Cast(replace_window_calls(e.operand, mapping), e.to)
    if isinstance(e, A.Between):
        return A.Between(replace_window_calls(e.operand, mapping),
                         replace_window_calls(e.low, mapping),
                         replace_window_calls(e.high, mapping),
                         e.negated)
    if isinstance(e, A.InList):
        return A.InList(replace_window_calls(e.operand, mapping),
                        [replace_window_calls(x, mapping) for x in e.items],
                        e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(replace_window_calls(e.operand, mapping),
                        e.negated)
    if isinstance(e, A.Case):
        return A.Case(
            replace_window_calls(e.operand, mapping) if e.operand else None,
            [(replace_window_calls(c, mapping),
              replace_window_calls(t, mapping)) for c, t in e.whens],
            replace_window_calls(e.else_, mapping) if e.else_ else None,
        )
    return e


def _frame_mode(spec: A.WindowSpec) -> tuple[str, int | None]:
    """-> (mode, k): 'running' (RANGE: peers share the frame end) |
    'running_rows' (ROWS: strictly per-row) | 'whole' |
    'rows_pre' (ROWS BETWEEN k PRECEDING AND CURRENT ROW, k in slot)."""
    if spec.frame is None:
        return ("running" if spec.order_by else "whole"), None
    text = spec.frame.upper()
    body = text.split("BETWEEN", 1)[-1].strip()
    if body == "UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING":
        return "whole", None
    if body == "UNBOUNDED PRECEDING AND CURRENT ROW":
        if not spec.order_by:
            return "whole", None
        return ("running_rows" if text.startswith("ROWS")
                else "running"), None
    import re as _re

    m = _re.fullmatch(r"(\d+)\s+PRECEDING\s+AND\s+CURRENT\s+ROW", body)
    if m and text.startswith("ROWS"):
        return "rows_pre", int(m.group(1))
    if "BETWEEN" not in text:
        # shorthand: 'ROWS k PRECEDING' == BETWEEN k PRECEDING AND
        # CURRENT ROW (SQL standard default frame end). Without BETWEEN
        # the split above kept the ROWS/RANGE keyword — strip it.
        short = _re.sub(r"^(ROWS|RANGE)\s+", "", body)
        m = _re.fullmatch(r"(\d+)\s+PRECEDING", short)
        if m and text.startswith("ROWS"):
            return "rows_pre", int(m.group(1))
        if short == "UNBOUNDED PRECEDING":
            if not spec.order_by:
                return "whole", None
            return ("running_rows" if text.startswith("ROWS")
                    else "running"), None
    raise UnsupportedError(f"window frame not supported: {spec.frame}")


def _key_codes(col: Col) -> np.ndarray:
    """Column -> dense int codes (nulls get their own code)."""
    vals = col.values
    if vals.dtype == object:
        vals = np.asarray([str(v) for v in vals], object)
    _, codes = np.unique(vals, return_inverse=True)
    if col.validity is not None:
        codes = np.where(col.valid_mask, codes, -1)
    return codes.astype(np.int64)


def eval_window(fc: A.FuncCall, src) -> Col:
    """Evaluate one window call over the full row source."""
    spec = fc.over
    n = src.num_rows
    if n == 0:
        return Col(np.zeros(0))
    mode, frame_k = _frame_mode(spec)

    # ---- partition ids + intra-partition order ------------------------
    part_keys = [_key_codes(eval_expr(p, src)) for p in spec.partition_by]
    if part_keys:
        stacked = np.stack(part_keys, axis=1)
        _, pid = np.unique(stacked, axis=0, return_inverse=True)
    else:
        pid = np.zeros(n, np.int64)

    order_cols = [eval_expr(o.expr, src) for o in spec.order_by]
    from greptimedb_tpu.query.executor import _sort_indices

    # partition most-significant, then the ORDER BY keys with SQL null
    # placement; _sort_indices' lexsort is stable, so equal keys keep
    # row order (deterministic)
    order = _sort_indices(
        order_cols,
        [o.asc for o in spec.order_by],
        [o.nulls_first for o in spec.order_by],
        primary=pid,
    )
    # positions: order[i] = original row index of the i-th ordered row
    opid = pid[order]
    part_start = np.zeros(n, dtype=bool)
    part_start[0] = True
    part_start[1:] = opid[1:] != opid[:-1]

    # peer boundaries: a change in any order key OR its null-ness
    if order_cols:
        peer_start = part_start.copy()
        for col in order_cols:
            codes = np.where(col.valid_mask, _sortable(col), 0)[order]
            nulls = (~col.valid_mask)[order]
            peer_start[1:] |= (codes[1:] != codes[:-1]) | (
                nulls[1:] != nulls[:-1]
            )
    else:
        peer_start = part_start.copy()

    out_ordered, validity_ordered = _dispatch(
        fc, src, mode, order, part_start, peer_start, n,
        frame_k=frame_k,
    )
    inv = np.empty(n, np.int64)
    inv[order] = np.arange(n)
    values = out_ordered[inv]
    validity = None if validity_ordered is None else validity_ordered[inv]
    return Col(values, validity)


def _sortable(col: Col) -> np.ndarray:
    """Order-preserving codes for peer detection. Integers stay int64
    (a float cast would merge distinct keys above 2^53)."""
    vals = col.values
    if vals.dtype == object:
        _, codes = np.unique(
            np.asarray([str(v) for v in vals], object), return_inverse=True
        )
        return codes.astype(np.int64)
    if vals.dtype == np.bool_ or vals.dtype.kind in "iu":
        return vals.astype(np.int64)
    return vals


def _partition_index(part_start: np.ndarray) -> np.ndarray:
    """ordered-position -> index within its partition (0-based)."""
    n = len(part_start)
    idx = np.arange(n)
    start_idx = np.maximum.accumulate(np.where(part_start, idx, 0))
    return idx - start_idx


def _dispatch(fc, src, mode, order, part_start, peer_start, n, *,
              frame_k: int | None = None):
    name = fc.name
    within = _partition_index(part_start)
    part_id = np.cumsum(part_start) - 1

    if name == "row_number":
        return within + 1, None
    if name in ("rank", "dense_rank", "percent_rank", "cume_dist"):
        peer_id = np.cumsum(peer_start) - 1
        # rank: 1 + number of rows before the peer group, per partition
        first_of_peer = np.where(peer_start)[0]
        rank_at_peer = within[first_of_peer] + 1
        rank = rank_at_peer[peer_id]
        if name == "rank":
            return rank, None
        if name == "dense_rank":
            # peer index minus the partition's first peer index, +1
            # (peer_id is nondecreasing, so a running max of the values
            # pinned at partition starts broadcasts each partition's
            # first peer id)
            part_first_peer = np.maximum.accumulate(
                np.where(part_start, peer_id, 0)
            )
            return peer_id - part_first_peer + 1, None
        part_sizes = np.bincount(part_id, minlength=int(part_id.max()) + 1)
        size = part_sizes[part_id].astype(np.float64)
        if name == "percent_rank":
            return np.where(size > 1, (rank - 1) / np.maximum(size - 1, 1),
                            0.0), None
        # cume_dist: peers count to the END of the peer group
        peer_id2 = np.cumsum(peer_start) - 1
        last_of_peer = np.zeros(int(peer_id2.max()) + 1, np.int64)
        np.maximum.at(last_of_peer, peer_id2, within)
        return (last_of_peer[peer_id2] + 1) / size, None

    if name == "ntile":
        if not fc.args:
            raise PlanError("ntile(k) needs an argument")
        from greptimedb_tpu.query.expr import eval_const

        k = int(eval_const(fc.args[0]))
        if k <= 0:
            raise PlanError("ntile(k): k must be positive")
        part_sizes = np.bincount(part_id, minlength=int(part_id.max()) + 1)
        size = part_sizes[part_id]
        return (within * k // np.maximum(size, 1)) + 1, None

    if name in ("lag", "lead"):
        col = eval_expr(fc.args[0], src)
        offset = 1
        default = None
        if len(fc.args) > 1:
            from greptimedb_tpu.query.expr import eval_const

            offset = int(eval_const(fc.args[1]))
        if len(fc.args) > 2:
            from greptimedb_tpu.query.expr import eval_const

            default = eval_const(fc.args[2])
        vals = col.values[order]
        valid = col.valid_mask[order]
        shift = offset if name == "lag" else -offset
        out = np.empty_like(vals)
        ok = np.zeros(n, dtype=bool)
        idx = np.arange(n)
        src_idx = idx - shift
        in_range = (src_idx >= 0) & (src_idx < n)
        same_part = np.zeros(n, dtype=bool)
        part_id_arr = part_id
        sel = in_range.copy()
        sel[in_range] = (
            part_id_arr[src_idx[in_range]] == part_id_arr[idx[in_range]]
        )
        out[sel] = vals[src_idx[sel]]
        ok[sel] = valid[src_idx[sel]]
        if default is not None:
            fillable = ~sel
            if vals.dtype == object:
                out[fillable] = str(default)
            else:
                out[fillable] = default
            ok[fillable] = True
        return out, ok

    if name in ("first_value", "last_value", "nth_value"):
        col = eval_expr(fc.args[0], src)
        vals = col.values[order]
        valid = col.valid_mask[order]
        first_pos = np.maximum.accumulate(
            np.where(part_start, np.arange(n), 0)
        )
        if mode == "rows_pre":
            # frame = [max(i - k, partition start), i]
            fs = np.maximum(np.arange(n) - frame_k, first_pos)
            if name == "first_value":
                return vals[fs], valid[fs]
            if name == "last_value":
                return vals, valid
            from greptimedb_tpu.query.expr import eval_const

            k2 = int(eval_const(fc.args[1])) - 1
            # membership BEFORE clamping: a frame with < N rows is NULL
            ok = (fs + k2) <= np.arange(n)
            pos = np.minimum(fs + k2, n - 1)
            return vals[pos], ok & valid[pos]
        if name == "first_value":
            return vals[first_pos], valid[first_pos]
        if name == "nth_value":
            from greptimedb_tpu.query.expr import eval_const

            k = int(eval_const(fc.args[1])) - 1
            pos = np.minimum(first_pos + k, n - 1)
            within_arr = _partition_index(part_start)
            if mode in ("running", "running_rows"):
                # NULL until the frame has reached the k-th row
                ok = within_arr >= k
            else:
                part_sizes = np.bincount(
                    part_id, minlength=int(part_id.max()) + 1
                )
                ok = part_sizes[part_id] > k
            return vals[pos], ok & valid[pos]
        if mode == "running_rows":
            # ROWS frame: the frame ends exactly at the current row
            return vals, valid
        # last_value: running frame -> end of the current PEER group
        # (ties on the order keys share the frame end); whole ->
        # partition last
        if mode == "running":
            peer_id = np.cumsum(peer_start) - 1
            last_of_peer = np.zeros(int(peer_id.max()) + 1, np.int64)
            np.maximum.at(last_of_peer, peer_id, np.arange(n))
            pos = last_of_peer[peer_id]
            return vals[pos], valid[pos]
        last_pos = _part_last(part_start, n)
        return vals[last_pos], valid[last_pos]

    if name in _AGG_OVER:
        if name == "count" and (
            not fc.args or isinstance(fc.args[0], A.Star)
        ):
            col = Col(np.ones(n, np.int64))
        else:
            col = eval_expr(fc.args[0], src)
        vals = col.values[order]
        valid = col.valid_mask[order]
        return _agg_over(name, vals, valid, mode, part_start, peer_start,
                         part_id, n, frame_k=frame_k)

    raise UnsupportedError(f"window function {name!r} not supported")


def _part_last(part_start: np.ndarray, n: int) -> np.ndarray:
    """ordered-position -> position of the LAST row of its partition."""
    ends = np.empty(n, np.int64)
    starts = np.where(part_start)[0]
    bounds = np.append(starts[1:], n) - 1
    ends[:] = np.repeat(bounds, np.diff(np.append(starts, n)))
    return ends


# rows at/above this run the running scans on the device (segmented
# associative scans, ops/segment.py); below it host numpy wins on
# dispatch latency
DEVICE_THRESHOLD = 262_144


def _x64_enabled() -> bool:
    import jax

    try:
        return bool(jax.config.read("jax_enable_x64"))
    except Exception:  # noqa: BLE001 - config API drift
        return False


def _split_two_float(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f64 -> (hi, lo) f32 pair with hi + lo == x to f32-pair precision.
    Non-finite values keep hi and a zero low part (inf - inf is NaN)."""
    hi = x.astype(np.float32)
    with np.errstate(invalid="ignore"):
        lo = np.where(
            np.isfinite(hi), x - hi.astype(np.float64), 0.0
        ).astype(np.float32)
    return hi, lo


def _running_scans(numeric, cnt, valid, part_start, name, n):
    """(run_sum, run_cnt, run_minmax|None, path) — running aggregates
    within partitions, on device for large inputs.

    Without x64 (the real-TPU configuration) the device path runs
    Neumaier-compensated / two-float f32 segmented scans
    (ops/segment.py) instead of falling back to host numpy: sums carry a
    compensation slot, min/max compare (hi, lo) pairs, counts are exact
    int32 — results match the host f64 path to ~1 ulp (VERDICT r4 #5;
    the flow engine's device_state.py proved the pattern)."""
    from greptimedb_tpu.query import stats

    want_mm = name in ("min", "max")
    use_device = n >= DEVICE_THRESHOLD
    x64 = _x64_enabled() if use_device else False
    if use_device and not x64:
        # no-x64 guard: every input finite (inf would make the combine's
        # error term inf - inf = NaN; NaN inputs stay host because the
        # host path's global-cumsum NaN smear is the comparison
        # baseline) AND no possible f32 overflow of any running sum
        # (bounded by n * max|value|)
        max_abs = float(np.abs(numeric).max()) if n else 0.0
        use_device = (bool(np.isfinite(numeric).all())
                      and n * max_abs < 3.0e38)
    if use_device:
        import jax.numpy as jnp

        from greptimedb_tpu.ops import segment as S

        masked = None
        if want_mm:
            masked = np.where(valid, numeric,
                              -np.inf if name == "max" else np.inf)
        with stats.timed("window_device_ms"):
            d_reset = jnp.asarray(part_start)
            if x64:
                run_sum = np.asarray(S.segmented_cumsum(
                    jnp.asarray(numeric, jnp.float64), d_reset
                ))
                run_cnt = np.asarray(S.segmented_cumsum(
                    # this branch only runs with x64 enabled (the
                    # `if x64` guard above), so int64 is exact here
                    jnp.asarray(cnt, jnp.int64), d_reset  # gtlint: disable=GT009
                ))
                run_mm = None
                if want_mm:
                    run_mm = np.asarray(S.segmented_cumextreme(
                        jnp.asarray(masked, jnp.float64), d_reset,
                        take_max=name == "max",
                    ))
            else:
                v_hi, v_lo = _split_two_float(numeric)
                packed = np.asarray(S.segmented_cumsum_compensated_packed(
                    jnp.asarray(v_hi), jnp.asarray(v_lo), d_reset
                ), np.float64)
                run_sum = packed[0] + packed[1]
                # row counts fit int32 exactly (n < 2^31)
                run_cnt = np.asarray(S.segmented_cumsum(
                    jnp.asarray(cnt, jnp.int32), d_reset
                )).astype(np.int64)
                run_mm = None
                if want_mm:
                    m_hi, m_lo = _split_two_float(masked)
                    h, low = S.segmented_cumextreme2(
                        jnp.asarray(m_hi), jnp.asarray(m_lo), d_reset,
                        take_max=name == "max",
                    )
                    run_mm = (np.asarray(h, np.float64)
                              + np.asarray(low, np.float64))
        stats.note("exec_path_window", "device")
        return run_sum, run_cnt, run_mm, "device"
    csum = np.cumsum(numeric)
    ccnt = np.cumsum(cnt)
    starts = np.where(part_start)[0]
    base_sum = np.repeat(
        np.append(0.0, csum[starts[1:] - 1]),
        np.diff(np.append(starts, n)),
    )
    base_cnt = np.repeat(
        np.append(0, ccnt[starts[1:] - 1]),
        np.diff(np.append(starts, n)),
    )
    run_mm = None
    if want_mm:
        masked = np.where(valid, numeric,
                          -np.inf if name == "max" else np.inf)
        op = np.maximum if name == "max" else np.minimum
        run_mm = np.empty(n)
        for s, e in zip(starts, np.append(starts[1:], n)):
            run_mm[s:e] = op.accumulate(masked[s:e])
    stats.note("exec_path_window", "host")
    return csum - base_sum, ccnt - base_cnt, run_mm, "host"


def _agg_over(name, vals, valid, mode, part_start, peer_start, part_id, n,
              *, frame_k: int | None = None):
    numeric = np.where(valid, vals.astype(np.float64, copy=False), 0.0) \
        if vals.dtype != object else None
    if numeric is None:
        raise PlanError(f"{name}() over string column")
    cnt = valid.astype(np.int64)
    if mode == "whole":
        nparts = int(part_id.max()) + 1
        if name in ("sum", "avg", "mean", "count"):
            s = np.bincount(part_id, weights=numeric, minlength=nparts)
            c = np.bincount(part_id, weights=cnt, minlength=nparts)
            if name == "count":
                return c[part_id].astype(np.int64), None
            out = s[part_id]
            if name in ("avg", "mean"):
                out = out / np.maximum(c[part_id], 1)
            return out, (c[part_id] > 0)
        red = np.full(nparts, -np.inf if name == "max" else np.inf)
        op = np.maximum if name == "max" else np.minimum
        masked = np.where(valid, numeric,
                          -np.inf if name == "max" else np.inf)
        getattr(op, "at")(red, part_id, masked)
        c = np.bincount(part_id, weights=cnt, minlength=nparts)
        return red[part_id], (c[part_id] > 0)
    if mode == "rows_pre":
        return _agg_rows_pre(name, numeric, cnt, valid, part_start, n,
                             frame_k)
    # running: cumulative within partition, then peers share the value at
    # the END of their peer group (SQL default RANGE frame)
    run_sum, run_cnt, run_mm, _path = _running_scans(
        numeric, cnt, valid, part_start, name, n
    )
    if name in ("min", "max"):
        run = run_mm
    elif name == "count":
        run = run_cnt
    elif name in ("avg", "mean"):
        run = run_sum / np.maximum(run_cnt, 1)
    else:
        run = run_sum
    if mode == "running_rows":
        # ROWS frame: strictly per-row, no peer sharing
        if name == "count":
            return run_cnt.astype(np.int64), None
        return run, (run_cnt > 0)
    # peers share the frame end: broadcast the value at each peer
    # group's last row back over the group
    peer_id = np.cumsum(peer_start) - 1
    npeers = int(peer_id.max()) + 1
    last_of_peer = np.zeros(npeers, np.int64)
    np.maximum.at(last_of_peer, peer_id, np.arange(n))
    run = run[last_of_peer[peer_id]]
    run_cnt_b = run_cnt[last_of_peer[peer_id]]
    if name == "count":
        return run.astype(np.int64), None
    return run, (run_cnt_b > 0)


# compiled halo-window programs, keyed (mesh, k)
_HALO_PROGRAMS = ProgramCache(
    lambda key: _rows_pre_halo_program(*key), cap=8
)
_ROWS_PRE_MAX_HALO = 4096  # halo cells shipped per shard boundary


def _rows_pre_halo_program(mesh, k: int):
    """shard_map sliding-frame program: rows sharded over AXIS_SHARD,
    each shard prepends the previous shard's k-row tail (halo_prev_1d)
    so frames crossing the shard boundary stay local, then computes the
    frame sum/count by local f64 prefix-sum difference. The halo is the
    only cross-device traffic — one (k,) ppermute per input."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.dist import halo_prev_1d
    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    @jax.jit
    def program(x, cnt, fs):
        def local(x, cnt, fs):
            n_loc = x.shape[0]
            base = jax.lax.axis_index(AXIS_SHARD) * n_loc
            cx = jnp.cumsum(halo_prev_1d(x, k, fill=0.0))
            cc = jnp.cumsum(halo_prev_1d(cnt, k, fill=0.0))
            end = jnp.arange(n_loc, dtype=jnp.int32) + k
            # frame start in halo'd coords; the first shard's halo is
            # zero-filled and fs >= 0, so it never leaks into a frame
            rel = jnp.clip(fs - base + k, 0, end)
            w_sum = cx[end] - jnp.where(rel > 0, cx[rel - 1], 0.0)
            w_cnt = cc[end] - jnp.where(rel > 0, cc[rel - 1], 0.0)
            return w_sum, w_cnt

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS_SHARD), P(AXIS_SHARD), P(AXIS_SHARD)),
            out_specs=(P(AXIS_SHARD), P(AXIS_SHARD)),
            check_rep=False,
        )(x, cnt, fs)

    return program


def _rows_pre_sharded(name, numeric, cnt, fs, n, k: int):
    """Mesh path for ROWS k PRECEDING sum/count/avg, or None when the
    process-wide mesh / query shape doesn't qualify."""
    if name not in ("sum", "avg", "mean", "count"):
        return None
    if k < 1 or k > _ROWS_PRE_MAX_HALO or not _x64_enabled():
        return None
    from greptimedb_tpu.parallel.mesh import (
        AXIS_SHARD, global_mesh, global_mesh_opts, shard_count,
    )
    from greptimedb_tpu.query import planner, stats

    mesh = global_mesh()
    ns = shard_count(mesh)
    if ns <= 1:
        return None
    if n < DEVICE_THRESHOLD:
        # below the device-execution floor the host path wins regardless
        # of the operator's shard threshold
        return None
    if not np.isfinite(numeric).all():
        # non-finite values stay on the host baseline: its global-cumsum
        # NaN/inf smear is the established comparison semantics (same
        # guard as _running_scans' no-x64 path), while per-shard cumsums
        # would localize the smear to one shard
        return None
    dec = planner.decide_mesh_execution(
        mesh, kind="window", rows=n, opts=global_mesh_opts(),
    )
    planner.record_mesh_decision(dec, "window")
    if not dec.shard:
        return None
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_pad = -(-n // ns) * ns
    pad = n_pad - n
    x = np.pad(numeric, (0, pad))
    c = np.pad(cnt.astype(np.float64), (0, pad))
    # padded rows: empty frame (fs == own index -> w spans one 0 cell)
    fs_p = np.pad(fs, (0, pad), constant_values=0).astype(np.int32)
    if pad:
        fs_p[n:] = np.arange(n, n_pad, dtype=np.int32)
    prog = _HALO_PROGRAMS.get((mesh, k))
    sh = NamedSharding(mesh, P(AXIS_SHARD))
    with stats.timed("window_device_ms"):
        w_sum, w_cnt = prog(
            jax.device_put(x, sh), jax.device_put(c, sh),
            jax.device_put(fs_p, sh),
        )
        w_sum = np.asarray(w_sum, np.float64)[:n]
        w_cnt = np.asarray(w_cnt, np.float64)[:n]
    stats.note("exec_path_window", "device_mesh")
    if name == "count":
        return w_cnt.astype(np.int64), None
    if name in ("avg", "mean"):
        return w_sum / np.maximum(w_cnt, 1), (w_cnt > 0)
    return w_sum, (w_cnt > 0)


def _agg_rows_pre(name, numeric, cnt, valid, part_start, n, k: int):
    """ROWS BETWEEN k PRECEDING AND CURRENT ROW: sliding frames via
    prefix-sum differences (sum/count/avg) or a windowed reduce
    (min/max); decomposable frames run row-sharded over the process-
    wide mesh (halo exchange covers frames crossing shard boundaries)."""
    start_idx = np.maximum.accumulate(
        np.where(part_start, np.arange(n), 0)
    )
    fs = np.maximum(np.arange(n) - k, start_idx)  # frame start
    sharded = _rows_pre_sharded(name, numeric, cnt, fs, n, k)
    if sharded is not None:
        return sharded
    if name in ("sum", "avg", "mean", "count"):
        csum = np.cumsum(numeric)
        ccnt = np.cumsum(cnt)
        # window = csum[i] - csum[fs-1] (fs==0 -> 0)
        prev = fs - 1
        base_s = np.where(prev >= 0, csum[np.maximum(prev, 0)], 0.0)
        base_c = np.where(prev >= 0, ccnt[np.maximum(prev, 0)], 0)
        w_sum = csum - base_s
        w_cnt = ccnt - base_c
        if name == "count":
            return w_cnt.astype(np.int64), None
        if name in ("avg", "mean"):
            return w_sum / np.maximum(w_cnt, 1), (w_cnt > 0)
        return w_sum, (w_cnt > 0)
    if name in ("min", "max"):
        ident = -np.inf if name == "max" else np.inf
        masked = np.where(valid, numeric, ident)
        # windowed reduce over k+1 trailing positions, partition-
        # clipped; processed in row chunks so peak memory is bounded at
        # chunk*(k+1) elements instead of n*(k+1)
        pad = np.concatenate([np.full(k, ident), masked])
        out = np.empty(n)
        chunk = max(1, (1 << 22) // (k + 1))
        offs = np.arange(-k, 1)[None, :]
        for s in range(0, n, chunk):
            e = min(s + chunk, n)
            win = np.lib.stride_tricks.sliding_window_view(
                pad[s:e + k], k + 1
            )
            rel = offs + np.arange(s, e)[:, None]
            w = np.where(rel >= fs[s:e, None], win, ident)
            out[s:e] = w.max(axis=1) if name == "max" else w.min(axis=1)
        # validity: any valid row inside the frame
        ccnt = np.cumsum(cnt)
        prev = fs - 1
        base_c = np.where(prev >= 0, ccnt[np.maximum(prev, 0)], 0)
        return out, (ccnt - base_c > 0)
    raise UnsupportedError(
        f"{name}() with a ROWS k PRECEDING frame is not supported"
    )
