"""Device-resident RANGE execution: the fused SQL->TPU hot path.

This is the point where the SQL engine and the device kernels meet: RANGE
queries (`agg(x) RANGE 'r' ... ALIGN 'a' BY (tags)`) lower onto
device-resident (series x time-cell) partial-state grids instead of the
host NumPy bucket machinery in executor.py.

Capability counterpart of the reference's RangeSelect physical plan + mito
scan with its page cache hot
(/root/reference/src/query/src/range_select/plan.rs:368-446,
src/mito2/src/read/scan_region.rs:59): where the reference streams
row groups out of the page cache into per-window accumulators on the CPU,
here the working set is pinned in HBM as dense per-cell aggregate states
and every query is one XLA program:

    cells (S, NB) --mask--> fold sids->groups --gather--> window combine
    (stride doubling, O(log W) passes) --strided sample--> finalize

Cache design:
- one `_Entry` per (table, resolution, phase); holds (S, NB) device arrays
  of per-cell partial aggregate states per field: {s, n, s2, mn, mx, vl/tl,
  vf/tf} built lazily for the ops seen, plus field-independent row-presence
  and per-cell ts min/max for exact window math;
- cell resolution = gcd(align, range, data interval) when affordable, so
  the grid *is* the data for regular series (one sample per cell) and the
  per-query device reduction does the real work;
- entries are invalidated by Table.data_version (every write/truncate bumps
  it) — the page-cache-invalidation analog;
- partial states compose exactly, so results are identical to the host path
  up to f32 accumulation (the device stays in f32/int32: no x64 on TPU).

The executor falls back to the host path whenever a query shape is not
expressible over cell partials (residual row filters, non-cell-aligned time
bounds, expression-valued aggregate args, quantiles).
"""

from __future__ import annotations

import functools
import logging
import math
import threading

from dataclasses import dataclass, field as dc_field

import numpy as np

from greptimedb_tpu.errors import UnsupportedError
from greptimedb_tpu.program_cache import ProgramCache
from greptimedb_tpu.sql import ast as A

from greptimedb_tpu import concurrency

_log = logging.getLogger("greptimedb_tpu.query.device_range")

DEVICE_THRESHOLD = 262_144       # min table rows before the cache pays off
_CELL_CAP = 256 * 1024 * 1024    # max S*NB cells per cached array (1GB f32)
_MAX_ENTRIES = 8                 # LRU entry-count cap across all tables
_BYTE_BUDGET = 4 * 1024**3       # LRU byte cap across all cached entries

# Timestamps ride as exact (cell index, intra-cell ms offset) int32 pairs:
# cell < nb <= _CELL_CAP and intra < res < 2^31, so both halves are exact
# where a single int32/f32 tick would lose precision on long spans.
_I32_MAX = 2**31 - 1

_DEVICE_RANGE_OPS = {
    "count", "sum", "mean", "min", "max",
    "var_pop", "var_samp", "stddev_pop", "stddev_samp",
    "first_value", "last_value",
}

# build-state keys needed per op (field-level arrays, all (S, NB))
_STATE_KEYS = {
    "count": ("n",),
    "sum": ("s", "n"),
    "mean": ("s", "n"),
    "min": ("mn", "n"),
    "max": ("mx", "n"),
    "var_pop": ("s", "s2", "n"),
    "var_samp": ("s", "s2", "n"),
    "stddev_pop": ("s", "s2", "n"),
    "stddev_samp": ("s", "s2", "n"),
    # first/last carry both directions: the window combine picks winners
    # from either half, so it needs all four arrays regardless of which op
    # the query asked for (mirrors executor.py _bucket_partials).
    # "if"/"il" are the intra-cell ms offsets of the first/last row.
    "first_value": ("vf", "if", "vl", "il", "n"),
    "last_value": ("vf", "if", "vl", "il", "n"),
}


@dataclass
class _Entry:
    version: tuple
    res: int                     # cell width, ms
    phase: int                   # cell boundary phase: boundaries ≡ phase (mod res)
    t0c: int                     # absolute ms of cell 0's left edge
    nb: int                      # number of cells
    num_series: int
    registry: object             # SeriesRegistry of the building scan
    rows_scanned: int
    # field name -> state key -> device (S, NB) array
    fields: dict = dc_field(default_factory=dict)
    # field name -> True when all data + f32 partials are finite, so
    # presence can ride inside the value plane as NaN (halves the
    # device->host result payload)
    nan_ok: dict = dc_field(default_factory=dict)
    # field-independent: row presence / per-cell ts extremes (device)
    nrow: object = None          # (S, NB) int32 rows per cell (all rows)
    imin: object = None          # (S, NB) int32 intra-cell offset of min ts
    imax: object = None          # (S, NB) int32 intra-cell offset of max ts
    # memoized prelude results keyed by (matcher_sig, lo, hi)
    prelude: dict = dc_field(default_factory=dict)
    # memoized per-query-shape device args + group decode (steady-state
    # queries re-upload nothing)
    query_memo: dict = dc_field(default_factory=dict)
    # device bytes held; stored (not recomputed) so concurrent readers
    # never iterate `fields` while a grow mutates it
    nbytes: int = 0
    # serializes in-place growth (ensure_states) across query threads
    grow_lock: object = dc_field(default_factory=concurrency.Lock)
    # host-side grid arrays retained by build_entry(keep_host=True) until
    # persist_entry writes the restart snapshot
    host_snap: dict | None = None
    # fields whose "n" state IS entry.nrow (every row valid): stored and
    # transferred once, aliased everywhere else
    n_aliased: set = dc_field(default_factory=set)
    # static program specs this entry has executed (insertion-ordered:
    # dict keys); persisted so a restart can precompile them during
    # warm (cold-start killer)
    program_specs: dict = dc_field(default_factory=dict)

    def recount_bytes(self) -> int:
        per = self.num_series * self.nb * 4
        # count UNIQUE device arrays: "__rows__" and all-valid field "n"
        # states alias entry.nrow
        seen = {id(self.nrow), id(self.imin), id(self.imax)}
        n_arr = 3
        for d in self.fields.values():
            for arr in d.values():
                if id(arr) not in seen:
                    seen.add(id(arr))
                    n_arr += 1
        self.nbytes = per * n_arr
        return self.nbytes

    def bytes(self) -> int:
        return self.nbytes


class DeviceRangeCache:
    """LRU of device grid entries, shared by a QueryEngine.

    Budgeted two ways: entry count (_MAX_ENTRIES) and total device bytes
    across entries (_BYTE_BUDGET) — an entry holds 3 + sum-of-field-state
    arrays, so byte accounting (entry.bytes()), not array-element caps,
    bounds HBM use."""

    def __init__(self, byte_budget: int = _BYTE_BUDGET):
        self._entries: dict[tuple, _Entry] = {}
        self._lock = concurrency.Lock()
        self.byte_budget = byte_budget
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.register_pool(
            "range_grid", "device", self,
            stats=DeviceRangeCache._mem_stats,
            evict=DeviceRangeCache.evict_bytes,
            buffers=DeviceRangeCache._device_buffers,
        )

    def _release(self, entry: "_Entry"):
        """Drop the entry's session-resident result buffers with it
        (query/sessions.py): session keys embed id(entry), so a
        replaced/evicted grid entry's buffers could otherwise never be
        probed again — each (write, poll) cycle would strand one folded
        buffer per query shape until LRU byte pressure."""
        from greptimedb_tpu.query import sessions as _sessions

        self._evictions += 1
        _sessions.global_sessions.purge_table(("range", id(entry)))

    def lookup_compatible(self, tkey, version, r0: int, align_to: int
                          ) -> _Entry | None:
        """Find a live entry for `tkey` whose resolution serves a query
        with bucket gcd r0 and phase align_to. Evicts stale-version
        entries for the table; LRU-touches the hit."""
        with self._lock:
            for key in list(self._entries):
                if key[0] != tkey:
                    continue
                e = self._entries[key]
                if e.version != version:
                    del self._entries[key]
                    self._release(e)
                    continue
                if r0 % e.res == 0 and align_to % e.res == e.phase:
                    self._entries.pop(key)
                    self._entries[key] = e
                    self._hits += 1
                    return e
            self._misses += 1
        return None

    def insert(self, key: tuple, entry: _Entry):
        with self._lock:
            self._insert_locked(key, entry)
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.note_device_bytes()

    def _insert_locked(self, key: tuple, entry: _Entry):
        old = self._entries.pop(key, None)
        if old is not None and old is not entry:
            self._release(old)
        total = sum(e.bytes() for e in self._entries.values())
        total += entry.bytes()
        while self._entries and (
            len(self._entries) >= _MAX_ENTRIES
            or total > self.byte_budget
        ):
            victim = self._entries.pop(next(iter(self._entries)))
            self._release(victim)
            total -= victim.bytes()
        self._entries[key] = entry

    def has_table(self, tkey) -> bool:
        with self._lock:
            return any(k[0] == tkey for k in self._entries)

    def insert_if_table_absent(self, key: tuple, entry: _Entry) -> bool:
        """Insert unless ANY live entry exists for the same table —
        the warm thread must never clobber an entry a query built."""
        with self._lock:
            if any(k[0] == key[0] for k in self._entries):
                return False
            self._insert_locked(key, entry)
        # warm-start restores grow the pool like any query-path insert:
        # the global watermark applies from the first restored grid,
        # not from the first later query
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.note_device_bytes()
        return True

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.bytes() for e in self._entries.values())

    def reserve_growth(self, entry: _Entry, add: int) -> bool:
        """Admit an in-place entry growth of `add` bytes against the
        AGGREGATE budget, evicting other LRU entries if needed. False ->
        the growth cannot fit (caller falls back to host)."""
        with self._lock:
            if entry.bytes() + add > self.byte_budget:
                return False
            total = sum(e.bytes() for e in self._entries.values()) + add
            for key in list(self._entries):
                if total <= self.byte_budget:
                    break
                if self._entries[key] is entry:
                    continue
                victim = self._entries.pop(key)
                self._release(victim)
                total -= victim.bytes()
            return total <= self.byte_budget

    def clear(self):
        with self._lock:
            for e in self._entries.values():
                self._release(e)
            self._entries.clear()

    # ------------------------------------------------------------------
    # memory accountant surface (telemetry/memory.py)
    # ------------------------------------------------------------------
    def _mem_stats(self) -> dict:
        with self._lock:
            total = 0
            for e in self._entries.values():
                total += e.bytes()
                # per-query-shape gid/mask device inputs ride the
                # entry (query_memo) but are outside recount_bytes'
                # grid contract — the watermark must still see them
                # (the census enumerates the same arrays)
                for memo in list(e.query_memo.values()):
                    for k in ("gid", "mask"):
                        arr = memo.get(k)
                        if arr is not None:
                            total += int(getattr(arr, "nbytes", 0))
            return {
                "bytes": total,
                "entries": len(self._entries),
                "budget_bytes": self.byte_budget,
                "max_entries": _MAX_ENTRIES,
                "hits": self._hits, "misses": self._misses,
                "evictions": self._evictions,
            }

    def evict_bytes(self, target: int) -> int:
        """Shed LRU grid entries until `target` bytes are freed
        (cross-pool pressure from the global device watermark)."""
        freed = 0
        with self._lock:
            while freed < target and self._entries:
                key = next(iter(self._entries))
                victim = self._entries.pop(key)
                self._release(victim)
                freed += victim.bytes()
        return freed

    def _device_buffers(self):
        out = []
        with self._lock:
            for key, e in self._entries.items():
                tag = f"range:{key[0][0]}.{key[0][1]}"
                seen = set()
                for arr in (e.nrow, e.imin, e.imax):
                    if arr is not None and id(arr) not in seen:
                        seen.add(id(arr))
                        out.append((arr, tag))
                # list() snapshots: fields/query_memo grow under the
                # entry's grow_lock / query path, not this cache lock
                for fname, d in list(e.fields.items()):
                    for arr in list(d.values()):
                        if id(arr) not in seen:
                            seen.add(id(arr))
                            out.append((arr, f"{tag}:{fname}"))
                # per-query-shape device inputs (gid/mask uploads) the
                # steady state keeps resident — without owner tags the
                # census would read them as leaks
                for memo in list(e.query_memo.values()):
                    for k in ("gid", "mask"):
                        arr = memo.get(k)
                        if arr is not None and id(arr) not in seen:
                            seen.add(id(arr))
                            out.append((arr, f"{tag}:query_memo"))
        return out


# ----------------------------------------------------------------------
# eligibility
# ----------------------------------------------------------------------

def plan_lowering(plan, table):
    """Return (field per item, op per item) when `plan` can lower onto cell
    partials; None -> host path. Checks everything except cell alignment of
    time bounds (needs the entry's resolution, checked later)."""
    if plan.kind != "range":
        return None
    if plan.scan.residual is not None:
        return None
    items = []
    for it in plan.range_items:
        if it.op not in _DEVICE_RANGE_OPS:
            return None
        if it.arg is None:
            if it.op != "count":
                return None
            items.append(("__rows__", it.op))
            continue
        if not isinstance(it.arg, A.Column):
            return None
        cs = table.schema.maybe_column(it.arg.name)
        if cs is None or cs.is_tag or cs.is_time_index:
            return None
        if cs.data_type.is_string():
            return None
        items.append((it.arg.name, it.op))
    for k in plan.keys:
        if not (isinstance(k.expr, A.Column) and k.expr.name in table.tag_names):
            return None
    return items


# ----------------------------------------------------------------------
# cache build (host, vectorized over the sorted scan)
# ----------------------------------------------------------------------

def _is_sid_ts_sorted(sid: np.ndarray, ts: np.ndarray) -> bool:
    if len(sid) < 2:
        return True
    d_sid = np.diff(sid.astype(np.int64))
    return bool(np.all((d_sid > 0) | ((d_sid == 0) & (np.diff(ts) >= 0))))


def _pick_res(plan, ts: np.ndarray, num_series: int) -> int | None:
    r0 = plan.align_ms
    for it in plan.range_items:
        r0 = math.gcd(r0, it.range_ms)
    # estimate the data interval from time deltas (sorted by (sid, ts))
    if len(ts) > 1:
        d = np.diff(ts)
        pos = d[d > 0]
        if len(pos):
            res = math.gcd(r0, int(pos.min()))
            span = int(ts[-1]) - int(ts[0]) + res
            if num_series * (span // res + 1) <= _CELL_CAP:
                return res
    span = int(ts[-1]) - int(ts[0]) + r0 if len(ts) else r0
    if num_series * (span // r0 + 1) > _CELL_CAP:
        return None
    return r0


def _make_put(mesh):
    """Host->device placement: single-device jnp.asarray, or series-axis
    sharding over the mesh (SURVEY.md §2.7 #1 — the region-partitioning
    analog; XLA inserts the cross-shard collectives for group folds)."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        return jnp.asarray, jnp.asarray
    from jax.sharding import NamedSharding, PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    s2d = NamedSharding(mesh, P(AXIS_SHARD, None))
    s1d = NamedSharding(mesh, P(AXIS_SHARD))

    def put2(x):
        return jax.device_put(np.asarray(x), s2d)

    def put1(x):
        return jax.device_put(np.asarray(x), s1d)

    return put2, put1


def _series_pad(s: int, mesh) -> int:
    """Pad the series axis to the fold-block multiple (and the shard
    count): block boundaries are part of the numeric contract — the
    blocked group fold combines per-block f32 partials in one fixed
    order, so sharded and single-device entries of the same table get
    IDENTICAL block contents and bit-identical results."""
    from greptimedb_tpu.parallel.mesh import AXIS_SHARD, FOLD_BLOCKS

    mult = FOLD_BLOCKS
    if mesh is not None:
        n = mesh.shape[AXIS_SHARD]
        mult = mult * n // math.gcd(mult, n)
    return -(-s // mult) * mult


def build_entry(plan, table, items, mesh=None, mesh_opts=None,
                byte_budget: int = _BYTE_BUDGET,
                keep_host: bool = False) -> _Entry | None:
    """Scan the table once and build the device cell-state grids.

    With a mesh, the replicate-vs-shard planner decides placement from
    the series count: large grids get a series-axis NamedSharding (the
    shard_map range program recombines group folds with collectives),
    small ones stay single-device. keep_host=True additionally retains
    the host-side grid arrays on entry.host_snap so persist_entry can
    write a restart snapshot without a device readback."""
    import jax.numpy as jnp

    needed: dict[str, set] = {}
    for fname, op in items:
        if fname != "__rows__":
            needed.setdefault(fname, set()).update(_STATE_KEYS[op])
    # version BEFORE the scan: a write racing the build leaves the entry
    # stamped stale, so the next query rebuilds (conservative, never mixes)
    version = table.data_version()
    data = table.scan(field_names=sorted(needed))
    rows = data.rows
    if rows is None or len(rows) == 0:
        return None
    ts = rows.ts
    sid = rows.sid
    if not _is_sid_ts_sorted(sid, ts):
        order = np.lexsort((ts, sid))
        ts = ts[order]
        sid = sid[order]
        reorder = order
    else:
        reorder = None
    S = max(data.registry.num_series, int(sid.max()) + 1 if len(sid) else 1)
    decision = None
    if mesh is not None:
        from greptimedb_tpu.query.planner import decide_mesh_execution

        decision = decide_mesh_execution(
            mesh, kind="range", series=S, ops=[op for _, op in items],
            opts=mesh_opts,
        )
        if not decision.shard:
            mesh = None
    S = _series_pad(S, mesh)
    res = _pick_res(plan, ts, S)
    if res is None or res >= _I32_MAX:
        # res >= 2^31 ms (~25-day cells) would overflow the exact int32
        # intra-cell offsets; such queries fall back to the host path.
        return None
    phase = plan.align_to % res
    data_min = int(ts.min())
    data_max = int(ts.max())
    t0c = phase + ((data_min - phase) // res) * res
    nb = (data_max - t0c) // res + 1
    if S * nb > _CELL_CAP:
        return None
    # projected device bytes for the full entry must fit the cache budget
    n_arr = 3 + sum(len(k) for k in needed.values())
    if S * nb * 4 * n_arr > byte_budget:
        return None

    cell = (ts - t0c) // res
    seg = sid.astype(np.int64) * nb + cell
    nseg = S * nb
    # exact intra-cell ms offset (0 <= intra < res < 2^31)
    intra = (ts - t0c - cell * res).astype(np.int64)

    entry = _Entry(
        version=version, res=res, phase=phase, t0c=t0c, nb=nb,
        num_series=S, registry=data.registry,
        rows_scanned=len(rows),
    )
    entry.mesh = mesh
    entry.mesh_decision = decision
    snap = {} if keep_host else None
    put2, _ = _make_put(mesh)
    shape = (S, nb)
    nrow = np.bincount(seg, minlength=nseg)
    nrow = nrow.reshape(shape).astype(np.int32)
    if snap is not None:
        snap["nrow"] = nrow
    entry.nrow = put2(nrow)
    # per-cell ts extremes: rows are (sid, ts)-sorted, so each seg run's
    # first/last row give the extremes directly
    change = np.empty(len(seg), bool)
    if len(seg):
        change[0] = True
        change[1:] = seg[1:] != seg[:-1]
    starts = np.nonzero(change)[0]
    ends = np.r_[starts[1:], len(seg)] - 1
    useg = seg[starts]
    imin = np.zeros(nseg, np.int64)
    imax = np.zeros(nseg, np.int64)
    imin[useg] = intra[starts]
    imax[useg] = intra[ends]
    imin = imin.reshape(shape).astype(np.int32)
    imax = imax.reshape(shape).astype(np.int32)
    if snap is not None:
        snap["imin"] = imin
        snap["imax"] = imax
    entry.imin = put2(imin)
    entry.imax = put2(imax)

    for fname, keys in needed.items():
        vals = rows.fields[fname]
        if reorder is not None:
            vals = vals[reorder]
        vals = vals.astype(np.float64, copy=False)
        if rows.field_valid is not None and fname in rows.field_valid:
            valid = rows.field_valid[fname]
            if reorder is not None:
                valid = valid[reorder]
        else:
            valid = np.ones(len(vals), bool)
        states, nan_ok, n_aliased = _build_field_states(
            keys, vals, valid, seg, nseg, intra, shape, put2,
            snap=snap, snap_prefix=f"f::{fname}::",
            nrow_alias=entry.nrow,
        )
        entry.fields[fname] = states
        entry.nan_ok[fname] = nan_ok
        if n_aliased:
            entry.n_aliased.add(fname)
    _ensure_rows_pseudo(entry, items, jnp)
    entry.recount_bytes()
    if snap is not None:
        entry.host_snap = snap
    return entry


def _build_field_states(keys, vals, valid, seg, nseg, intra, shape, put,
                        snap=None, snap_prefix="", nrow_alias=None):
    out = {}

    def emit(key, arr):
        if snap is not None:
            snap[snap_prefix + key] = arr
        out[key] = put(arr)

    all_valid = valid.all()
    vm = vals if all_valid else np.where(valid, vals, 0.0)
    nan_ok = bool(np.isfinite(vm).all())
    n_aliased = False
    if all_valid and nrow_alias is not None:
        # every row carries this field: its per-cell count IS the row
        # count — alias the device array (no second build/transfer)
        out["n"] = nrow_alias
        n_aliased = True
    else:
        n = (np.bincount(seg, minlength=nseg) if all_valid
             else np.bincount(seg[valid], minlength=nseg))
        emit("n", n.reshape(shape).astype(np.int32))
    if "s" in keys:
        s = np.bincount(seg, weights=vm, minlength=nseg).astype(np.float32)
        nan_ok = nan_ok and bool(np.isfinite(s).all())
        emit("s", s.reshape(shape))
    if "s2" in keys:
        s2 = np.bincount(seg, weights=vm * vm, minlength=nseg).astype(
            np.float32
        )
        nan_ok = nan_ok and bool(np.isfinite(s2).all())
        emit("s2", s2.reshape(shape))
    if keys & {"mn", "mx", "vf", "if", "vl", "il"}:
        segf = seg if all_valid else seg[valid]
        vf_ = vals if all_valid else vals[valid]
        intraf = intra if all_valid else intra[valid]
        change = np.empty(len(segf), bool)
        if len(segf):
            change[0] = True
            change[1:] = segf[1:] != segf[:-1]
        starts = np.nonzero(change)[0]
        ends = np.r_[starts[1:], len(segf)] - 1
        useg = segf[starts]
        if "mn" in keys:
            arr = np.full(nseg, np.inf)
            if len(starts):
                arr[useg] = np.minimum.reduceat(vf_, starts)
            emit("mn", arr.reshape(shape).astype(np.float32))
        if "mx" in keys:
            arr = np.full(nseg, -np.inf)
            if len(starts):
                arr[useg] = np.maximum.reduceat(vf_, starts)
            emit("mx", arr.reshape(shape).astype(np.float32))
        if "vf" in keys:
            arr = np.zeros(nseg)
            t = np.zeros(nseg, np.int64)
            arr[useg] = vf_[starts]
            t[useg] = intraf[starts]
            emit("vf", arr.reshape(shape).astype(np.float32))
            emit("if", t.reshape(shape).astype(np.int32))
        if "vl" in keys:
            arr = np.zeros(nseg)
            t = np.zeros(nseg, np.int64)
            arr[useg] = vf_[ends]
            t[useg] = intraf[ends]
            emit("vl", arr.reshape(shape).astype(np.float32))
            emit("il", t.reshape(shape).astype(np.int32))
    return out, nan_ok, n_aliased


def _ensure_rows_pseudo(entry, items, jnp):
    if any(f == "__rows__" for f, _ in items):
        entry.fields.setdefault("__rows__", {})["n"] = entry.nrow


# ----------------------------------------------------------------------
# restart snapshots: the cold-start killer. A built entry's host-side
# grids persist under the region dir; reopening the table restores them
# with puts only (no SST scan, no host aggregation), and the persistent
# XLA compilation cache (instance.py) covers the compile. Analog of the
# reference keeping its page cache warm across queries — here made
# durable across process restarts.
# ----------------------------------------------------------------------

_SNAP_DIRNAME = "device_cache"
_snapshot_io_lock = concurrency.Lock()
# per-table restore serialization: the warm thread and a racing query
# must not both decode + device-transfer the same GB-scale snapshot
_restore_locks: dict = {}


def _restore_lock(tkey) -> threading.Lock:
    with _snapshot_io_lock:
        return _restore_locks.setdefault(tkey, concurrency.Lock())

def _ver_json(version) -> str:
    import json as _json

    def norm(v):
        if isinstance(v, (list, tuple)):
            return [norm(x) for x in v]
        return int(v) if isinstance(v, (bool, np.integer)) else v

    return _json.dumps(norm(version))


_SNAP_MAGIC = b"GTDEVC1\n"
_SNAP_ALIGN = 64


def persist_entry(entry: _Entry, table) -> bool:
    """Write the entry's host grids as a restart snapshot under the
    region dir (single-region tables only). Clears entry.host_snap.

    Format: magic + u64 json-meta length + meta + 64-aligned raw array
    bytes — flat on purpose, so load_entry_snapshot can memory-map each
    array and hand zero-copy views straight to the device put (no zip
    decode, no host-side copy of GB-scale grids)."""
    snap = entry.host_snap
    entry.host_snap = None
    if snap is None or len(table.regions) != 1:
        return False
    region = table.regions[0]
    import io
    import json as _json
    import os

    names = list(snap)
    layout = []
    off = 0
    for k in names:
        arr = np.ascontiguousarray(snap[k])
        snap[k] = arr
        pad = (-off) % _SNAP_ALIGN
        off += pad
        layout.append({
            "key": k, "dtype": arr.dtype.str, "shape": list(arr.shape),
            "offset": off, "nbytes": arr.nbytes,
        })
        off += arr.nbytes
    meta = {
        "version": _ver_json(entry.version),
        "res": entry.res, "phase": entry.phase, "t0c": entry.t0c,
        "nb": entry.nb, "num_series": entry.num_series,
        "rows_scanned": entry.rows_scanned,
        "nan_ok": {k: bool(v) for k, v in entry.nan_ok.items()},
        "n_alias": sorted(entry.n_aliased),
        "arrays": layout,
    }
    mb = _json.dumps(meta).encode()
    header = _SNAP_MAGIC + len(mb).to_bytes(8, "little") + mb
    data_start = len(header) + ((-len(header)) % _SNAP_ALIGN)

    def _stream(f):
        f.write(header)
        f.write(b"\x00" * (data_start - len(header)))
        pos = 0
        for k, ent in zip(names, layout):
            f.write(b"\x00" * (ent["offset"] - pos))
            f.write(memoryview(snap[k]).cast("B"))
            pos = ent["offset"] + ent["nbytes"]

    path = (f"{region.prefix}/{_SNAP_DIRNAME}/"
            f"grid_{entry.res}_{entry.phase}.gtdc")
    try:
        with _snapshot_io_lock:
            try:
                lp = region.store.local_path(path)
            except NotImplementedError:
                buf = io.BytesIO()
                _stream(buf)
                region.store.write(path, buf.getvalue())
            else:
                # stream straight to disk: snapshots can be ~GB-scale
                os.makedirs(os.path.dirname(lp), exist_ok=True)
                tmp = lp + ".tmp"
                with open(tmp, "wb") as f:
                    _stream(f)
                os.replace(tmp, lp)
        return True
    except Exception:
        return False


def _snap_open(region, path):
    """-> (meta, fetch(layout_entry) -> np view). Local files memory-map
    (zero host copies); object-store bytes slice via frombuffer."""
    import json as _json

    try:
        lp = region.store.local_read_path(path)
    except (NotImplementedError, FileNotFoundError, OSError):
        raw = region.store.read(path)
        if raw[:len(_SNAP_MAGIC)] != _SNAP_MAGIC:
            raise ValueError("bad snapshot magic")
        mlen = int.from_bytes(
            raw[len(_SNAP_MAGIC):len(_SNAP_MAGIC) + 8], "little"
        )
        hdr_end = len(_SNAP_MAGIC) + 8 + mlen
        meta = _json.loads(raw[len(_SNAP_MAGIC) + 8:hdr_end])
        data_start = hdr_end + ((-hdr_end) % _SNAP_ALIGN)

        def fetch(ent):
            return np.frombuffer(
                raw, np.dtype(ent["dtype"]),
                count=ent["nbytes"] // np.dtype(ent["dtype"]).itemsize,
                offset=data_start + ent["offset"],
            ).reshape(ent["shape"])

        return meta, fetch

    with open(lp, "rb") as f:
        magic = f.read(len(_SNAP_MAGIC))
        if magic != _SNAP_MAGIC:
            raise ValueError("bad snapshot magic")
        mlen = int.from_bytes(f.read(8), "little")
        meta = _json.loads(f.read(mlen))
    hdr_end = len(_SNAP_MAGIC) + 8 + mlen
    data_start = hdr_end + ((-hdr_end) % _SNAP_ALIGN)

    def fetch(ent):
        return np.memmap(
            lp, dtype=np.dtype(ent["dtype"]), mode="r",
            offset=data_start + ent["offset"],
            shape=tuple(ent["shape"]),
        )

    return meta, fetch


def load_entry_snapshot(table, r0: int, align_to: int, mesh=None,
                        mesh_opts=None,
                        byte_budget: int = _BYTE_BUDGET) -> _Entry | None:
    """Restore a compatible snapshot for the table's CURRENT data
    version, deleting stale snapshot files as they are found."""
    if len(table.regions) != 1:
        return None
    region = table.regions[0]
    prefix = f"{region.prefix}/{_SNAP_DIRNAME}/"

    # captured ONCE: the restored entry must be stamped with the version
    # that was validated, or a racing write could stamp it newer than the
    # grids really are (same discipline as build_entry's pre-scan stamp)
    version = table.data_version()
    cur_ver = _ver_json(version)
    with _snapshot_io_lock:
        metas = region.store.list(prefix)
    for m in metas:
        # cheap pre-filter: res/phase ride in the filename
        base = m.path.rsplit("/", 1)[-1]
        if base.startswith("grid_") and base.endswith(".gtdc"):
            try:
                _, res_s, phase_s = base[:-5].split("_")
                if (r0 % int(res_s) != 0
                        or align_to % int(res_s) != int(phase_s)):
                    continue
            except ValueError:
                pass
        try:
            with _snapshot_io_lock:
                meta, fetch = _snap_open(region, m.path)
        except Exception:
            region.store.delete(m.path)
            continue
        if meta["version"] != cur_ver:
            # stale: data changed since this snapshot was written
            region.store.delete(m.path)
            continue
        res, phase = meta["res"], meta["phase"]
        if r0 % res != 0 or align_to % res != phase:
            continue
        n_arr = len(meta["arrays"])
        if meta["num_series"] * meta["nb"] * 4 * n_arr > byte_budget:
            continue
        decision = None
        if mesh is not None:
            from greptimedb_tpu.query.planner import (
                decide_mesh_execution,
            )
            from greptimedb_tpu.parallel.mesh import AXIS_SHARD

            decision = decide_mesh_execution(
                mesh, kind="range", series=meta["num_series"],
                ops=(), opts=mesh_opts,
            )
            if decision.shard and meta["num_series"] % mesh.shape[
                    AXIS_SHARD]:
                # snapshots from an unpadded/unsharded build stay
                # single-device (the series axis must split evenly)
                from greptimedb_tpu.query.planner import MeshDecision

                decision = MeshDecision("replicate", "snapshot_unaligned",
                                        devices=decision.devices)
            if not decision.shard:
                mesh = None
        put2, _ = _make_put(mesh)
        entry = _Entry(
            version=version, res=res, phase=phase,
            t0c=meta["t0c"], nb=meta["nb"],
            num_series=meta["num_series"], registry=region.series,
            rows_scanned=meta["rows_scanned"],
        )
        entry.mesh = mesh
        entry.mesh_decision = decision
        by_key = {ent["key"]: ent for ent in meta["arrays"]}
        entry.nrow = put2(fetch(by_key["nrow"]))
        entry.imin = put2(fetch(by_key["imin"]))
        entry.imax = put2(fetch(by_key["imax"]))
        for key, ent in by_key.items():
            if not key.startswith("f::"):
                continue
            _, fname, skey = key.split("::", 2)
            entry.fields.setdefault(fname, {})[skey] = put2(fetch(ent))
        for fname in meta.get("n_alias", []):
            entry.fields.setdefault(fname, {})["n"] = entry.nrow
            entry.n_aliased.add(fname)
        for fname in entry.fields:
            entry.nan_ok[fname] = bool(meta["nan_ok"].get(fname, False))
        entry.recount_bytes()
        return entry
    return None


def _program_specs_path(entry: _Entry, region) -> str:
    return (f"{region.prefix}/{_SNAP_DIRNAME}/"
            f"programs_{entry.res}_{entry.phase}.json")


def _persist_program_specs(entry: _Entry, table) -> None:
    """Record the static jit specs this entry has served (capped), so a
    restarted process can precompile them during warm — the first query
    after restore then pays steady-state latency, not trace + XLA
    compile-cache load (VERDICT r3 cold-start task)."""
    if len(table.regions) != 1:
        return
    import json as _json

    region = table.regions[0]
    # most-RECENT 8 (insertion order): the specs a restart will actually
    # be asked for again
    specs = list(entry.program_specs)[-8:]
    doc = [
        {"stride": st, "n_steps": ns, "g": g, "fold": fo,
         "nanenc": ne, "items": [list(it) for it in items]}
        for st, ns, g, fo, ne, items in specs
    ]
    try:
        region.store.write(
            _program_specs_path(entry, region),
            _json.dumps(doc).encode(),
        )
    except Exception as e:  # noqa: BLE001
        # advisory warm-start metadata only; queries recompile lazily
        _log.debug("program-spec snapshot write skipped: %s", e)


class _WarmScratch:
    """Device buffers pinned by the warm-start precompile pass (the
    zero sid/mask spec inputs each persisted program is re-invoked
    with). They exist only while `precompile_programs` runs, but
    without an owner tag every warm restart would read as a transient
    device leak in the census — so they register as their own pool and
    drop when the pass finishes."""

    def __init__(self):
        self._lock = concurrency.Lock()
        self._bufs: dict[int, tuple] = {}   # id -> (arr, label)
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.register_pool(
            "warm_precompile", "device", self,
            stats=_WarmScratch._mem_stats,
            buffers=_WarmScratch._device_buffers,
        )

    def hold(self, arr, label: str):
        with self._lock:
            self._bufs[id(arr)] = (arr, label)
        return arr

    def drop(self, *arrs):
        with self._lock:
            for arr in arrs:
                self._bufs.pop(id(arr), None)

    def _mem_stats(self) -> dict:
        with self._lock:
            return {
                "bytes": sum(
                    int(getattr(a, "nbytes", 0))
                    for a, _ in self._bufs.values()
                ),
                "entries": len(self._bufs),
            }

    def _device_buffers(self):
        with self._lock:
            return [
                (a, f"warm_precompile:{label}")
                for a, label in self._bufs.values()
            ]


_WARM_SCRATCH = _WarmScratch()


def precompile_programs(entry: _Entry, table) -> int:
    """Re-invoke the range program for every persisted spec with the
    restored grids (values are irrelevant — static spec + array
    shapes/dtypes pin the XLA program), so the compilations land in the
    jit cache before the first real query. Returns programs compiled."""
    if len(table.regions) != 1:
        return 0
    import json as _json

    import jax.numpy as jnp

    region = table.regions[0]
    try:
        raw = region.store.read(_program_specs_path(entry, region))
        doc = _json.loads(raw)
    except Exception:  # noqa: BLE001 - no specs file: nothing to do
        return 0
    # the prelude program runs before every query; compile it too (the
    # matcher-less variant the flagship shape uses)
    try:
        run_prelude(entry, None, -(2**31) + 1, 2**31 - 1)
    except Exception as e:  # noqa: BLE001
        # warmup miss: the first real query compiles it instead
        _log.debug("prelude precompile skipped: %s", e)
    entry_mesh = getattr(entry, "mesh", None)
    _, put1 = _make_put(entry_mesh)
    # spec inputs shared by every precompile invocation below: pinned
    # (and owner-tagged) in the warm-scratch pool for the duration
    label = f"{table.info.database}.{table.info.name}"
    zero_sid = _WARM_SCRATCH.hold(
        put1(np.zeros(entry.num_series, np.int32)), label
    )
    ones_mask = _WARM_SCRATCH.hold(
        put1(np.ones(entry.num_series, bool)), label
    )
    try:
        return _precompile_loop(entry, doc, entry_mesh, zero_sid,
                                ones_mask, jnp)
    finally:
        _WARM_SCRATCH.drop(zero_sid, ones_mask)


def _precompile_loop(entry, doc, entry_mesh, zero_sid, ones_mask, jnp):
    done = 0
    for s in doc:
        try:
            items = tuple(
                (op, int(w), fname) for op, w, fname in s["items"]
            )
            arrs = {}
            usable = True
            for _op, _w, fname in items:
                if fname not in entry.fields:
                    usable = False
                    break
                d = arrs.setdefault(fname, {})
                for bk in _STATE_KEYS[_op]:
                    if bk not in entry.fields[fname]:
                        usable = False
                        break
                    d[bk] = entry.fields[fname][bk]
            if not usable:
                continue
            spec = (int(s["stride"]), int(s["n_steps"]), int(s["g"]),
                    bool(s["fold"]), bool(s["nanenc"]), items)
            # select the program the way execute_range_device will, so
            # the warm compile is the one that actually serves queries
            # (sharded entries use the shard_map twin except for
            # affordable blocked folds)
            program = get_program()
            prog_tag = "single" if entry_mesh is None else "auto_spmd"
            if entry_mesh is not None and (
                not spec[3]
                or _fold_blocks(spec[2], entry.nb,
                                entry.num_series) != 1
            ):
                program = get_sharded_program(entry_mesh)
                prog_tag = "sharded"
            # the warm dispatch rides the same device_call boundary
            # (same registry key as the query path) so the profiler
            # row attributes the compile to the program that will
            # serve queries
            from greptimedb_tpu.telemetry import device_trace

            with device_trace.device_call(
                    "range", key=("range", prog_tag, spec)) as dcall:
                out = dcall.run(
                    program,
                    arrs,
                    zero_sid,
                    ones_mask,
                    jnp.int32(0), jnp.int32(-(2**31) + 1),
                    jnp.int32(2**31 - 1),
                    spec=spec,
                )
                out.block_until_ready()
                dcall.executed()
            entry.program_specs[spec] = True
            done += 1
        except Exception:  # noqa: BLE001 - best-effort warm
            continue
    return done


def persist_entry_async(entry: _Entry, table) -> None:
    if entry.host_snap is None:
        return
    concurrency.Thread(
        target=persist_entry, args=(entry, table),
        daemon=True, name="device-cache-persist",
    ).start()


def force_resident(entry: _Entry) -> None:
    """Synchronously materialize every grid on device. Dispatch is async
    (and some attachments defer host->device until first use), so the
    warm thread forces the transfer HERE, off the query path: by the
    time a query arrives the grids are genuinely HBM-resident."""
    import jax
    import jax.numpy as jnp

    arrs = [entry.nrow, entry.imin, entry.imax]
    seen = {id(a) for a in arrs}
    for d in entry.fields.values():
        for a in d.values():
            if id(a) not in seen:
                seen.add(id(a))
                arrs.append(a)

    @jax.jit
    def touch(*xs):
        # FULL-array reductions: every element of every grid must be
        # materialized on device (an x[0,0] probe could let a lazy
        # attachment ship only the touched tiles)
        return sum(x.sum().astype(jnp.float32) for x in xs)

    from greptimedb_tpu.telemetry import device_trace

    # the warm materialization is a real dispatch (and the host->device
    # attachment it forces is real tunnel traffic): profile it like
    # every other program, keyed by the grid geometry
    with device_trace.device_call(
            "warm_touch",
            key=("warm_touch", tuple(tuple(a.shape) for a in arrs)),
    ) as dcall:
        dcall.transfer(
            sum(int(getattr(a, "nbytes", 0)) for a in arrs), "upload"
        )
        # float() is a real synchronization point (device->host
        # readback)
        float(dcall.run(touch, *arrs))
        dcall.executed()


def warm_from_snapshots(engine, catalog) -> int:
    """Restore every table's snapshot into the engine's range cache
    (called in a background thread at instance open). Returns the number
    of entries restored."""
    restored = 0
    for table in catalog.all_tables():
        try:
            db, name = table.info.database, table.info.name
            if len(table.regions) != 1:
                continue
            region = table.regions[0]
            if not region.store.list(f"{region.prefix}/{_SNAP_DIRNAME}/"):
                continue
            tkey = (db, name, id(table))
            cache: DeviceRangeCache = engine.range_cache
            with _restore_lock(tkey):
                if cache.has_table(tkey):
                    continue
                entry = _load_any_snapshot(table, engine)
                inserted = entry is not None and \
                    cache.insert_if_table_absent(
                        (tkey, entry.res, entry.phase), entry
                    )
            if inserted:
                force_resident(entry)
                precompile_programs(entry, table)
                restored += 1
        except Exception:
            continue
    return restored


def _load_any_snapshot(table, engine) -> _Entry | None:
    region = table.regions[0]
    prefix = f"{region.prefix}/{_SNAP_DIRNAME}/"
    for m in region.store.list(prefix):
        base = m.path.rsplit("/", 1)[-1]
        if not base.startswith("grid_") or not base.endswith(".gtdc"):
            continue
        try:
            _, res_s, phase_s = base[:-5].split("_")
            res, phase = int(res_s), int(phase_s)
        except ValueError:
            continue
        entry = load_entry_snapshot(
            table, r0=res, align_to=phase, mesh=getattr(engine, "mesh", None),
            mesh_opts=getattr(engine, "mesh_opts", None),
            byte_budget=engine.range_cache.byte_budget,
        )
        if entry is not None:
            return entry
    return None


def ensure_states(entry: _Entry, plan, table, items,
                  cache: "DeviceRangeCache | None" = None) -> bool:
    """Add any state arrays a new query needs that the entry lacks (same
    resolution/phase, different ops). Returns False if a rescan failed."""
    import jax.numpy as jnp

    if table.data_version() != entry.version:
        return False  # racing write; caller falls back / rebuilds later
    with entry.grow_lock:
        ok = _ensure_states_locked(entry, plan, table, items, cache, jnp)
    if ok:
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.note_device_bytes()
    return ok


def _ensure_states_locked(entry, plan, table, items, cache, jnp) -> bool:
    missing: dict[str, set] = {}
    for fname, op in items:
        if fname == "__rows__":
            _ensure_rows_pseudo(entry, items, jnp)
            continue
        have = entry.fields.get(fname, {})
        want = set(_STATE_KEYS[op]) - set(have)
        if want:
            missing.setdefault(fname, set()).update(want)
    if not missing:
        return True
    # growing the entry in place must respect the same AGGREGATE HBM
    # budget that gated its construction
    add = 0
    for fname, keys in missing.items():
        have = set(entry.fields.get(fname, {}))
        add += entry.num_series * entry.nb * 4 * len((keys | {"n"}) - have)
    if cache is not None and not cache.reserve_growth(entry, add):
        return False
    data = table.scan(field_names=sorted(missing))
    if table.data_version() != entry.version:
        # a write raced the rescan: the new states would include rows the
        # old states lack — refuse the mixed entry (caller falls back; the
        # next query rebuilds against the new version)
        return False
    rows = data.rows
    if rows is None:
        return False
    ts, sid = rows.ts, rows.sid
    order = None
    if not _is_sid_ts_sorted(sid, ts):
        order = np.lexsort((ts, sid))
        ts, sid = ts[order], sid[order]
    cell = (ts - entry.t0c) // entry.res
    seg = sid.astype(np.int64) * entry.nb + cell
    nseg = entry.num_series * entry.nb
    if len(cell) and (cell.min() < 0 or cell.max() >= entry.nb
                      or sid.max() >= entry.num_series):
        return False  # data changed shape under us; caller re-validates
    intra = (ts - entry.t0c - cell * entry.res).astype(np.int64)
    shape = (entry.num_series, entry.nb)
    for fname, keys in missing.items():
        vals = rows.fields[fname]
        valid = (rows.field_valid or {}).get(fname)
        if order is not None:
            vals = vals[order]
            valid = valid[order] if valid is not None else None
        if valid is None:
            valid = np.ones(len(vals), bool)
        put2, _ = _make_put(getattr(entry, "mesh", None))
        states, nan_ok, n_aliased = _build_field_states(
            keys | {"n"}, vals.astype(np.float64, copy=False), valid,
            seg, nseg, intra, shape, put2, nrow_alias=entry.nrow,
        )
        entry.fields.setdefault(fname, {}).update(states)
        entry.nan_ok[fname] = entry.nan_ok.get(fname, True) and nan_ok
        if n_aliased:
            entry.n_aliased.add(fname)
    entry.recount_bytes()
    return True


# ----------------------------------------------------------------------
# device programs
# ----------------------------------------------------------------------

def _prelude_program():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def prelude(nrow, imin, imax, sid_mask, lo, hi):
        nb = nrow.shape[1]
        cells = jnp.arange(nb, dtype=jnp.int32)
        cmask = (cells >= lo) & (cells < hi)
        act = (nrow > 0) & cmask[None, :] & sid_mask[:, None]
        sid_active = jnp.any(act, axis=1)
        colact = jnp.any(act, axis=0)
        big = jnp.int32(_I32_MAX)
        # global min ts lives in the first active cell (cells are
        # time-ordered), global max in the last: two exact int32 stages
        c_lo = jnp.min(jnp.where(colact, cells, big))
        c_hi = jnp.max(jnp.where(colact, cells, -1))
        i_lo = jnp.min(jnp.where(act & (cells[None, :] == c_lo), imin, big))
        i_hi = jnp.max(jnp.where(act & (cells[None, :] == c_hi), imax, -1))
        return sid_active, c_lo, i_lo, c_hi, i_hi

    return prelude


_PRELUDE = None


def _clamp_i32(v: int) -> int:
    """Cell bounds from WHERE ts can land arbitrarily far outside the
    grid; clamping both directions is lossless (comparisons only see
    cells in [0, nb))."""
    return max(-(2**31) + 1, min(int(v), 2**31 - 1))


def run_prelude(entry: _Entry, sid_mask: np.ndarray, lo: int, hi: int):
    """Exact (filtered ts_min, ts_max, active sids) from cell states —
    mirrors the host path's `rows.ts.min()/max()` window-math inputs.
    Memoized per (mask signature, bounds) on the entry."""
    global _PRELUDE
    key = (sid_mask.tobytes() if sid_mask is not None else None, lo, hi)
    hit = entry.prelude.get(key)
    if hit is not None:
        return hit
    if len(entry.prelude) >= 32:
        entry.prelude.pop(next(iter(entry.prelude)))
    import jax.numpy as jnp

    if _PRELUDE is None:
        _PRELUDE = _prelude_program()
    mask = (jnp.asarray(sid_mask) if sid_mask is not None
            else jnp.ones((entry.num_series,), bool))
    from greptimedb_tpu.telemetry import device_trace

    # the prelude runs before every device RANGE query; it registers
    # with the program profiler like every other dispatch (shape is
    # the program identity — one compiled prelude per grid geometry)
    from greptimedb_tpu.query import readback as _readback

    with device_trace.device_call(
            "range_prelude",
            key=("prelude", tuple(entry.nrow.shape))) as dcall:
        act_d, c_lo, i_lo, c_hi, i_hi = dcall.run(
            _PRELUDE, entry.nrow, entry.imin, entry.imax, mask,
            np.int32(_clamp_i32(lo)), np.int32(_clamp_i32(hi)),
        )
        act_d.block_until_ready()
        dcall.executed()
        # execute split from readback like every other site; the
        # active-sid mask crosses at the blessed readback boundary
        act = _readback.read_full(act_d)
        dcall.transfer(act.nbytes)
    if not act.any():
        out = (act, None, None)
    else:
        out = (
            act,
            entry.t0c + int(c_lo) * entry.res + int(i_lo),
            entry.t0c + int(c_hi) * entry.res + int(i_hi),
        )
    entry.prelude[key] = out
    return out


# jnp window-combine machinery (device mirror of executor.py's
# _combine_states/_shift_left/_window_combine/_finalize_window)

def _identity(key, op, jnp):
    if key == "mn" or (key == "m" and op == "min"):
        return jnp.inf
    if key == "mx" or (key == "m" and op == "max"):
        return -jnp.inf
    if key == "cl":
        return -1          # "no cell": loses every last-cell max
    if key == "cf":
        return _I32_MAX    # "no cell": loses every first-cell min
    if key in ("il", "if"):
        return 0           # intra offsets are tie-broken under cl/cf
    return 0.0


def _shift_left_j(state: dict, k: int, op, jnp):
    out = {}
    for key, v in state.items():
        pad = jnp.full(v.shape[:1] + (k,), _identity(key, op, jnp), v.dtype)
        out[key] = jnp.concatenate([v[:, k:], pad], axis=1)
    return out


def _combine_j(op, a: dict, b: dict, jnp):
    if op == "count":
        return {"n": a["n"] + b["n"]}
    if op in ("sum", "mean"):
        return {"s": a["s"] + b["s"], "n": a["n"] + b["n"]}
    if op == "min":
        return {"m": jnp.minimum(a["m"], b["m"]), "n": a["n"] + b["n"]}
    if op == "max":
        return {"m": jnp.maximum(a["m"], b["m"]), "n": a["n"] + b["n"]}
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        return {"s": a["s"] + b["s"], "s2": a["s2"] + b["s2"],
                "n": a["n"] + b["n"]}
    if op in ("first_value", "last_value"):
        # exact (cell, intra) lexicographic timestamp compare; within one
        # combine a and b come from distinct cells, so cl/cf ties only
        # happen between empty halves (where the value is irrelevant)
        pick_b_last = (b["cl"] > a["cl"]) | (
            (b["cl"] == a["cl"]) & (b["il"] > a["il"])
        )
        pick_a_first = (a["cf"] < b["cf"]) | (
            (a["cf"] == b["cf"]) & (a["if"] <= b["if"])
        )
        return {
            "vl": jnp.where(pick_b_last, b["vl"], a["vl"]),
            "il": jnp.where(pick_b_last, b["il"], a["il"]),
            "cl": jnp.maximum(a["cl"], b["cl"]),
            "vf": jnp.where(pick_a_first, a["vf"], b["vf"]),
            "if": jnp.where(pick_a_first, a["if"], b["if"]),
            "cf": jnp.minimum(a["cf"], b["cf"]),
            "n": a["n"] + b["n"],
        }
    raise UnsupportedError(op)


def _window_combine_j(op, state: dict, w: int, jnp):
    if w == 1:
        return state
    levels = []
    size = 1
    cur = state
    while size < w:
        nxt = _combine_j(op, cur, _shift_left_j(cur, size, op, jnp), jnp)
        levels.append((size * 2, nxt))
        cur = nxt
        size *= 2
    tables = {1: state}
    for sz, st in levels:
        tables[sz] = st
    result = None
    offset = 0
    remaining = w
    bit = 1
    parts = []
    while remaining:
        if remaining & bit:
            parts.append((offset, bit))
            offset += bit
            remaining &= ~bit
        bit <<= 1
    for off, sz in parts:
        st = tables[sz]
        piece = _shift_left_j(st, off, op, jnp) if off else st
        result = piece if result is None else _combine_j(op, result, piece, jnp)
    return result


def _finalize_j(op, state: dict, jnp):
    n = state["n"].astype(jnp.float32)
    present = state["n"] > 0
    if op == "count":
        return n, present
    if op == "sum":
        return jnp.where(present, state["s"], 0.0), present
    if op == "mean":
        return state["s"] / jnp.maximum(n, 1), present
    if op in ("min", "max"):
        return jnp.where(present, state["m"], 0.0), present
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        ddof = 1 if op.endswith("_samp") else 0
        mean = state["s"] / jnp.maximum(n, 1)
        var = jnp.maximum(state["s2"] / jnp.maximum(n, 1) - mean * mean, 0.0)
        if ddof:
            var = var * n / jnp.maximum(n - 1, 1)
            present = state["n"] > 1
        if op.startswith("stddev"):
            return jnp.sqrt(var), present
        return var, present
    if op == "last_value":
        return jnp.where(present, state["vl"], 0.0), present
    if op == "first_value":
        return jnp.where(present, state["vf"], 0.0), present
    raise UnsupportedError(op)


def _fold_blocks(g: int, nb: int, s: int) -> int:
    """Series-block count for the group fold. FOLD_BLOCKS when the
    (blocks, g, nb) partial tensor is affordable and the series axis is
    block-aligned; 1 degenerates to the direct fold (sharded execution
    then stays on the auto-SPMD program — see execute_range_device)."""
    from greptimedb_tpu.parallel.mesh import FOLD_BLOCKS

    if s % FOLD_BLOCKS == 0 and FOLD_BLOCKS * g * nb <= 256_000_000:
        return FOLD_BLOCKS
    return 1


def _fold_groups(op, state, gid, g, jnp, ctx):
    """Fold per-series cell states into per-group states.

    n/s/s2 fold through FOLD_BLOCKS aligned series blocks combined in
    one fixed left-fold order (bit-identical across mesh sizes); min/max
    are exactly associative and recombine with pmin/pmax; first/last
    winners resolve by exact (ts, sid) staged selection and a masked
    sum extraction (adding zeros never perturbs the winner value)."""
    import jax

    out = {}
    s_total = state["n"].shape[0] * ctx.shards
    nb = state["n"].shape[1]
    fb = _fold_blocks(g, nb, s_total)
    fb_local = fb // ctx.shards if fb >= ctx.shards else 1
    s_local = state["n"].shape[0]

    def blocked_sum(arr):
        if fb == 1:
            return ctx.psum(
                jax.ops.segment_sum(arr, gid, num_segments=g)
            )
        per = s_local // fb_local
        bid = jnp.arange(s_local, dtype=jnp.int32) // jnp.int32(per)
        seg = jnp.where(gid < g, bid * jnp.int32(g) + gid,
                        jnp.int32(fb_local * g))
        p = jax.ops.segment_sum(arr, seg, num_segments=fb_local * g + 1)
        return ctx.fold_blocks(p[:-1].reshape(fb_local, g, nb))

    out["n"] = blocked_sum(state["n"])
    if "s" in state:
        out["s"] = blocked_sum(state["s"])
    if "s2" in state:
        out["s2"] = blocked_sum(state["s2"])
    if "m" in state:
        f = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        out["m"] = ctx.pext(
            f(state["m"], gid, num_segments=g), take_max=op != "min"
        )

    # first/last across sids within one cell: winner = (ts, sid)
    # lexicographic, matching the host path's deterministic rule
    # (max ts then max sid for last; min ts then min sid for first).
    # The winner is unique, so its value is extracted by a masked
    # segment_sum — exact for any float value incl. ±inf/NaN.
    def fold_extreme(v_arr, t_arr, pick_max):
        has = state["n"] > 0
        sid = (ctx.sid_base(s_local)
               + jnp.arange(s_local, dtype=jnp.int32))[:, None]
        seg_ext = jax.ops.segment_max if pick_max else jax.ops.segment_min
        t_id = -1 if pick_max else _I32_MAX
        t = jnp.where(has, t_arr, t_id)
        win_t = ctx.pext(seg_ext(t, gid, num_segments=g),
                         take_max=pick_max)
        tie = has & (t == win_t[gid])
        sid_w = ctx.pext(
            seg_ext(jnp.where(tie, sid, t_id), gid, num_segments=g),
            take_max=pick_max,
        )
        win = tie & (sid == sid_w[gid])
        v = ctx.psum(jax.ops.segment_sum(
            jnp.where(win, v_arr, 0.0), gid, num_segments=g
        ))
        return v, jnp.clip(win_t, 0, _I32_MAX - 1)

    if "il" in state:
        out["vl"], out["il"] = fold_extreme(
            state["vl"], state["il"], pick_max=True
        )
    if "if" in state:
        out["vf"], out["if"] = fold_extreme(
            state["vf"], state["if"], pick_max=False
        )
    return out


def _disjoint_reduce(op, state, n_steps, w, jnp):
    out = {}
    if op in ("first_value", "last_value"):
        G = state["n"].shape[0]
        n_r = state["n"].reshape(G, n_steps, w)
        has = n_r > 0
        pos = jnp.arange(w, dtype=jnp.int32)[None, None, :]
        # cells within a window carry distinct time ranges, so the
        # last/first present cell is the exact winner (no value ties)
        am_l = jnp.argmax(jnp.where(has, pos, -1), axis=2, keepdims=True)
        am_f = jnp.argmin(
            jnp.where(has, pos, _I32_MAX), axis=2, keepdims=True
        )
        for k, v in state.items():
            r = v.reshape(G, n_steps, w)
            if k == "n":
                out[k] = r.sum(axis=2)
            elif k in ("vl", "il", "cl"):
                out[k] = jnp.take_along_axis(r, am_l, axis=2)[..., 0]
            elif k in ("vf", "if", "cf"):
                out[k] = jnp.take_along_axis(r, am_f, axis=2)[..., 0]
        return out
    for k, v in state.items():
        r = v.reshape(v.shape[0], n_steps, w)
        if k in ("n", "s", "s2"):
            out[k] = r.sum(axis=2)
        elif k == "m":
            out[k] = (r.min(axis=2) if op == "min" else r.max(axis=2))
    return out


def _range_body(arrs, gid, sid_mask, delta, lo, hi, spec, ctx):
    """One RANGE query over (local) cell-state grids. spec =
    (stride, n_steps, g, fold, nanenc, items), items (op, w, field_key)
    — everything shape-determining static. Shared verbatim by the
    single-device program and each shard_map shard (the fold ctx is the
    only difference), so sharded == unsharded bit-for-bit."""
    import jax
    import jax.numpy as jnp

    stride, n_steps, g, fold, nanenc, items = spec
    vals_out = []
    pres_out = []
    nb = next(iter(next(iter(arrs.values())).values())).shape[1]
    cell_ids = jnp.arange(nb, dtype=jnp.int32)
    cmask = (cell_ids >= lo) & (cell_ids < hi)
    for op, w, fkey in items:
        raw = arrs[fkey]
        # map build-state keys to combine-state keys
        state = {}
        state["n"] = jnp.where(
            cmask[None, :] & sid_mask[:, None], raw["n"], 0
        )
        for bk, ck in (("s", "s"), ("s2", "s2"), ("mn", "m"), ("mx", "m"),
                       ("vl", "vl"), ("il", "il"), ("vf", "vf"),
                       ("if", "if")):
            if bk in raw and ck in _STATE_COMBINE.get(op, ()):
                ident = _identity(bk, op, jnp)
                v = raw[bk]
                if ck not in ("il", "if"):
                    v = v.astype(jnp.float32)
                state[ck] = jnp.where(
                    cmask[None, :] & sid_mask[:, None], v,
                    jnp.asarray(ident, v.dtype),
                )
        if fold:
            state = _fold_groups(op, state, gid, g, jnp, ctx)
        # gather the query's cell window: nb_q cells starting at delta
        nb_q = (n_steps - 1) * stride + w
        idx = delta + jnp.arange(nb_q, dtype=jnp.int32)
        okc = (idx >= 0) & (idx < nb)
        safe = jnp.clip(idx, 0, nb - 1)
        state = {
            k: jnp.where(
                okc[None, :], v[:, safe],
                jnp.asarray(_identity(_ck_to_bk(k, op), op, jnp), v.dtype),
            )
            for k, v in state.items()
        }
        if op in ("first_value", "last_value"):
            # cell keys for the lexicographic (cell, intra) ts compare;
            # window position is monotone in absolute cell index
            pres = state["n"] > 0
            pos = jnp.arange(nb_q, dtype=jnp.int32)[None, :]
            state["cl"] = jnp.where(pres, pos, -1)
            state["cf"] = jnp.where(pres, pos, _I32_MAX)
        if w == stride and nb_q == n_steps * w:
            # disjoint windows: reshape-reduce (the TSBS double-groupby
            # shape — rides dense reductions, no stride doubling)
            combined = _disjoint_reduce(op, state, n_steps, w, jnp)
        else:
            combined = _window_combine_j(op, state, w, jnp)
            combined = {
                k: jax.lax.slice_in_dim(v, 0, (n_steps - 1) * stride + 1,
                                        stride, axis=1)
                for k, v in combined.items()
            }
        v, p = _finalize_j(op, combined, jnp)
        if nanenc:
            # presence rides inside the value plane as NaN (data is
            # known all-finite): halves the result payload
            v = jnp.where(p, v, jnp.nan)
        vals_out.append(v.astype(jnp.float32))
        pres_out.append(p)
    # ONE output array -> one device->host transfer per query (each
    # readback is a full round trip on a remote-attached chip)
    if nanenc:
        return jnp.stack(vals_out)
    return jnp.concatenate(
        [jnp.stack(vals_out), jnp.stack(pres_out).astype(jnp.float32)],
        axis=0,
    )


def _make_range_program():
    import jax

    from greptimedb_tpu.parallel.dist import LocalFoldCtx

    @functools.partial(jax.jit, static_argnames=("spec",))
    def program(arrs, gid, sid_mask, delta, lo, hi, *, spec):
        return _range_body(arrs, gid, sid_mask, delta, lo, hi, spec,
                           LocalFoldCtx())

    return program


def _make_sharded_range_program(mesh, kernel: bool = False):
    """shard_map twin of the range program: grids series-sharded over
    AXIS_SHARD, each shard runs _range_body on its slice with the
    collective fold ctx. fold=True outputs replicate (the post-fold
    window combine is tiny and runs redundantly per shard); fold=False
    outputs stay series-sharded. kernel=True threads the Pallas ring
    fold ctx (parallel/kernels/ring_fold) instead of the gather_blocks
    collectives — same fold order, 2(ns-1) accumulator hops."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.dist import ShardFoldCtx
    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    ns = mesh.shape[AXIS_SHARD]

    @functools.partial(jax.jit, static_argnames=("spec",))
    def program(arrs, gid, sid_mask, delta, lo, hi, *, spec):
        fold = spec[3]
        arr_specs = jax.tree_util.tree_map(
            lambda _: P(AXIS_SHARD, None), arrs
        )
        if kernel:
            from greptimedb_tpu.parallel.kernels import RingFoldCtx

            ctx = RingFoldCtx(ns)
        else:
            ctx = ShardFoldCtx(ns)

        def local(arrs, gid, sid_mask, delta, lo, hi):
            return _range_body(arrs, gid, sid_mask, delta, lo, hi, spec,
                               ctx)

        return shard_map(
            local, mesh=mesh,
            in_specs=(arr_specs, P(AXIS_SHARD), P(AXIS_SHARD),
                      P(), P(), P()),
            out_specs=P() if fold else P(None, AXIS_SHARD, None),
            check_rep=False,
        )(arrs, gid, sid_mask, delta, lo, hi)

    return program


_SHARDED_RANGE = ProgramCache(_make_sharded_range_program)
_SHARDED_RANGE_PALLAS = ProgramCache(
    lambda mesh: _make_sharded_range_program(mesh, kernel=True)
)


def get_sharded_program(mesh, kernel: bool = False):
    if kernel:
        return _SHARDED_RANGE_PALLAS.get(mesh)
    return _SHARDED_RANGE.get(mesh)


_STATE_COMBINE = {
    "count": (),
    "sum": ("s",), "mean": ("s",),
    "min": ("m",), "max": ("m",),
    "var_pop": ("s", "s2"), "var_samp": ("s", "s2"),
    "stddev_pop": ("s", "s2"), "stddev_samp": ("s", "s2"),
    "first_value": ("vl", "il", "vf", "if"),
    "last_value": ("vl", "il", "vf", "if"),
}


def _ck_to_bk(ck: str, op: str) -> str:
    if ck == "m":
        return "mn" if op == "min" else "mx"
    return ck


_PROGRAM = None


def get_program():
    global _PROGRAM
    if _PROGRAM is None:
        _PROGRAM = _make_range_program()
    return _PROGRAM


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------

def _group_ids_from_sids(plan, registry, active: np.ndarray):
    """Per-sid group ids over the entry's series space. Returns
    (gid_full (S,) int32 with inactive sids routed past g, g, key_cols).
    Mirrors executor.QueryEngine._group_ids but derives groups from sids
    instead of rows (same decoded key values, possibly different group
    order — assembly sorts deterministically)."""
    from greptimedb_tpu.query.expr import Col

    S = len(active)
    act_idx = np.nonzero(active)[0]
    if not plan.keys:
        gid_full = np.full(S, 1, np.int32)
        gid_full[act_idx] = 0
        return gid_full, 1, {}
    code_cols = []
    vocabs = []
    cards = []
    for k in plan.keys:
        name = k.expr.name
        codes = registry.tag_codes(name).astype(np.int64)
        vocab = np.asarray(
            registry.dicts[registry.tag_names.index(name)].values,
            dtype=object,
        )
        code_cols.append(codes)
        vocabs.append(vocab)
        cards.append(max(len(vocab), 1))
    combined = code_cols[0].copy()
    for codes, card in zip(code_cols[1:], cards[1:]):
        combined = combined * card + codes
    uniq, inv = np.unique(combined[act_idx], return_inverse=True)
    g = len(uniq)
    gid_full = np.full(S, g, np.int32)
    gid_full[act_idx] = inv.astype(np.int32)
    key_cols = {}
    rem = uniq
    for i in range(len(code_cols) - 1, -1, -1):
        card = cards[i]
        code_i = rem % card
        rem = rem // card
        key_cols[plan.keys[i].key] = Col(vocabs[i][code_i])
    return gid_full, g, key_cols


def execute_range_device(engine, plan, table):
    """Try to run a RANGE plan on the device grid cache. Returns a
    QueryResult, or None to fall back to the host path."""
    if getattr(table, "remote", False):
        # distributed tables: rows live on datanode processes (each of
        # which runs its own device paths); the frontend merges on host
        return None
    items = plan_lowering(plan, table)
    if items is None:
        return None
    prefer = engine.prefer_device
    if prefer is False:
        return None
    if prefer is None and table.row_count() < DEVICE_THRESHOLD:
        return None

    import jax.numpy as jnp

    align = plan.align_ms
    if align is None or align <= 0:
        return None
    r0 = align
    for it in plan.range_items:
        r0 = math.gcd(r0, it.range_ms)

    from greptimedb_tpu.query import stats

    version = table.data_version()
    cache: DeviceRangeCache = engine.range_cache
    tkey = (table.info.database, table.info.name, id(table))
    entry = cache.lookup_compatible(tkey, version, r0, plan.align_to)
    hit_note = "hit"
    if entry is None and getattr(engine, "persist_device_cache", True):
        with stats.timed("grid_cache_restore_ms"), _restore_lock(tkey):
            # the warm thread may have restored while we waited
            entry = cache.lookup_compatible(tkey, version, r0,
                                            plan.align_to)
            if entry is None:
                entry = load_entry_snapshot(
                    table, r0, plan.align_to,
                    mesh=getattr(engine, "mesh", None),
                    mesh_opts=getattr(engine, "mesh_opts", None),
                    byte_budget=cache.byte_budget,
                )
                if entry is not None:
                    cache.insert((tkey, entry.res, entry.phase), entry)
                    hit_note = "miss(restored)"
    if entry is None:
        with stats.timed("grid_cache_build_ms"):
            entry = build_entry(
                plan, table, items,
                mesh=getattr(engine, "mesh", None),
                mesh_opts=getattr(engine, "mesh_opts", None),
                byte_budget=cache.byte_budget,
                keep_host=getattr(engine, "persist_device_cache", True),
            )
        if entry is None:
            return None
        stats.note("grid_cache", "miss(build)")
        cache.insert((tkey, entry.res, entry.phase), entry)
        persist_entry_async(entry, table)
    else:
        stats.note("grid_cache", hit_note)
        with stats.timed("grid_cache_ensure_ms"):
            ok = ensure_states(entry, plan, table, items, cache=cache)
        if not ok:
            return None
    stats.add("grid_cache_bytes", entry.bytes())
    if getattr(engine, "mesh", None) is not None:
        from greptimedb_tpu.query.planner import (
            MeshDecision, record_mesh_decision,
        )
        from greptimedb_tpu.parallel.mesh import shard_count

        dec = getattr(entry, "mesh_decision", None)
        if dec is None:
            dec = MeshDecision(
                "shard" if getattr(entry, "mesh", None) is not None
                else "replicate", "cached",
                devices=shard_count(engine.mesh),
            )
        record_mesh_decision(dec, "range")

    res = entry.res
    # WHERE ts bounds must land on cell edges or partials can't honor them
    s = plan.scan
    if s.ts_min is not None and (s.ts_min - entry.t0c) % res != 0:
        return None
    if s.ts_max is not None and (s.ts_max + 1 - entry.t0c) % res != 0:
        return None
    lo = ((s.ts_min - entry.t0c) // res if s.ts_min is not None
          else -(2**31) + 1)
    hi = ((s.ts_max + 1 - entry.t0c) // res if s.ts_max is not None
          else 2**31 - 1)

    names = [nm for _, nm in plan.post_items]
    empty = engine._empty_result(names)
    sid_mask = None
    mask_key = None
    from greptimedb_tpu.query.planner import record_scan_path

    if s.matchers:
        from greptimedb_tpu import index as _index

        record_scan_path(_index.enabled())
        sids = _index.match_sids(entry.registry, s.matchers)
        if len(sids) == 0:
            return empty
        sid_mask = np.zeros(entry.num_series, bool)
        sid_mask[sids[sids < entry.num_series]] = True
        # memo on the canonical matcher key + registry version instead
        # of hashing an O(num_series) mask per query
        mask_key = (_index.matcher_key(s.matchers),
                    entry.registry.version)
    else:
        record_scan_path(False)

    active, ts_min_f, ts_max_f = run_prelude(entry, sid_mask, lo, hi)
    if ts_min_f is None:
        return empty
    if plan.grid_ts_min is not None:
        # distributed fill-grid override (see dist/dist_query.py): use
        # the negotiated global extent so per-datanode grids match
        ts_min_f = plan.grid_ts_min
        ts_max_f = plan.grid_ts_max

    # window math — identical to the host path (executor._execute_range)
    align_to = plan.align_to % align if plan.align_to else 0
    max_range = max(r.range_ms for r in plan.range_items)
    j_first = -((-(ts_min_f - max_range + 1 - align_to)) // align)
    j_last = (ts_max_f - align_to) // align
    n_steps = int(j_last - j_first + 1)
    if n_steps <= 0:
        return empty
    stride = align // res
    t0q = align_to + j_first * align
    delta = (t0q - entry.t0c) // res
    if not (-(2**31) < delta < 2**31):
        return None  # query window absurdly far from the data grid
    lo_c = _clamp_i32(lo)
    hi_c = _clamp_i32(hi)

    memo_key = (
        mask_key,
        tuple(k.expr.name for k in plan.keys),
        delta, lo_c, hi_c,
    )
    uploaded_bytes = 0
    memo = entry.query_memo.get(memo_key)
    if memo is None:
        gid_full, g, key_cols = _group_ids_from_sids(
            plan, entry.registry, active
        )
        # identity grouping (each real series is its own group, padded
        # tail routed past g) needs no fold: the per-series state IS the
        # group state. num_series is FOLD_BLOCKS-padded, so compare the
        # real prefix, not the whole axis.
        fold = not (g <= entry.num_series
                    and np.array_equal(gid_full[:g], np.arange(g))
                    and (gid_full[g:] == g).all())
        _, put1 = _make_put(getattr(entry, "mesh", None))
        dmask = (put1(sid_mask & active) if sid_mask is not None
                 else put1(active))
        memo = {
            "gid": put1(gid_full), "mask": dmask, "g": g,
            "key_cols": key_cols, "fold": fold,
            "delta": jnp.int32(delta), "lo": jnp.int32(lo_c),
            "hi": jnp.int32(hi_c),
        }
        # host-side sizes as the upload proxy (the devices hold the
        # padded copies): per-query tunnel traffic for the trace span
        uploaded_bytes = int(gid_full.nbytes) + int(active.nbytes)
        if len(entry.query_memo) >= 32:
            entry.query_memo.pop(next(iter(entry.query_memo)))
        entry.query_memo[memo_key] = memo
    g = memo["g"]
    key_cols = memo["key_cols"]
    for item in plan.range_items:
        w_i = item.range_ms // res
        nb_i = (n_steps - 1) * (align // res) + w_i
        if g * nb_i > 256_000_000:
            return None
    step_ts = (align_to + (j_first + np.arange(n_steps)) * align).astype(
        np.int64
    )

    prog_items = tuple(
        (op, it.range_ms // res, fname)
        for (fname, op), it in zip(items, plan.range_items)
    )
    arrs = {}
    for fname, op in items:
        d = arrs.setdefault(fname, {})
        for bk in _STATE_KEYS[op]:
            d[bk] = entry.fields[fname][bk]
    nanenc = all(
        entry.nan_ok.get(fname, fname == "__rows__") for fname, _ in items
    )
    program = get_program()
    prog_tag = "single"
    comm_bytes = 0
    entry_mesh = getattr(entry, "mesh", None)
    if entry_mesh is not None:
        if (not memo["fold"]
                or _fold_blocks(g, entry.nb, entry.num_series) != 1):
            # explicit-collective shard_map program with the blocked
            # exact fold (bit-identical across mesh sizes)
            program = get_sharded_program(entry_mesh)
            prog_tag = "sharded"
            # kernel variant: same decision decide_mesh_execution
            # recorded at plan time (deterministic in the same inputs,
            # so no double count here)
            from greptimedb_tpu.query.planner import decide_kernel

            kern, _ = decide_kernel(
                "range", series=entry.num_series,
                opts=getattr(engine, "mesh_opts", None),
            )
            if kern == "pallas":
                program = get_sharded_program(entry_mesh, kernel=True)
                prog_tag = "sharded_pallas"
                from greptimedb_tpu.parallel.kernels.ring_fold import (
                    fold_comm_bytes,
                )
                from greptimedb_tpu.parallel.mesh import shard_count

                ns_ = shard_count(entry_mesh)
                for op_i, w_i, _f in prog_items:
                    nb_i = (n_steps - 1) * stride + w_i
                    planes = 1 + len(_STATE_COMBINE.get(op_i, ()))
                    comm_bytes += fold_comm_bytes(ns_, g, nb_i, planes)
        else:
            # oversized blocked fold (FOLD_BLOCKS*g*nb past the partial
            # budget): stays on the auto-SPMD jit program — still
            # sharded, but XLA picks the combine order, so this is a
            # DOCUMENTED bit-identity exception; surface it
            stats.note("mesh_fold_range", "auto_spmd(oversized_fold)")
            prog_tag = "auto_spmd"
    prog_spec = (stride, n_steps, g, memo["fold"], nanenc, prog_items)
    from greptimedb_tpu.query import readback, sessions
    from greptimedb_tpu.telemetry import device_trace

    # delta-poll cursor: j0 = first step whose __ts is past the
    # client's watermark. With FILL the full grid must assemble first
    # (PREV/LINEAR carry from pre-cursor steps), so the cursor moves
    # to cell emission; otherwise only delta steps cross the tunnel.
    since = sessions.current_since()
    has_fill = plan.fill is not None or any(
        r.fill is not None for r in plan.range_items
    )
    j0 = 0
    if since is not None and not has_fill:
        j0 = int(np.searchsorted(step_ts, since, side="right"))
        if j0 >= n_steps:
            return empty  # the client has every step already

    # persistent query session: the folded RESULT buffer of this exact
    # query shape stays HBM-resident across polls — a repeated
    # dashboard query skips the program dispatch round trip entirely
    # (each dispatch is a full RTT on a tunnel-attached chip) and the
    # delta path slices the resident buffer device-side below
    # keyed to THIS grid entry (id): two engines over the same table
    # (e.g. the sharded and single-device twins in the parity fuzz)
    # must not blindly share buffers across entries, and the cache
    # releases an entry's buffers when it drops the entry
    # (DeviceRangeCache._release — id reuse can never serve stale).
    # Tables assembled per-call (datanode partials) opt out — their
    # entry ids never repeat, so puts could only accumulate dead
    # buffers.
    use_sessions = getattr(table, "session_cacheable", True)
    session_tkey = ("range", id(entry))
    session_key = (memo_key, prog_spec)
    out_dev = (sessions.global_sessions.get(
        session_tkey, session_key, entry.version
    ) if use_sessions else None)
    # device-time attribution: one span per query carrying compile
    # (first-call vs cache-hit), block_until_ready execute time and
    # transfer bytes — the tunnel floor becomes a named span on the
    # trace. Attribution comes from device_trace's PROCESS-level memo,
    # matching the jit cache's scope (the entry-level program_specs
    # memo resets with every rebuilt grid entry — e.g. each datanode
    # partial builds a fresh table — and would mislabel warm programs
    # as first_call). A session hit keeps the span (execute is the
    # skipped dispatch, ~0) so traces always show the device leg.
    first_spec = prog_spec not in entry.program_specs
    # program identity carries the mesh variant (single-device vs
    # shard_map twin vs auto-SPMD fold): the profiler must never
    # cross-serve mesh twins under one registry row
    with stats.timed("device_exec_ms"), \
            device_trace.device_call(
                "range", key=("range", prog_tag, prog_spec),
                groups=g, steps=n_steps,
                collective=prog_tag == "sharded_pallas",
                comm_bytes=comm_bytes) as dcall:
        if out_dev is not None:
            stats.note("device_session", "hit")
            dcall.executed()
        else:
            stats.note("device_session", "miss")
            if uploaded_bytes:
                dcall.transfer(uploaded_bytes, "upload")
            out_dev = dcall.run(
                program,
                arrs, memo["gid"], memo["mask"],
                memo["delta"], memo["lo"], memo["hi"],
                spec=prog_spec,
            )
            out_dev.block_until_ready()
            dcall.executed()
            if use_sessions:
                sessions.global_sessions.put(
                    session_tkey, session_key, entry.version, out_dev,
                    int(out_dev.nbytes),
                )
        # fold=False leaves the series axis un-folded: rows [g:] are
        # the padded/inactive tail (fold=True already has exactly g
        # rows). Both slices happen on the DEVICE array, so a delta
        # poll moves only the unseen steps across the tunnel
        # (readback.read_delta feeds
        # gtpu_readback_bytes_total{mode=full|delta}).
        sliced = out_dev if memo["fold"] else out_dev[:, :g]
        out = readback.read_delta(sliced, j0, axis=-1)
        dcall.transfer(out.nbytes, "readback")
    if first_spec:
        entry.program_specs[prog_spec] = True
        concurrency.Thread(
            target=_persist_program_specs, args=(entry, table),
            daemon=True, name="program-specs-persist",
        ).start()
    step_ts_eff = step_ts[j0:] if j0 else step_ts
    n_steps_eff = n_steps - j0
    stats.add("device_readback_bytes", out.nbytes)
    stats.add("range_groups", g)
    stats.add("range_steps", n_steps)
    n_items = len(plan.range_items)
    vals = out[:n_items].astype(np.float64)
    if nanenc:
        pres = np.empty_like(vals, dtype=bool)
        for i, (fname, op) in enumerate(items):
            if op == "count":
                pres[i] = vals[i] > 0
            else:
                pres[i] = np.isfinite(vals[i])
    else:
        pres = out[n_items:] > 0.5

    item_vals = {}
    item_present = {}
    for i, item in enumerate(plan.range_items):
        item_vals[item.key] = vals[i]
        item_present[item.key] = pres[i]
    return engine._assemble_range_result(
        plan, table, item_vals, item_present, key_cols, step_ts_eff,
        g, n_steps_eff,
        since_ms=since if has_fill else None,
    )
