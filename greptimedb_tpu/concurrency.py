"""Concurrency facade: every lock, condvar, event, thread, and pool
the system creates comes from here.

Off path (the default): each factory returns the *raw stdlib object* —
`Lock()` is `threading.Lock()`, `ThreadPoolExecutor(...)` is
`concurrent.futures.ThreadPoolExecutor(...)`.  No wrapper classes, no
extra frames, no per-operation cost; the only overhead is one flag
check at construction time.

On path: with the gtsan sanitizer enabled (`GTPU_SAN=1`, the
`[sanitizer]` TOML section, `greptimedb-tpu san -- <cmd>`, or
`tools.san.enable()` in tests), the factories return instrumented
wrappers that feed the lock-order graph, blocking-under-lock and
hold-time checks, and the thread/pool lifecycle registry.  See
`greptimedb_tpu/tools/san/`.

Extra (sanitizer-only) keyword arguments accepted by every factory and
silently dropped on the off path:

- `name=` on Lock/RLock/Condition: a human label for reports (default:
  the construction site `path:line`).
- `shared=True` on ThreadPoolExecutor: marks an intentionally
  process-wide pool (module-level singleton) exempt from the
  un-shutdown-pool leak check.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor as _RawExecutor

__all__ = ["Condition", "Event", "Lock", "RLock", "Thread",
           "ThreadPoolExecutor", "sanitizer_enabled"]

_enabled = False
_env_checked = False
# serializes the one-time lazy env check: without it, two threads
# making their first factory call under GTPU_SAN=1 could race one into
# handing out a raw, never-instrumented primitive
_init_mu = threading.Lock()


def _set_enabled(value: bool):
    """Called by tools.san.enable/disable; not public API."""
    global _enabled, _env_checked
    _enabled = value
    _env_checked = True


def sanitizer_enabled() -> bool:
    """True when factories currently hand out instrumented objects."""
    global _env_checked
    if not _env_checked:
        with _init_mu:
            if not _env_checked:
                # one-time lazy GTPU_SAN=1 auto-enable (sets _enabled
                # via _set_enabled); keeps plain imports free of san
                # machinery
                if (os.environ.get("GTPU_SAN") or "").strip().lower() \
                        in ("1", "true", "on", "yes"):
                    from greptimedb_tpu.tools import san

                    san.ensure_enabled_from_env()
                _env_checked = True
    return _enabled


def Lock(*, name: str | None = None):
    if not sanitizer_enabled():
        return threading.Lock()
    from greptimedb_tpu.tools.san.wrappers import SanLock

    return SanLock(name)


def RLock(*, name: str | None = None):
    if not sanitizer_enabled():
        return threading.RLock()
    from greptimedb_tpu.tools.san.wrappers import SanRLock

    return SanRLock(name)


def Condition(lock=None, *, name: str | None = None):
    if not sanitizer_enabled():
        return threading.Condition(lock)
    from greptimedb_tpu.tools.san.wrappers import SanCondition

    return SanCondition(lock, name=name)


def Event():
    if not sanitizer_enabled():
        return threading.Event()
    from greptimedb_tpu.tools.san.wrappers import SanEvent

    return SanEvent()


def Thread(*args, **kwargs):
    if not sanitizer_enabled():
        # factory passthrough: lifecycle hygiene is checked at the CALL
        # site (GT008) and at runtime by gtsan (GTS104), not here
        return threading.Thread(*args, **kwargs)  # gtlint: disable=GT008
    from greptimedb_tpu.tools.san.wrappers import SanThread

    return SanThread(*args, **kwargs)


def ThreadPoolExecutor(*args, shared: bool = False, **kwargs):
    if not sanitizer_enabled():
        return _RawExecutor(*args, **kwargs)
    from greptimedb_tpu.tools.san.wrappers import SanThreadPoolExecutor

    return SanThreadPoolExecutor(*args, shared=shared, **kwargs)
