"""Table schemas with semantic column roles.

Equivalent to the reference's `ColumnSchema`/`Schema` with TIME INDEX and
tag/field semantics (/root/reference/src/datatypes/src/schema/column_schema.rs
and /root/reference/src/api: SemanticType). The TAG / FIELD / TIMESTAMP split
is load-bearing for the TPU design: TAG columns are dictionary-encoded on the
host and become int32 series ids on device; FIELD columns become dense f32/f64
matrices; the TIMESTAMP column defines the time axis of every device grid.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Iterable

import pyarrow as pa

from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.errors import ColumnNotFoundError, InvalidArgumentError


class SemanticType(enum.IntEnum):
    TAG = 0
    FIELD = 1
    TIMESTAMP = 2


@dataclass
class ColumnSchema:
    name: str
    data_type: ConcreteDataType
    semantic_type: SemanticType = SemanticType.FIELD
    nullable: bool = True
    default: Any = None
    # fulltext-index flag, mirroring the reference's fulltext column option.
    fulltext: bool = False
    # inverted-index flag for tag pruning.
    inverted_index: bool = False

    def to_arrow_field(self) -> pa.Field:
        meta = {
            b"greptime:semantic_type": str(int(self.semantic_type)).encode(),
        }
        return pa.field(
            self.name, self.data_type.to_arrow(), nullable=self.nullable, metadata=meta
        )

    @staticmethod
    def from_arrow_field(f: pa.Field) -> "ColumnSchema":
        sem = SemanticType.FIELD
        if f.metadata and b"greptime:semantic_type" in f.metadata:
            sem = SemanticType(int(f.metadata[b"greptime:semantic_type"]))
        return ColumnSchema(
            name=f.name,
            data_type=ConcreteDataType.from_arrow(f.type),
            semantic_type=sem,
            nullable=f.nullable,
        )

    @property
    def is_tag(self) -> bool:
        return self.semantic_type == SemanticType.TAG

    @property
    def is_field(self) -> bool:
        return self.semantic_type == SemanticType.FIELD

    @property
    def is_time_index(self) -> bool:
        return self.semantic_type == SemanticType.TIMESTAMP


@dataclass
class Schema:
    """An ordered set of columns with exactly one TIME INDEX."""

    columns: list[ColumnSchema]
    version: int = 0
    _index: dict[str, int] = field(default_factory=dict, repr=False)

    def __post_init__(self):
        self._index = {c.name: i for i, c in enumerate(self.columns)}
        if len(self._index) != len(self.columns):
            raise InvalidArgumentError("duplicate column names in schema")
        ts_cols = [c for c in self.columns if c.is_time_index]
        if len(ts_cols) > 1:
            raise InvalidArgumentError("schema must have at most one TIME INDEX column")

    # ---- lookups ------------------------------------------------------
    def __len__(self) -> int:
        return len(self.columns)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def column(self, name: str) -> ColumnSchema:
        try:
            return self.columns[self._index[name]]
        except KeyError:
            raise ColumnNotFoundError(f"column not found: {name}") from None

    def column_index(self, name: str) -> int:
        try:
            return self._index[name]
        except KeyError:
            raise ColumnNotFoundError(f"column not found: {name}") from None

    def maybe_column(self, name: str) -> ColumnSchema | None:
        i = self._index.get(name)
        return None if i is None else self.columns[i]

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    @property
    def time_index(self) -> ColumnSchema:
        for c in self.columns:
            if c.is_time_index:
                return c
        raise InvalidArgumentError("schema has no TIME INDEX column")

    @property
    def maybe_time_index(self) -> ColumnSchema | None:
        for c in self.columns:
            if c.is_time_index:
                return c
        return None

    @property
    def tag_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.is_tag]

    @property
    def field_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.is_field]

    @property
    def primary_key(self) -> list[str]:
        return [c.name for c in self.tag_columns]

    # ---- arrow --------------------------------------------------------
    def to_arrow(self) -> pa.Schema:
        return pa.schema(
            [c.to_arrow_field() for c in self.columns],
            metadata={b"greptime:version": str(self.version).encode()},
        )

    @staticmethod
    def from_arrow(s: pa.Schema) -> "Schema":
        version = 0
        if s.metadata and b"greptime:version" in s.metadata:
            version = int(s.metadata[b"greptime:version"])
        return Schema([ColumnSchema.from_arrow_field(f) for f in s], version=version)

    def project(self, names: Iterable[str]) -> "Schema":
        return Schema([self.column(n) for n in names], version=self.version)

    def with_column(self, col: ColumnSchema) -> "Schema":
        return Schema(self.columns + [col], version=self.version + 1)

    def without_column(self, name: str) -> "Schema":
        self.column(name)  # raise if missing
        return Schema(
            [c for c in self.columns if c.name != name], version=self.version + 1
        )
