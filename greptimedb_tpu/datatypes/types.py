"""Column type system bridging Arrow <-> NumPy <-> JAX.

Equivalent in capability to the reference's `ConcreteDataType`
(/root/reference/src/datatypes/src/data_type.rs) but designed around what a
TPU can hold natively: numerics and timestamps become device arrays; strings
live on the host as Arrow dictionary-encoded columns whose int32 codes are
what ships to the device (SURVEY.md §7 step 1).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np
import pyarrow as pa


class TypeId(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"
    BINARY = "binary"
    # timestamps are int64 with a unit; millisecond is the canonical TIME INDEX
    # unit, like the reference's TimestampMillisecond default.
    TIMESTAMP_SECOND = "timestamp_s"
    TIMESTAMP_MILLISECOND = "timestamp_ms"
    TIMESTAMP_MICROSECOND = "timestamp_us"
    TIMESTAMP_NANOSECOND = "timestamp_ns"
    DATE = "date"
    JSON = "json"
    # INTERVAL: a duration, stored as int64 milliseconds (the reference
    # carries IntervalMonthDayNano, src/common/time/src/interval.rs; the
    # fixed-ms form covers the arithmetic/ordering surface this engine
    # computes with)
    INTERVAL = "interval"
    # Decimal128 (reference: src/common/decimal/): exact (precision,
    # scale) at the schema/wire/Parquet boundary; the in-memory and
    # on-device representation is float64 (the TPU computes in floats —
    # values round-trip exactly for precision <= 15)
    DECIMAL = "decimal"


_TS_UNITS = {
    TypeId.TIMESTAMP_SECOND: "s",
    TypeId.TIMESTAMP_MILLISECOND: "ms",
    TypeId.TIMESTAMP_MICROSECOND: "us",
    TypeId.TIMESTAMP_NANOSECOND: "ns",
}

_TS_PER_SECOND = {"s": 1, "ms": 1_000, "us": 1_000_000, "ns": 1_000_000_000}


@dataclass(frozen=True)
class ConcreteDataType:
    id: TypeId
    # decimal parameters (None for every other type)
    precision: int | None = None
    scale: int | None = None

    # ---- constructors -------------------------------------------------
    @staticmethod
    def bool_() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.BOOL)

    @staticmethod
    def int8() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.INT8)

    @staticmethod
    def int16() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.INT16)

    @staticmethod
    def int32() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.INT32)

    @staticmethod
    def int64() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.INT64)

    @staticmethod
    def uint8() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.UINT8)

    @staticmethod
    def uint16() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.UINT16)

    @staticmethod
    def uint32() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.UINT32)

    @staticmethod
    def uint64() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.UINT64)

    @staticmethod
    def float32() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.FLOAT32)

    @staticmethod
    def float64() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.FLOAT64)

    @staticmethod
    def string() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.STRING)

    @staticmethod
    def binary() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.BINARY)

    @staticmethod
    def json() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.JSON)

    @staticmethod
    def timestamp_millisecond() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.TIMESTAMP_MILLISECOND)

    @staticmethod
    def timestamp_second() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.TIMESTAMP_SECOND)

    @staticmethod
    def timestamp_microsecond() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.TIMESTAMP_MICROSECOND)

    @staticmethod
    def timestamp_nanosecond() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.TIMESTAMP_NANOSECOND)

    @staticmethod
    def date() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.DATE)

    @staticmethod
    def interval() -> "ConcreteDataType":
        return ConcreteDataType(TypeId.INTERVAL)

    def is_interval(self) -> bool:
        return self.id == TypeId.INTERVAL

    @staticmethod
    def decimal128(precision: int = 38, scale: int = 10
                   ) -> "ConcreteDataType":
        if not (1 <= precision <= 38):
            raise ValueError(f"decimal precision {precision} out of [1,38]")
        if not (0 <= scale <= precision):
            raise ValueError(
                f"decimal scale {scale} out of [0,{precision}]"
            )
        return ConcreteDataType(TypeId.DECIMAL, precision, scale)

    def is_decimal(self) -> bool:
        return self.id == TypeId.DECIMAL

    # ---- predicates ---------------------------------------------------
    def is_timestamp(self) -> bool:
        return self.id in _TS_UNITS

    def is_string(self) -> bool:
        return self.id in (TypeId.STRING, TypeId.JSON)

    def is_numeric(self) -> bool:
        return self.id in (
            TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
            TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64,
            TypeId.FLOAT32, TypeId.FLOAT64, TypeId.DECIMAL,
        )

    def is_float(self) -> bool:
        # decimal computes as float64 in this engine (see TypeId.DECIMAL)
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64, TypeId.DECIMAL)

    def is_integer(self) -> bool:
        return self.is_numeric() and not self.is_float()

    def is_signed(self) -> bool:
        return self.id in (
            TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
            TypeId.FLOAT32, TypeId.FLOAT64, TypeId.DECIMAL,
        )

    @property
    def timestamp_unit(self) -> str:
        return _TS_UNITS[self.id]

    @property
    def ticks_per_second(self) -> int:
        return _TS_PER_SECOND[_TS_UNITS[self.id]]

    # ---- conversions --------------------------------------------------
    def to_arrow(self) -> pa.DataType:
        t = self.id
        if t == TypeId.DECIMAL:
            return pa.decimal128(self.precision or 38, self.scale or 0)
        if t == TypeId.BOOL:
            return pa.bool_()
        if t == TypeId.STRING:
            return pa.string()
        if t == TypeId.JSON:
            return pa.string()
        if t == TypeId.BINARY:
            return pa.binary()
        if t == TypeId.DATE:
            return pa.date32()
        if t == TypeId.INTERVAL:
            return pa.duration("ms")
        if self.is_timestamp():
            return pa.timestamp(_TS_UNITS[t])
        return pa.type_for_alias(t.value)

    def to_numpy(self) -> np.dtype:
        t = self.id
        if t == TypeId.BOOL:
            return np.dtype(np.bool_)
        if t in (TypeId.STRING, TypeId.JSON, TypeId.BINARY):
            return np.dtype(object)
        if self.is_timestamp() or t in (TypeId.DATE, TypeId.INTERVAL):
            return np.dtype(np.int64)
        if t == TypeId.DECIMAL:
            return np.dtype(np.float64)
        return np.dtype(t.value)

    @property
    def name(self) -> str:
        if self.id == TypeId.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        return self.id.value

    @staticmethod
    def from_arrow(dt: pa.DataType) -> "ConcreteDataType":
        if pa.types.is_dictionary(dt):
            return ConcreteDataType.from_arrow(dt.value_type)
        if pa.types.is_boolean(dt):
            return ConcreteDataType.bool_()
        if pa.types.is_timestamp(dt):
            return ConcreteDataType(
                {v: k for k, v in _TS_UNITS.items()}[dt.unit]
            )
        if pa.types.is_date(dt):
            return ConcreteDataType.date()
        if pa.types.is_duration(dt):
            return ConcreteDataType.interval()
        if pa.types.is_decimal(dt):
            return ConcreteDataType.decimal128(dt.precision, dt.scale)
        if pa.types.is_string(dt) or pa.types.is_large_string(dt):
            return ConcreteDataType.string()
        if pa.types.is_binary(dt) or pa.types.is_large_binary(dt):
            return ConcreteDataType.binary()
        if pa.types.is_float64(dt):
            return ConcreteDataType.float64()
        if pa.types.is_float32(dt):
            return ConcreteDataType.float32()
        try:
            return ConcreteDataType(TypeId(str(dt)))
        except ValueError as e:
            raise ValueError(f"unsupported arrow type: {dt}") from e

    @staticmethod
    def from_name(name: str) -> "ConcreteDataType":
        name = name.strip().lower()
        aliases = {
            "boolean": TypeId.BOOL,
            "tinyint": TypeId.INT8,
            "smallint": TypeId.INT16,
            "int": TypeId.INT32,
            "integer": TypeId.INT32,
            "bigint": TypeId.INT64,
            "tinyint unsigned": TypeId.UINT8,
            "smallint unsigned": TypeId.UINT16,
            "int unsigned": TypeId.UINT32,
            "bigint unsigned": TypeId.UINT64,
            "float": TypeId.FLOAT32,
            "real": TypeId.FLOAT32,
            "double": TypeId.FLOAT64,
            "varchar": TypeId.STRING,
            "text": TypeId.STRING,
            "varbinary": TypeId.BINARY,
            "timestamp": TypeId.TIMESTAMP_MILLISECOND,
            "timestamp(0)": TypeId.TIMESTAMP_SECOND,
            "timestamp(3)": TypeId.TIMESTAMP_MILLISECOND,
            "timestamp(6)": TypeId.TIMESTAMP_MICROSECOND,
            "timestamp(9)": TypeId.TIMESTAMP_NANOSECOND,
            "timestamp_s": TypeId.TIMESTAMP_SECOND,
            "timestamp_ms": TypeId.TIMESTAMP_MILLISECOND,
            "timestamp_us": TypeId.TIMESTAMP_MICROSECOND,
            "timestamp_ns": TypeId.TIMESTAMP_NANOSECOND,
        }
        if name in aliases:
            return ConcreteDataType(aliases[name])
        import re as _re

        m = _re.fullmatch(
            r"(?:decimal|numeric)\s*(?:\(\s*(\d+)\s*(?:,\s*(\d+)\s*)?\))?",
            name,
        )
        if m:
            precision = int(m.group(1)) if m.group(1) else 38
            scale = int(m.group(2)) if m.group(2) else (
                10 if m.group(1) is None else 0
            )
            return ConcreteDataType.decimal128(precision, scale)
        return ConcreteDataType(TypeId(name))
