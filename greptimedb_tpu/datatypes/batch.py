"""Host-side columnar batches and the host->device bridge.

The capability counterpart of the reference's `common-recordbatch` crate, but
the conversion policy is TPU-first (SURVEY.md §7 step 1):

- string/tag columns are dictionary-encoded on the host; only the int32 codes
  ship to the device,
- nulls become explicit validity masks (bool arrays), since XLA has no null
  semantics,
- batches are padded up to a bucket size so jit traces are reused across
  batches of different row counts (static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import pyarrow as pa

from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.datatypes.types import ConcreteDataType


def bucket_size(n: int, *, minimum: int = 1024) -> int:
    """Round ``n`` up to a shape bucket (power of two) to bound the number of
    distinct compiled shapes. Mirrors the padding/bucketing policy named in
    SURVEY.md §7 hard-part (b)."""
    if n <= 0:
        return minimum
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class HostColumn:
    """One column: numpy values + validity. Strings stay as object arrays on
    the host; `codes`/`vocab` appear once dictionary-encoded."""

    name: str
    data_type: ConcreteDataType
    values: np.ndarray
    validity: np.ndarray | None = None  # None == all valid

    def __len__(self) -> int:
        return len(self.values)

    @property
    def valid_mask(self) -> np.ndarray:
        if self.validity is None:
            return np.ones(len(self.values), dtype=bool)
        return self.validity

    def to_arrow(self) -> pa.Array:
        mask = None if self.validity is None else ~self.validity
        if self.data_type.is_decimal():
            # float64 in memory -> exact decimal128 on the wire
            arr = pa.array(
                np.asarray(self.values, np.float64), pa.float64(), mask=mask
            )
            return arr.cast(self.data_type.to_arrow(), safe=False)
        return pa.array(self.values, type=self.data_type.to_arrow(), mask=mask)

    @staticmethod
    def from_arrow(name: str, arr: pa.Array | pa.ChunkedArray) -> "HostColumn":
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_dictionary(arr.type):
            arr = arr.cast(arr.type.value_type)
        dt = ConcreteDataType.from_arrow(arr.type)
        validity = None
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
        if dt.is_string() or dt.id.value == "binary":
            values = np.asarray(arr.to_pylist(), dtype=object)
        elif dt.is_decimal():
            arr = arr.cast(pa.float64())
            if arr.null_count:
                arr = arr.fill_null(0)
            values = np.asarray(arr)
        elif dt.is_timestamp():
            arr = arr.cast(pa.int64())
            if arr.null_count:
                arr = arr.fill_null(0)
            values = np.asarray(arr)
        elif dt.id.value == "date":
            arr = arr.cast(pa.int32())
            if arr.null_count:
                arr = arr.fill_null(0)
            values = np.asarray(arr).astype(np.int64)
        elif dt.is_interval():
            # normalize any duration unit to the type's int64-ms
            # representation (a duration("s") 5 must become 5000, and
            # even duration("ms") must land as int64, not timedelta64)
            arr = arr.cast(pa.duration("ms")).cast(pa.int64())
            if arr.null_count:
                arr = arr.fill_null(0)
            values = np.asarray(arr)
        else:
            if arr.null_count:
                arr = arr.fill_null(0)
            values = np.asarray(arr)
        return HostColumn(name, dt, values, validity)


class Dictionary:
    """Incremental string -> int32 code dictionary (one per tag column).

    The device never sees strings: tag values are interned here at ingest and
    group-by/series identification runs over the codes (the TPU analog of the
    reference's mcmp primary-key encoding, /root/reference/src/mito2/src/
    row_converter.rs:54)."""

    def __init__(self, values: list[str] | None = None):
        self._values: list[str] = []
        self._codes: dict[str, int] = {}
        if values:
            for v in values:
                self.intern(v)

    def __len__(self) -> int:
        return len(self._values)

    def intern(self, value: str) -> int:
        code = self._codes.get(value)
        if code is None:
            code = len(self._values)
            self._codes[value] = code
            self._values.append(value)
        return code

    def intern_array(self, values: np.ndarray) -> np.ndarray:
        """Vectorized interning: hash-based dictionary encode once per
        batch (Arrow, O(n)), dict work only on the (few) distinct values,
        then a single np.take to expand. np.unique's sort-based O(n log n)
        string compares are the fallback for non-string payloads."""
        try:
            enc = pa.array(values, type=pa.string()).dictionary_encode()
            uniq = enc.dictionary.to_pylist()
            inv = enc.indices.to_numpy(zero_copy_only=False)
        except (pa.lib.ArrowInvalid, pa.lib.ArrowTypeError):
            uniq, inv = np.unique(values, return_inverse=True)
        codes = self._codes
        uniq_codes = np.empty(len(uniq), dtype=np.int32)
        for i, v in enumerate(uniq):
            c = codes.get(v)
            if c is None:
                c = len(self._values)
                codes[v] = c
                self._values.append(v)
            uniq_codes[i] = c
        return uniq_codes[np.asarray(inv, np.int64)]

    def lookup(self, value: str) -> int | None:
        return self._codes.get(value)

    def decode(self, code: int) -> str:
        return self._values[code]

    def decode_array(self, codes: np.ndarray) -> np.ndarray:
        vals = np.asarray(self._values, dtype=object)
        return vals[codes]

    @property
    def values(self) -> list[str]:
        return self._values


@dataclass
class HostBatch:
    """A schema'd bundle of HostColumns (host-side RecordBatch)."""

    schema: Schema
    columns: list[HostColumn]
    num_rows: int = field(init=False)

    def __post_init__(self):
        self.num_rows = len(self.columns[0]) if self.columns else 0
        for c in self.columns:
            assert len(c) == self.num_rows, "ragged batch"

    def column(self, name: str) -> HostColumn:
        return self.columns[self.schema.column_index(name)]

    def to_arrow(self) -> pa.Table:
        return pa.table(
            [c.to_arrow() for c in self.columns], schema=self.schema.to_arrow()
        )

    @staticmethod
    def from_arrow(table: pa.Table, schema: Schema | None = None) -> "HostBatch":
        if schema is None:
            schema = Schema.from_arrow(table.schema)
        cols = [
            HostColumn.from_arrow(name, table.column(name))
            for name in table.column_names
        ]
        return HostBatch(schema, cols)

    def select(self, names: list[str]) -> "HostBatch":
        return HostBatch(self.schema.project(names), [self.column(n) for n in names])

    def take(self, indices: np.ndarray) -> "HostBatch":
        cols = [
            HostColumn(
                c.name,
                c.data_type,
                c.values[indices],
                None if c.validity is None else c.validity[indices],
            )
            for c in self.columns
        ]
        return HostBatch(self.schema, cols)

    @staticmethod
    def concat(batches: list["HostBatch"]) -> "HostBatch":
        assert batches, "cannot concat zero batches"
        schema = batches[0].schema
        cols = []
        for i, cs in enumerate(batches[0].columns):
            vals = np.concatenate([b.columns[i].values for b in batches])
            if any(b.columns[i].validity is not None for b in batches):
                validity = np.concatenate(
                    [b.columns[i].valid_mask for b in batches]
                )
            else:
                validity = None
            cols.append(HostColumn(cs.name, cs.data_type, vals, validity))
        return HostBatch(schema, cols)


def pad_to(values: np.ndarray, n: int, fill=0) -> np.ndarray:
    """Pad a 1-D array up to length ``n`` with ``fill``."""
    if len(values) == n:
        return values
    assert len(values) < n
    out = np.full(n, fill, dtype=values.dtype)
    out[: len(values)] = values
    return out
