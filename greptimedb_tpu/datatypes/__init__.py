from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType

__all__ = ["ConcreteDataType", "ColumnSchema", "Schema", "SemanticType"]
