"""Cached device fast path for hot PromQL shapes.

The generic engine (promql/engine.py) rescans storage, regridifies, and
builds one Python label dict per series on every query — fine at 10k
series, hopeless at 1M. This module is the counterpart of the reference's
specialised PromQL plans (/root/reference/src/query/src/promql/planner.rs
PromPlanner + src/promql/src/extension_plan/range_manipulate.rs), rebuilt
around the device grid cache idea proven by query/device_range.py:

- a **selector grid cache**: per (table, field), the full (series x cell)
  vals/has/tsg grids for every series live in HBM, version-stamped by
  Table.data_version() and evicted under a byte budget;
- **dictionary-coded label algebra**: matchers evaluate per distinct tag
  value then broadcast through int32 code columns (SeriesRegistry.
  match_mask); group-by keys come from the cached codes matrix via one
  np.unique — no per-series Python;
- **one fused XLA program** per query shape: range function (prefix-path
  kernels from ops/window.py) + cross-series aggregation
  (ops/promql.aggregate_across_series) compile into a single jit call, so
  a query moves J*12 bytes of window indices to the device and (G, J)
  results back — independent of the series count.

Shapes handled: `agg [by/without (...)] (range_fn(sel[d]))` and
`agg [by/without (...)] (sel)` for the prefix-path range functions and the
simple aggregators. Everything else falls back to the generic engine, as
do queries whose step/range don't align with the cached grid resolution.
"""

from __future__ import annotations

import functools
import os

import time
from dataclasses import dataclass, field

import jax
import numpy as np

from greptimedb_tpu.promql.parser import (
    Agg,
    Binary,
    Call,
    NumberLit,
    VectorSelector,
)
from greptimedb_tpu.program_cache import ProgramCache
from greptimedb_tpu.telemetry.metrics import global_registry

from greptimedb_tpu import concurrency

# range functions computable from per-series prefix sums: O(S*T) memory,
# no (S, J, L) window materialisation, safe at 1M series.
_PREFIX_FNS = frozenset({
    "rate", "increase", "delta", "idelta", "irate",
    "sum_over_time", "count_over_time", "avg_over_time",
    "last_over_time", "first_over_time", "present_over_time",
    "changes", "resets",
    "min_over_time", "max_over_time", "stddev_over_time",
    "stdvar_over_time", "mad_over_time", "deriv",
    "quantile_over_time", "predict_linear", "holt_winters",
})
# leading scalar-literal argument count per arg-taking range function
_FN_LEAD_ARGS = {
    "quantile_over_time": 1, "predict_linear": 0, "holt_winters": 0,
}
# trailing scalar args (after the selector)
_FN_TRAIL_ARGS = {"predict_linear": 1, "holt_winters": 2}
_SIMPLE_AGGS = frozenset(
    {"sum", "avg", "min", "max", "count", "group", "stddev", "stdvar"}
)

_FAST_HITS = global_registry.counter(
    "greptime_promql_fast_path_total",
    "PromQL queries served from the selector grid cache", ("event",),
)


def _budget_bytes() -> int:
    return int(os.environ.get(
        "GREPTIMEDB_TPU_PROMQL_CACHE_BYTES", 4 * 1024**3
    ))


def _pow2_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class _Entry:
    table: object
    fieldname: str
    version: tuple
    registry: object            # SeriesRegistry snapshot backing the grids
    spec: object                # ops.grid.GridSpec
    vals: object                # (S_pad, NC) device float32
    has: object                 # (S_pad, NC) device bool
    tsg: object                 # (S_pad, NC) device int32
    num_series: int
    s_pad: int
    nbytes: int
    last_used: float = 0.0
    mesh: object = None         # series-axis sharding mesh (None = 1 dev)
    mesh_decision: object = None  # planner MeshDecision (replicate/shard)
    # per-entry derived caches (device-resident, so queries move no masks)
    match_cache: dict = field(default_factory=dict)
    group_cache: dict = field(default_factory=dict)
    win_cache: dict = field(default_factory=dict)


class SelectorGridCache:
    """LRU byte-budgeted cache of full-table selector grids."""

    def __init__(self):
        self._entries: dict[tuple, _Entry] = {}
        self._lock = concurrency.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.register_pool(
            "promql_grid", "device", self,
            stats=SelectorGridCache._mem_stats,
            evict=SelectorGridCache.evict_bytes,
            buffers=SelectorGridCache._device_buffers,
        )

    def get_entry(self, table, fieldname: str, mesh=None,
                  mesh_opts=None) -> _Entry | None:
        key = (id(table), fieldname)
        version = table.data_version()
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.table is table and e.version == version:
                e.last_used = time.monotonic()
                self._hits += 1
                return e
            self._misses += 1
        e = _build_entry(table, fieldname, version, mesh=mesh,
                         mesh_opts=mesh_opts)
        if e is None:
            return None
        with self._lock:
            old = self._entries.get(key)
            if old is not None and old is not e:
                self._release(old)  # stale version: free its buffers
            self._entries[key] = e
            e.last_used = time.monotonic()
            self._evict_locked(keep=key)
        from greptimedb_tpu.telemetry import memory as _memory

        _memory.note_device_bytes()
        return e

    def _release(self, entry: "_Entry"):
        """Drop the entry's session-resident result buffers with it: a
        freed _Entry's id() can be reused by a new entry whose version
        coincides, and the packed buffers would otherwise pin HBM until
        unrelated LRU pressure (query/sessions.py purge contract)."""
        from greptimedb_tpu.query import sessions as _sessions

        self._evictions += 1
        _sessions.global_sessions.purge_table(("promql", id(entry)))

    def _evict_locked(self, keep):
        budget = _budget_bytes()
        total = sum(e.nbytes for e in self._entries.values())
        if total <= budget:
            return
        for key, _ in sorted(
            self._entries.items(), key=lambda kv: kv[1].last_used
        ):
            if key == keep:
                continue
            victim = self._entries.pop(key)
            self._release(victim)
            total -= victim.nbytes
            if total <= budget:
                return

    def invalidate(self):
        with self._lock:
            for e in self._entries.values():
                self._release(e)
            self._entries.clear()

    def drop_table(self, table):
        with self._lock:
            for key in [
                k for k, e in self._entries.items() if e.table is table
            ]:
                self._release(self._entries.pop(key))

    # ------------------------------------------------------------------
    # memory accountant surface (telemetry/memory.py)
    # ------------------------------------------------------------------
    def _mem_stats(self) -> dict:
        from greptimedb_tpu.telemetry.memory import iter_device_arrays

        with self._lock:
            total = 0
            seen: set[int] = set()
            for e in self._entries.values():
                total += e.nbytes
                # derived per-query device inputs (match masks, group
                # ids, window indices) pinned on the entry count too —
                # the global watermark must see every resident byte
                # (same arrays the census enumerates)
                for cname in ("match_cache", "group_cache",
                              "win_cache"):
                    for v in list((getattr(e, cname, None) or {})
                                  .values()):
                        for arr in iter_device_arrays(v):
                            if id(arr) not in seen:
                                seen.add(id(arr))
                                total += int(arr.nbytes)
            return {
                "bytes": total,
                "entries": len(self._entries),
                "budget_bytes": _budget_bytes(),
                "hits": self._hits, "misses": self._misses,
                "evictions": self._evictions,
            }

    def evict_bytes(self, target: int) -> int:
        """Shed least-recently-used grids until `target` bytes are
        freed (cross-pool pressure from the global device watermark)."""
        freed = 0
        with self._lock:
            for key, e in sorted(
                self._entries.items(), key=lambda kv: kv[1].last_used
            ):
                if freed >= target:
                    break
                self._release(self._entries.pop(key))
                freed += e.nbytes
        return freed

    def _device_buffers(self):
        from greptimedb_tpu.telemetry.memory import iter_device_arrays

        out = []
        with self._lock:
            for key, e in self._entries.items():
                tag = f"promql:{e.fieldname}"
                for arr in (e.vals, e.has, e.tsg):
                    if arr is not None:
                        out.append((arr, tag))
                # derived per-query device inputs (match masks, group
                # ids, window indices) pinned on the entry
                for cname in ("match_cache", "group_cache", "win_cache"):
                    cache = getattr(e, cname, None) or {}
                    for v in list(cache.values()):
                        for arr in iter_device_arrays(v):
                            out.append((arr, f"{tag}:{cname}"))
        return out


_CACHE = SelectorGridCache()


def _session_exec(entry: _Entry, skey: tuple, run):
    """Persistent query session for a fused program's packed result: an
    identical repeated poll serves the HBM-resident buffer without
    re-dispatching the program (query/sessions.py — each dispatch is a
    full RTT on a tunnel-attached chip). The shape key embeds the
    device-array identities of the cached masks/grouping/window inputs
    (match_cache/group_cache/win_cache): same id => same immutable
    buffer, and an evicted input only costs a false miss. Entry version
    rides the registry's validation, so any data change invalidates."""
    from greptimedb_tpu.query import sessions as _sessions

    tkey = ("promql", id(entry))
    buf = _sessions.global_sessions.get(tkey, skey, entry.version)
    if buf is None:
        buf = run()
        buf.block_until_ready()
        _sessions.global_sessions.put(
            tkey, skey, entry.version, buf, int(buf.nbytes)
        )
    return buf


def _series_sharding(mesh, ndim: int):
    """NamedSharding partitioning axis 0 (series) over the mesh; None
    when single-device."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    spec = [None] * ndim
    spec[0] = AXIS_SHARD
    return NamedSharding(mesh, P(*spec))


def _build_entry(table, fieldname: str, version, mesh=None,
                 mesh_opts=None) -> _Entry | None:
    """Scan the whole table once and gridify every series onto one
    HBM-resident grid. Resolution is the gcd of observed sample intervals
    (coarsened if the grid would blow the cell cap, same approximation as
    ops/window.plan_grid_and_windows)."""
    if getattr(table, "remote", False):
        return None  # distributed tables: grids live on the datanodes
    import jax.numpy as jnp

    from greptimedb_tpu.ops import grid as G

    col = next(
        (c for c in table.info.schema.field_columns if c.name == fieldname),
        None,
    )
    if col is None or col.data_type.is_string():
        return None  # no device grid for string fields; skip the scan
    t0_build = time.perf_counter()
    data = table.scan(field_names=[fieldname])
    rows = data.rows
    registry = data.registry
    if rows is None or len(rows) == 0 or registry.num_series == 0:
        return _Entry(
            table, fieldname, version, registry, None, None, None, None,
            0, 0, 0,
        )
    vals_np = rows.fields[fieldname]
    if not np.issubdtype(np.asarray(vals_np).dtype, np.number):
        return None  # string field: no device grid
    ts = np.asarray(rows.ts, np.int64)
    uniq_ts = np.unique(ts)
    if len(uniq_ts) > 1:
        res = int(np.gcd.reduce(np.diff(uniq_ts)))
    else:
        res = 1000
    res = max(res, 1)
    t_min = int(uniq_ts[0])
    t_max = int(uniq_ts[-1])
    s = registry.num_series
    mesh_decision = None
    if mesh is not None:
        # replicate-vs-shard: small grids stay single-device (collective
        # + launch latency dominates), large ones shard the series axis
        from greptimedb_tpu.query.planner import decide_mesh_execution

        mesh_decision = decide_mesh_execution(
            mesh, kind="promql", series=s, opts=mesh_opts,
        )
        if not mesh_decision.shard:
            mesh = None
    s_pad = _pow2_bucket(s)
    if mesh is not None:
        from greptimedb_tpu.parallel.mesh import AXIS_SHARD

        # series axis shards over the mesh; pow2 buckets >= 8 divide an
        # 8-way mesh evenly, smaller grids pad up to it
        s_pad = max(s_pad, mesh.shape[AXIS_SHARD])
    # keep grid bytes within half the cache budget: coarsen res as needed
    # (sacrifices exact window alignment on pathological intervals; such
    # queries then fail the alignment check and use the generic path)
    max_cells = max(_budget_bytes() // 2 // (9 * s_pad), 16)
    while (t_max - t_min) // res + 2 > max_cells:
        res *= 2
    # anchor the grid to the data's phase: samples at t_min + k*res land
    # exactly on cell boundaries, so query starts on sample times satisfy
    # the alignment precondition in _plan_windows
    t0 = t_min - res
    nc = int(-((-(t_max - t0)) // res)) + 1
    spec = G.GridSpec.build(t0, res, nc)

    cell = spec.cell_of(ts).astype(np.int32)
    tsrel = spec.device_ts(ts)
    mask = np.ones(len(ts), bool)
    if rows.field_valid is not None and fieldname in rows.field_valid:
        mask = np.asarray(rows.field_valid[fieldname], bool)
    gvals, ghas, gtsg = G.gridify(
        jnp.asarray(np.asarray(rows.sid, np.int32)),
        jnp.asarray(cell),
        jnp.asarray(tsrel),
        jnp.asarray(np.asarray(vals_np, np.float32)),
        jnp.asarray(mask),
        s_pad, nc,
    )
    if mesh is not None:
        # resident grids shard over the series axis; queries then run
        # SPMD with XLA-inserted collectives for cross-shard group folds
        import jax

        sh2 = _series_sharding(mesh, 2)
        gvals = jax.device_put(gvals, sh2)
        ghas = jax.device_put(ghas, sh2)
        gtsg = jax.device_put(gtsg, sh2)
    nbytes = s_pad * nc * 9
    # the grid BUILD is the big host->device transfer of this path:
    # attribute it on the trace (a first query over a cold selector
    # pays it; steady-state queries hit the resident grid)
    from greptimedb_tpu.telemetry import tracing as _tracing

    with _tracing.child_span("device.upload", site="promql_grid",
                             upload_bytes=nbytes):
        gvals.block_until_ready()
    _FAST_HITS.labels("grid_build").inc()
    global_registry.gauge(
        "greptime_promql_grid_build_seconds",
        "wall seconds of the last selector grid build",
    ).set(time.perf_counter() - t0_build)
    entry = _Entry(
        table, fieldname, version, registry, spec, gvals, ghas, gtsg,
        s, s_pad, nbytes,
    )
    entry.mesh = mesh
    entry.mesh_decision = mesh_decision
    return entry


# ----------------------------------------------------------------------
# per-query planning against a cached grid
# ----------------------------------------------------------------------

@dataclass
class _WinShim:
    """Windows with traced lo/hi/t_end arrays + static scalars, shaped for
    ops/promql.eval_range_function inside jit."""

    lo: object
    hi: object
    t_end: object
    range_ticks: int
    range_seconds: float
    l_cells: int

    @property
    def num_cells_per_window(self) -> int:
        return self.l_cells


@dataclass
class _SpecShim:
    tps: float


def _eval_side(vals, has, tsg, smask, lo, hi, t_end, *, fname,
               range_ticks, range_seconds, l_cells, tps, fargs,
               lookback_ticks):
    """Instant-lookback / range-function evaluation of one masked grid —
    the shared (jit-traced) front half of every fused query."""
    from greptimedb_tpu.ops import promql as K
    from greptimedb_tpu.ops import window as W

    has = has & smask[:, None]
    if fname == "__instant__":
        return W.instant_lookback(vals, has, tsg, hi, t_end,
                                  lookback_ticks)
    win = _WinShim(lo, hi, t_end, range_ticks, range_seconds, l_cells)
    return K.eval_range_function(
        fname, vals, has, tsg, win, _SpecShim(tps), args=fargs
    )


def _plan_windows(entry: _Entry, ev, range_ms: int, offset_ms: int,
                  *, align_range: bool = True):
    """Window cell indices against the cached grid, or None if the query's
    step/range/start don't land on cell boundaries (exactness requires
    alignment; see ops/grid.py cell convention). Instant lookback compares
    exact sample ticks, so only step/start need aligning for it."""
    spec = entry.spec
    res = spec.res
    start = ev.start_ms - offset_ms
    end = ev.end_ms - offset_ms
    if ev.step_ms % res or (start - spec.t0) % res:
        return None
    if align_range and range_ms % res:
        return None
    key = (start, end, ev.step_ms, range_ms)
    hit = entry.win_cache.get(key)
    if hit is not None:
        return hit
    steps = np.arange(start, end + 1, ev.step_ms, dtype=np.int64)
    hi_raw = (steps - spec.t0) // res
    w = max(range_ms // res, 1)
    hi = np.clip(hi_raw, 0, spec.num_cells - 1).astype(np.int32)
    lo = np.clip(hi_raw - w, 0, spec.num_cells - 1).astype(np.int32)
    lo = np.minimum(lo, hi)
    t_end = np.clip(
        (steps - spec.t0) // spec.unit, -2**31 + 1, 2**31 - 1
    ).astype(np.int32)
    import jax.numpy as jnp

    # device-resident window indices: a repeated query uploads nothing
    out = (
        jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(t_end),
        int(range_ms // spec.unit), range_ms / 1000.0, int(w),
    )
    if len(entry.win_cache) >= 64:
        entry.win_cache.pop(next(iter(entry.win_cache)))
    entry.win_cache[key] = out
    return out


def _matcher_mask_dev(entry: _Entry, matchers):
    """Device-resident (S_pad,) bool mask for a matcher set (padded series
    are always False). Cached so repeated queries move no bytes."""
    import jax.numpy as jnp

    key = tuple(
        (name, op, value.pattern if hasattr(value, "pattern") else value)
        for name, op, value in matchers
    )
    hit = entry.match_cache.get(key)
    if hit is not None:
        return hit
    out = None
    if matchers:
        # HBM-resident label plane (index/device_plane): the mask is a
        # gather+AND over the device codes matrix — only the per-
        # distinct-value ok-tables cross the tunnel
        from greptimedb_tpu.index import device_plane

        out = device_plane.matcher_mask_dev(
            entry.registry, matchers, entry.s_pad,
            mesh=getattr(entry, "mesh", None),
            num_series=entry.num_series,
        )
    if out is None:
        mask = np.zeros(entry.s_pad, bool)
        if matchers:
            from greptimedb_tpu import index as _index

            mask[: entry.num_series] = _index.match_mask(
                entry.registry, matchers
            )[: entry.num_series]
        else:
            mask[: entry.num_series] = True
        any_match = bool(mask.any())
        sh = _series_sharding(getattr(entry, "mesh", None), 1)
        if sh is not None:
            import jax

            dev = jax.device_put(mask, sh)
        else:
            dev = jnp.asarray(mask)
        out = (dev, any_match)
    if len(entry.match_cache) >= 128:
        entry.match_cache.pop(next(iter(entry.match_cache)))
    entry.match_cache[key] = out
    return out


def _grouping_dev(entry: _Entry, table, grouping, without: bool):
    """(group label dicts, device gid (S_pad,), num_groups). Padded series
    map to group G (dropped after aggregation). Cached per label set."""
    import jax.numpy as jnp

    key = (tuple(sorted(grouping)), bool(without))
    hit = entry.group_cache.get(key)
    if hit is not None:
        return hit
    reg = entry.registry
    codes = reg.codes_matrix()
    visible = set(table.tag_names)
    cols = [
        i for i, nm in enumerate(reg.tag_names)
        if nm in visible and not nm.startswith("__")
        and ((nm not in grouping) if without else (nm in grouping))
    ]
    s = entry.num_series
    sh = _series_sharding(getattr(entry, "mesh", None), 1)

    def put(arr):
        if sh is not None:
            import jax

            return jax.device_put(arr, sh)
        return jnp.asarray(arr)

    if not cols or s == 0:
        labels = [{}]
        gid = np.zeros(entry.s_pad, np.int32)
        gid[s:] = 1
        out = (labels, put(gid), 1)
        entry.group_cache[key] = out
        return out
    sub = codes[:s, cols]
    uniq, inv = np.unique(sub, axis=0, return_inverse=True)
    labels = []
    for row in uniq:
        lab = {}
        for ci, code in zip(cols, row):
            v = reg.dicts[ci].decode(int(code))
            if v != "":
                lab[reg.tag_names[ci]] = v
        labels.append(lab)
    g = len(uniq)
    gid = np.full(entry.s_pad, g, np.int32)
    gid[:s] = inv.astype(np.int32)
    out = (labels, put(gid), g)
    if len(entry.group_cache) >= 128:
        entry.group_cache.pop(next(iter(entry.group_cache)))
    entry.group_cache[key] = out
    return out


@functools.partial(
    jax.jit,
    static_argnames=(
        "fname", "op", "g", "range_ticks", "range_seconds", "l_cells",
        "tps", "fargs", "lookback_ticks",
    ),
)
def _fused_query(
    vals, has, tsg, smask, gid, lo, hi, t_end, *,
    fname: str, op: str, g: int, range_ticks: int, range_seconds: float,
    l_cells: int, tps: float, fargs: tuple, lookback_ticks: int,
):
    """The whole query as one XLA program: matcher mask, range function or
    instant lookback, cross-series aggregation."""
    from greptimedb_tpu.ops import promql as K

    import jax.numpy as jnp

    out, pres = _eval_side(
        vals, has, tsg, smask, lo, hi, t_end, fname=fname,
        range_ticks=range_ticks, range_seconds=range_seconds,
        l_cells=l_cells, tps=tps, fargs=fargs,
        lookback_ticks=lookback_ticks,
    )
    # blocked fold: the same fixed combine structure the sharded twin
    # runs per shard, so mesh and single-device results agree bit-for-bit
    vals_g, pres_g = K.aggregate_across_series_blocked(
        out, pres, gid, g + 1, op, total_series=vals.shape[0],
    )
    # single packed (2G, J) buffer: one device->host transfer per query
    return jnp.concatenate([
        vals_g[:g], pres_g[:g].astype(vals_g.dtype),
    ])


def _make_sharded_fused_query(mesh):
    """shard_map twin of _fused_query: grids series-sharded over
    AXIS_SHARD, each shard evaluates its series slice (range functions
    are per-series) and the cross-series aggregation recombines with the
    SAME blocked left fold the single-device program runs — sharded ==
    unsharded bit-for-bit (the 1M-series parity contract)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.dist import ShardFoldCtx
    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    ns = mesh.shape[AXIS_SHARD]

    @functools.partial(
        jax.jit,
        static_argnames=(
            "fname", "op", "g", "range_ticks", "range_seconds",
            "l_cells", "tps", "fargs", "lookback_ticks",
        ),
    )
    def program(
        vals, has, tsg, smask, gid, lo, hi, t_end, *,
        fname: str, op: str, g: int, range_ticks: int,
        range_seconds: float, l_cells: int, tps: float, fargs: tuple,
        lookback_ticks: int,
    ):
        from greptimedb_tpu.ops import promql as K

        import jax.numpy as jnp

        def local(vals, has, tsg, smask, gid, lo, hi, t_end):
            out, pres = _eval_side(
                vals, has, tsg, smask, lo, hi, t_end, fname=fname,
                range_ticks=range_ticks, range_seconds=range_seconds,
                l_cells=l_cells, tps=tps, fargs=fargs,
                lookback_ticks=lookback_ticks,
            )
            vals_g, pres_g = K.aggregate_across_series_blocked(
                out, pres, gid, g + 1, op,
                total_series=vals.shape[0] * ns, ctx=ShardFoldCtx(ns),
            )
            return jnp.concatenate([
                vals_g[:g], pres_g[:g].astype(vals_g.dtype),
            ])

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS_SHARD, None), P(AXIS_SHARD, None),
                      P(AXIS_SHARD, None), P(AXIS_SHARD), P(AXIS_SHARD),
                      P(), P(), P()),
            out_specs=P(), check_rep=False,
        )(vals, has, tsg, smask, gid, lo, hi, t_end)

    return program


_SHARDED_QUERY = ProgramCache(_make_sharded_fused_query)


def _get_sharded_query(mesh):
    return _SHARDED_QUERY.get(mesh)


# ----------------------------------------------------------------------
# entry point
# ----------------------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=(
        "fname", "agg_op", "g_agg", "g", "b", "range_ticks",
        "range_seconds", "l_cells", "tps", "fargs", "lookback_ticks",
    ),
)
def _fused_hist_query(
    vals, has, tsg, smask, gid, slot, le, lo, hi, t_end, phi, *,
    fname: str, agg_op: str, g_agg: int, g: int, b: int,
    range_ticks: int, range_seconds: float, l_cells: int, tps: float,
    fargs: tuple, lookback_ticks: int,
):
    """histogram_quantile(phi, [sum by (le, ...)] (range_fn(sel))) as
    ONE XLA program: per-series range function, optional cross-series
    sum, scatter into (group, bucket) slots, quantile fold — no
    per-series host work at any cardinality (the fast-path answer to
    the reference's HistogramFold plan,
    /root/reference/src/promql/src/extension_plan/histogram_fold.rs)."""
    import jax.numpy as jnp

    from greptimedb_tpu.ops import promql as K

    out, pres = _eval_side(
        vals, has, tsg, smask, lo, hi, t_end, fname=fname,
        range_ticks=range_ticks, range_seconds=range_seconds,
        l_cells=l_cells, tps=tps, fargs=fargs,
        lookback_ticks=lookback_ticks,
    )
    if agg_op:
        # inner `sum by (le, ...)`: (S_pad, J) -> (G_agg, J); slot then
        # maps the AGGREGATED series into histogram cells. An aggregated
        # series EXISTS iff any member survived the matcher (the
        # generic engine's vector membership).
        src_exists = jax.ops.segment_sum(
            smask.astype(jnp.float32), gid, num_segments=g_agg + 1,
        )[:g_agg] > 0
        out, pres = K.aggregate_across_series(
            out, pres, gid, g_agg + 1, agg_op
        )
        out = out[:g_agg]
        pres = pres[:g_agg]
        sel_mask = src_exists
    else:
        sel_mask = smask
    # -> (G, B, J) via unique (group, bucket) slots
    seg = jnp.where(sel_mask & (slot >= 0), slot, jnp.int32(g * b))
    bsum = jax.ops.segment_sum(
        jnp.where(pres, out, 0.0).astype(jnp.float32), seg,
        num_segments=g * b + 1,
    )[:-1].reshape(g, b, -1)
    bpres = jax.ops.segment_sum(
        pres.astype(jnp.float32), seg, num_segments=g * b + 1,
    )[:-1].reshape(g, b, -1) > 0
    # Prometheus: a histogram without a +Inf bucket is undefined. The
    # +Inf bound is rank b-1 of the global layout; a group qualifies
    # only if a MATCHER-SURVIVING series fills that cell (the host
    # grouping is matcher-blind, so this must fold on device)
    inf_seg = jnp.where(
        sel_mask & (slot >= 0) & (slot % b == b - 1),
        slot // b, jnp.int32(g),
    )
    has_inf = jax.ops.segment_sum(
        jnp.ones(inf_seg.shape[0], jnp.float32), inf_seg,
        num_segments=g + 1,
    )[:g] > 0
    q_out, q_ok = K.histogram_quantile(
        le, bsum.transpose(0, 2, 1), bpres.transpose(0, 2, 1), phi,
    )
    q_ok = q_ok & has_inf[:, None]
    return jnp.concatenate([q_out, q_ok.astype(q_out.dtype)])


def _hist_grouping(entry: _Entry, table):
    """(labels, slot (S_pad,) int32, le (B,) f64, G, B) — groups are the
    label sets minus `le`; bucket index = rank of the series' le bound.
    None when the layout can't serve the fast path (no le tag, unparsable
    bounds, duplicate (group, le) series, or no +Inf bucket)."""
    key = ("__hist__",)
    hit = entry.group_cache.get(key)
    if hit is not None:
        return None if hit == "unservable" else hit

    def reject():
        # negative-cache: an unservable layout must not re-pay the
        # O(series) le-parsing on every query before falling back
        entry.group_cache[key] = "unservable"
        return None

    reg = entry.registry
    if "le" not in reg.tag_names:
        return reject()
    li = reg.tag_names.index("le")
    s = entry.num_series
    codes = reg.codes_matrix()[:s]
    le_raw = reg.tag_values("le")[:s]
    le_vals = np.full(s, np.nan)
    for i, t in enumerate(le_raw):
        if t == "":
            continue
        try:
            le_vals[i] = float(t.replace("+Inf", "inf"))
        except ValueError:
            pass
    valid = np.isfinite(le_vals) | np.isposinf(le_vals)
    if not valid.any():
        return reject()
    visible = set(table.tag_names)
    gcols = [
        i for i, nm in enumerate(reg.tag_names)
        if nm != "le" and nm in visible and not nm.startswith("__")
    ]
    uniq_le = np.unique(le_vals[valid])
    if not np.isposinf(uniq_le[-1]):
        return reject()  # no +Inf bucket: undefined histogram
    b = len(uniq_le)
    bidx = np.searchsorted(uniq_le, le_vals[valid])
    if gcols:
        sub = codes[valid][:, gcols]
        uniq_g, ginv = np.unique(sub, axis=0, return_inverse=True)
        g = len(uniq_g)
    else:
        uniq_g = np.zeros((1, 0), codes.dtype)
        ginv = np.zeros(int(valid.sum()), np.int64)
        g = 1
    slots = ginv * b + bidx
    if len(np.unique(slots)) != len(slots):
        return reject()  # duplicate (group, le): conflicting buckets
    slot_full = np.full(entry.s_pad, -1, np.int32)
    slot_full[np.nonzero(valid)[0]] = slots.astype(np.int32)
    labels = []
    for row in uniq_g:
        lab = {}
        for ci, code in zip(gcols, row):
            v = reg.dicts[ci].decode(int(code))
            if v != "" and reg.tag_names[ci] != "__name__":
                lab[reg.tag_names[ci]] = v
        labels.append(lab)
    sh = _series_sharding(getattr(entry, "mesh", None), 1)
    if sh is not None:
        d_slot = jax.device_put(slot_full, sh)
    else:
        import jax.numpy as jnp

        d_slot = jnp.asarray(slot_full)
    out = (labels, d_slot, uniq_le, g, b)
    if len(entry.group_cache) >= 128:
        entry.group_cache.pop(next(iter(entry.group_cache)))
    entry.group_cache[key] = out
    return out


def _resolve_fast_selector(engine, inner, ev):
    """Shared scaffold for the fast paths: match `range_fn(sel)` /
    bare instant selector, resolve table + grid entry, plan windows.
    Returns (entry, table, raw_matchers, fname, fargs, win) on success,
    "empty" for a resolvable-but-empty selector, None to fall back."""
    fargs: tuple = ()
    if isinstance(inner, Call) and inner.name in _PREFIX_FNS:
        # scalar-literal args ride as static fargs (phi, horizon, sf/tf)
        # in their EXACT generic-path positions — a misplaced scalar must
        # fall back so the generic engine rejects it consistently
        lead = _FN_LEAD_ARGS.get(inner.name, 0)
        trail = _FN_TRAIL_ARGS.get(inner.name, 0)
        args = inner.args
        if len(args) != lead + 1 + trail:
            return None
        if not all(isinstance(a, NumberLit)
                   for a in args[:lead] + args[lead + 1:]):
            return None
        fargs = tuple(
            float(a.value) for a in args[:lead] + args[lead + 1:]
        )
        sel = args[lead]
        if not isinstance(sel, VectorSelector) or sel.range_ms is None:
            return None
        fname = inner.name
        range_ms = sel.range_ms
    elif isinstance(inner, VectorSelector) and inner.range_ms is None:
        sel = inner
        fname = "__instant__"
        range_ms = ev.lookback_ms
    else:
        return None
    if sel.at_ms is not None:
        return None
    table, field_sel, raw_matchers = engine._resolve_table(sel)
    if table is None:
        return None
    try:
        fieldname = engine._value_field(table, field_sel)
    except Exception:  # noqa: BLE001 - resolution failure: generic path
        return None
    qe = getattr(engine.instance, "query_engine", None)
    mesh = getattr(qe, "mesh", None)
    entry = _CACHE.get_entry(table, fieldname, mesh=mesh,
                             mesh_opts=getattr(qe, "mesh_opts", None))
    if entry is None:
        return None
    if entry.num_series == 0:
        return "empty"
    win = _plan_windows(
        entry, ev, range_ms, sel.offset_ms,
        align_range=fname != "__instant__",
    )
    if win is None:
        return None
    return entry, table, raw_matchers, fname, fargs, win


def _note_mesh_decision(entry, *, auto_spmd_site: str | None = None):
    """Surface the entry's replicate-vs-shard decision for ONE fast-path
    query that actually EXECUTED (EXPLAIN + gtpu_mesh_*) — resolution
    alone records nothing, so queries that fall back to the generic
    engine (or resolve two operands) don't inflate the counters. Sites
    whose program runs single-device code over sharded grids (histogram
    and binary: XLA auto-SPMD picks its own combine order) tag the
    reason so the documented bit-identity exception stays visible."""
    dec = entry.mesh_decision
    if dec is None:
        return
    from greptimedb_tpu.query.planner import (
        MeshDecision, record_mesh_decision,
    )

    if auto_spmd_site is not None and dec.shard:
        dec = MeshDecision(
            dec.mode, f"{dec.reason}:auto_spmd_{auto_spmd_site}",
            dec.devices,
        )
    record_mesh_decision(dec, "promql")


def _hist_slots_from_labels(labels):
    """Histogram cells over AGGREGATED series labels (small lists):
    (out_labels, slot array, le array, G, B) or None."""
    keys, le_vals = [], []
    for lab in labels:
        le = lab.get("le")
        v = None
        if le is not None:
            try:
                v = float(str(le).replace("+Inf", "inf"))
            except ValueError:
                pass
        le_vals.append(v)
        keys.append(tuple(sorted(
            (k, val) for k, val in lab.items()
            if k not in ("le", "__name__")
        )))
    valid = [i for i, v in enumerate(le_vals) if v is not None]
    if not valid:
        return None
    uniq_le = np.unique(np.asarray([le_vals[i] for i in valid]))
    if not np.isposinf(uniq_le[-1]):
        return None
    b = len(uniq_le)
    uniq_keys = sorted({keys[i] for i in valid})
    kidx = {k: i for i, k in enumerate(uniq_keys)}
    g = len(uniq_keys)
    slot = np.full(len(labels), -1, np.int32)
    seen = set()
    for i in valid:
        s = kidx[keys[i]] * b + int(
            np.searchsorted(uniq_le, le_vals[i])
        )
        if s in seen:
            return None  # duplicate (group, le)
        seen.add(s)
        slot[i] = s
    out_labels = [dict(k) for k in uniq_keys]
    return out_labels, slot, uniq_le, g, b


def try_fast_histogram(engine, phi: float, inner, ev):
    """Serve `histogram_quantile(phi, range_fn(sel))`,
    `histogram_quantile(phi, sel)`, and
    `histogram_quantile(phi, sum by (le, ...)(range_fn(sel)))` from the
    grid cache. Returns a VectorValue, or None to fall back."""
    from greptimedb_tpu.promql.engine import VectorValue, _empty_vector

    agg = None
    if isinstance(inner, Agg) and inner.op == "sum" \
            and not inner.without and inner.grouping \
            and "le" in inner.grouping:
        agg = inner
        inner = inner.expr

    resolved = _resolve_fast_selector(engine, inner, ev)
    if resolved is None:
        _FAST_HITS.labels("fallback").inc()
        return None
    if resolved == "empty":
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    entry, table, raw_matchers, fname, fargs, win = resolved
    import jax.numpy as jnp

    if agg is not None:
        agg_labels, d_gid, g_agg = _grouping_dev(
            entry, table, agg.grouping, agg.without
        )
        slots = _hist_slots_from_labels(agg_labels)
        if slots is None:
            _FAST_HITS.labels("fallback").inc()
            return None
        labels, slot_np, uniq_le, g, b = slots
        d_slot = jnp.asarray(slot_np)
        agg_op = "sum"
    else:
        grouping = _hist_grouping(entry, table)
        if grouping is None:
            _FAST_HITS.labels("fallback").inc()
            return None
        labels, d_slot, uniq_le, g, b = grouping
        d_gid = jnp.zeros(entry.s_pad, jnp.int32)
        g_agg = 1
        agg_op = ""
    lo, hi, t_end, range_ticks, range_seconds, l_cells = win
    matchers = engine._to_registry_matchers(raw_matchers, table)
    smask, any_match = _matcher_mask_dev(entry, matchers)
    if not any_match:
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    lookback_ticks = max(int(ev.lookback_ms // entry.spec.unit), 1)
    _note_mesh_decision(entry, auto_spmd_site="histogram")
    from greptimedb_tpu.telemetry import device_trace

    from greptimedb_tpu.query import readback as _readback

    skey = ("hist", fname, agg_op, g_agg, g, b, range_ticks,
            range_seconds, l_cells, entry.spec.tps, fargs,
            lookback_ticks, float(phi),
            np.asarray(uniq_le).tobytes(),
            id(smask), id(d_gid), id(d_slot), id(lo), id(hi), id(t_end))
    with device_trace.device_call(
            "promql_histogram", key=("hist", fname, agg_op, g_agg, g, b,
                                     range_ticks, range_seconds,
                                     l_cells, entry.spec.tps, fargs,
                                     lookback_ticks)) as dcall:
        packed = _session_exec(entry, skey, lambda: dcall.run(
            _fused_hist_query,
            entry.vals, entry.has, entry.tsg, smask, d_gid, d_slot,
            jnp.asarray(uniq_le, jnp.float32), lo, hi, t_end,
            jnp.float32(phi),
            fname=fname, agg_op=agg_op, g_agg=g_agg, g=g, b=b,
            range_ticks=range_ticks,
            range_seconds=range_seconds, l_cells=l_cells,
            tps=entry.spec.tps, fargs=fargs,
            lookback_ticks=lookback_ticks,
        ))
        dcall.executed()
        packed_np = _readback.read_full(packed, np.float64)
        dcall.transfer(packed_np.nbytes, "readback")
    vals_np = packed_np[:g]
    pres_np = packed_np[g:] != 0.0
    keep = pres_np.any(axis=1)
    _FAST_HITS.labels("hit").inc()
    if not keep.all():
        idx = np.nonzero(keep)[0]
        return VectorValue(
            [labels[i] for i in idx], vals_np[idx], pres_np[idx]
        )
    return VectorValue(list(labels), vals_np, pres_np)


def try_fast(engine, e, ev):
    """Serve `agg(range_fn(selector))` / `agg(selector)` from the grid
    cache. Returns a VectorValue, or None to fall back to the generic
    path."""
    from greptimedb_tpu.promql.engine import VectorValue, _empty_vector

    if not isinstance(e, Agg) or e.op not in _SIMPLE_AGGS:
        return None
    resolved = _resolve_fast_selector(engine, e.expr, ev)
    if resolved is None:
        _FAST_HITS.labels("fallback").inc()
        return None
    if resolved == "empty":
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    entry, table, raw_matchers, fname, fargs, win = resolved
    lo, hi, t_end, range_ticks, range_seconds, l_cells = win
    matchers = engine._to_registry_matchers(raw_matchers, table)
    smask, any_match = _matcher_mask_dev(entry, matchers)
    if not any_match:
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    labels, gid, g = _grouping_dev(entry, table, e.grouping, e.without)
    lookback_ticks = max(int(ev.lookback_ms // entry.spec.unit), 1)
    program = (_fused_query if entry.mesh is None
               else _get_sharded_query(entry.mesh))
    _note_mesh_decision(entry)
    from greptimedb_tpu.telemetry import device_trace

    from greptimedb_tpu.query import readback as _readback

    skey = ("q", entry.mesh is None, fname, e.op, g, range_ticks,
            range_seconds, l_cells, entry.spec.tps, fargs,
            lookback_ticks, id(smask), id(gid), id(lo), id(hi),
            id(t_end))
    with device_trace.device_call(
            "promql", key=("promql", entry.mesh is None, fname, e.op,
                           g, range_ticks, range_seconds, l_cells,
                           entry.spec.tps, fargs, lookback_ticks),
            groups=g) as dcall:
        packed = _session_exec(entry, skey, lambda: dcall.run(
            program,
            entry.vals, entry.has, entry.tsg, smask, gid,
            lo, hi, t_end,
            fname=fname, op=e.op, g=g, range_ticks=range_ticks,
            range_seconds=range_seconds, l_cells=l_cells,
            tps=entry.spec.tps, fargs=fargs,
            lookback_ticks=lookback_ticks,
        ))
        dcall.executed()
        packed_np = _readback.read_full(packed, np.float64)
        dcall.transfer(packed_np.nbytes, "readback")
    vals_np = packed_np[:g]
    pres_np = packed_np[g:] != 0.0
    keep = pres_np.any(axis=1)
    _FAST_HITS.labels("hit").inc()
    if not keep.all():
        idx = np.nonzero(keep)[0]
        return VectorValue(
            [labels[i] for i in idx], vals_np[idx], pres_np[idx]
        )
    return VectorValue(list(labels), vals_np, pres_np)


# ----------------------------------------------------------------------
# per-series output labels (sid-aligned): topk and vector-vector outputs
# keep series identity, and building a million label dicts per QUERY
# would be the Python cliff the fast path exists to avoid — build them
# once per grid entry (same lifetime as the registry snapshot) instead
# ----------------------------------------------------------------------

def _series_labels(entry: _Entry, table) -> list[dict]:
    """Per-sid tag dicts (no __name__), aligned with the entry's sid
    space; built once per entry (cached on it, like group_cache)."""
    hit = entry.group_cache.get("__series_labels__")
    if hit is not None:
        return hit
    reg = entry.registry
    visible = set(table.tag_names)
    tag_names = [t for t in reg.tag_names
                 if t in visible and not t.startswith("__")]
    cols = {t: reg.tag_values(t) for t in tag_names}
    labels = []
    for s in range(entry.num_series):
        labels.append({
            t: str(cols[t][s]) for t in tag_names if cols[t][s] != ""
        })
    entry.group_cache["__series_labels__"] = labels
    return labels


def _series_labels_for(entry: _Entry, table, sids) -> list[dict]:
    """Tag dicts for just the requested sids (topk winners: O(k), not
    O(num_series)); memoized per entry alongside the bulk cache."""
    bulk = entry.group_cache.get("__series_labels__")
    if bulk is not None:
        return [dict(bulk[int(s)]) for s in sids]
    memo = entry.group_cache.setdefault("__series_labels_memo__", {})
    reg = entry.registry
    visible = set(table.tag_names)
    out = []
    for s in sids:
        s = int(s)
        lab = memo.get(s)
        if lab is None:
            lab = {
                k: str(v) for k, v in reg.series_tags(s).items()
                if v != "" and k in visible and not k.startswith("__")
            }
            memo[s] = lab
        out.append(dict(lab))
    return out


# ----------------------------------------------------------------------
# topk / bottomk over the grid cache
# ----------------------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=("fname", "k", "largest", "range_ticks",
                     "range_seconds", "l_cells", "tps", "fargs",
                     "lookback_ticks"),
)
def _fused_topk(
    vals, has, tsg, smask, lo, hi, t_end, *,
    fname: str, k: int, largest: bool, range_ticks: int,
    range_seconds: float, l_cells: int, tps: float, fargs: tuple,
    lookback_ticks: int,
):
    """range_fn/selector + per-step top-k as ONE XLA program; only the
    (k, J) winners cross back to the host (the extension-plan analog of
    the reference's TopK over SeriesDivide)."""
    import jax.numpy as jnp

    out, pres = _eval_side(
        vals, has, tsg, smask, lo, hi, t_end, fname=fname,
        range_ticks=range_ticks, range_seconds=range_seconds,
        l_cells=l_cells, tps=tps, fargs=fargs,
        lookback_ticks=lookback_ticks,
    )
    key = _topk_key(out, pres, largest)
    top_key, top_idx = jax.lax.top_k(key.T, k)       # (J, k)
    # presence gathered from the real mask; finite-key check drops the
    # absent fill slots when fewer than k series are present
    top_pres = (
        jnp.take_along_axis(pres.T, top_idx, axis=1)
        & jnp.isfinite(top_key)
    )
    top_vals = jnp.take_along_axis(out.T, top_idx, axis=1)
    # ONE packed (3J, k) f32 buffer = one device->host transfer (three
    # separate readbacks pay the dev-tunnel RTT three times). Winner
    # indices are exact in f32: s_pad < 2^24.
    return jnp.concatenate([
        top_vals.astype(jnp.float32),
        top_idx.astype(jnp.float32),
        top_pres.astype(jnp.float32),
    ])


def _topk_key(out, pres, largest: bool):
    """Descending sort key: present samples clamped to a finite range so
    genuine +-Inf values still rank above/below every absent slot (-inf
    fill); present NaN ranks below every real value but above absence
    (generic np.argsort puts NaN last), staying finite so the presence
    check keeps it when k exceeds the real winners."""
    import jax.numpy as jnp

    big = jnp.asarray(3.0e38, out.dtype)
    nan_key = jnp.asarray(-3.2e38, out.dtype)
    base = jnp.clip(out, -big, big)
    k_dir = base if largest else -base
    # canonicalize -0.0 -> +0.0: lax.top_k's total order ranks +0.0
    # above -0.0 while the ring-merge kernel compares them equal; a
    # single key representation keeps both paths bit-identical
    k_dir = k_dir + jnp.asarray(0.0, out.dtype)
    return jnp.where(
        pres, jnp.where(jnp.isnan(out), nan_key, k_dir), -jnp.inf
    )


def _make_sharded_fused_topk(mesh):
    """shard_map twin of _fused_topk using the dist_topk pattern
    (parallel/dist.py): each shard evaluates its series slice and takes
    a LOCAL per-step top-k, the (J, k)-sized winner sets all_gather in
    shard order, and one reselect over the ns*k candidates yields the
    global winners — only k rows per shard cross the ICI instead of the
    whole (S, J) matrix. Every global winner is inside its shard's local
    top-k, and candidate order (shard, then local rank) equals ascending
    global series index among equal keys, so selection — values, winner
    indices, tie-breaks — matches the single-device program exactly."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    @functools.partial(
        jax.jit,
        static_argnames=("fname", "k", "largest", "range_ticks",
                         "range_seconds", "l_cells", "tps", "fargs",
                         "lookback_ticks"),
    )
    def program(
        vals, has, tsg, smask, lo, hi, t_end, *,
        fname: str, k: int, largest: bool, range_ticks: int,
        range_seconds: float, l_cells: int, tps: float, fargs: tuple,
        lookback_ticks: int,
    ):
        import jax.numpy as jnp

        def local(vals, has, tsg, smask, lo, hi, t_end):
            out, pres = _eval_side(
                vals, has, tsg, smask, lo, hi, t_end, fname=fname,
                range_ticks=range_ticks, range_seconds=range_seconds,
                l_cells=l_cells, tps=tps, fargs=fargs,
                lookback_ticks=lookback_ticks,
            )
            s_loc = out.shape[0]
            key = _topk_key(out, pres, largest)
            kl = min(k, s_loc)
            l_key, l_idx = jax.lax.top_k(key.T, kl)    # (J, kl)
            base = jax.lax.axis_index(AXIS_SHARD) * jnp.int32(s_loc)
            l_gidx = base + l_idx.astype(jnp.int32)
            l_pres = jnp.take_along_axis(pres.T, l_idx, axis=1)
            l_vals = jnp.take_along_axis(out.T, l_idx, axis=1)
            cat = lambda x: jax.lax.all_gather(  # noqa: E731
                x, AXIS_SHARD, axis=1, tiled=True
            )
            c_key = cat(l_key)                         # (J, ns*kl)
            f_key, f_pos = jax.lax.top_k(c_key, k)
            f_vals = jnp.take_along_axis(cat(l_vals), f_pos, axis=1)
            f_idx = jnp.take_along_axis(cat(l_gidx), f_pos, axis=1)
            f_pres = (jnp.take_along_axis(cat(l_pres), f_pos, axis=1)
                      & jnp.isfinite(f_key))
            return jnp.concatenate([
                f_vals.astype(jnp.float32),
                f_idx.astype(jnp.float32),
                f_pres.astype(jnp.float32),
            ])

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS_SHARD, None), P(AXIS_SHARD, None),
                      P(AXIS_SHARD, None), P(AXIS_SHARD),
                      P(), P(), P()),
            out_specs=P(), check_rep=False,
        )(vals, has, tsg, smask, lo, hi, t_end)

    return program


def _make_sharded_fused_topk_pallas(mesh):
    """Pallas-kernel twin of _make_sharded_fused_topk: identical local
    candidate extraction, but the ns*k-candidate reselect is a ring of
    pairwise merge-path kernels (parallel/kernels/topk_merge.py) moving
    only the (J, k) winner planes hop-by-hop instead of all-gathering
    every shard's candidates everywhere. The sequential ring combines
    candidates in shard order with acc-wins tie-breaks — the same
    lower-index-wins order lax.top_k applies over the shard-ordered
    concat — so winners, values and indices stay bit-identical to the
    XLA twin (interpret-mode fuzz pins this)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from greptimedb_tpu.parallel.kernels import (
        interpret_mode, ring_topk_merge,
    )
    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    ns = int(mesh.devices.size)
    interp = interpret_mode()

    @functools.partial(
        jax.jit,
        static_argnames=("fname", "k", "largest", "range_ticks",
                         "range_seconds", "l_cells", "tps", "fargs",
                         "lookback_ticks"),
    )
    def program(
        vals, has, tsg, smask, lo, hi, t_end, *,
        fname: str, k: int, largest: bool, range_ticks: int,
        range_seconds: float, l_cells: int, tps: float, fargs: tuple,
        lookback_ticks: int,
    ):
        import jax.numpy as jnp

        def local(vals, has, tsg, smask, lo, hi, t_end):
            out, pres = _eval_side(
                vals, has, tsg, smask, lo, hi, t_end, fname=fname,
                range_ticks=range_ticks, range_seconds=range_seconds,
                l_cells=l_cells, tps=tps, fargs=fargs,
                lookback_ticks=lookback_ticks,
            )
            s_loc = out.shape[0]
            key = _topk_key(out, pres, largest)
            kl = min(k, s_loc)
            l_key, l_idx = jax.lax.top_k(key.T, kl)    # (J, kl)
            base = jax.lax.axis_index(AXIS_SHARD) * jnp.int32(s_loc)
            l_gidx = base + l_idx.astype(jnp.int32)
            l_pres = jnp.take_along_axis(pres.T, l_idx, axis=1)
            l_vals = jnp.take_along_axis(out.T, l_idx, axis=1)
            f_key, f_vals, f_idx, f_pres = ring_topk_merge(
                l_key, l_vals.astype(jnp.float32), l_gidx, l_pres,
                k=k, ns=ns, interpret=interp,
            )
            f_pres = f_pres & jnp.isfinite(f_key)
            return jnp.concatenate([
                f_vals.astype(jnp.float32),
                f_idx.astype(jnp.float32),
                f_pres.astype(jnp.float32),
            ])

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS_SHARD, None), P(AXIS_SHARD, None),
                      P(AXIS_SHARD, None), P(AXIS_SHARD),
                      P(), P(), P()),
            out_specs=P(), check_rep=False,
        )(vals, has, tsg, smask, lo, hi, t_end)

    return program


_SHARDED_TOPK = ProgramCache(_make_sharded_fused_topk)
_SHARDED_TOPK_PALLAS = ProgramCache(_make_sharded_fused_topk_pallas)


def _get_sharded_topk(mesh, kernel: bool = False):
    if kernel:
        return _SHARDED_TOPK_PALLAS.get(mesh)
    return _SHARDED_TOPK.get(mesh)


def try_fast_topk(engine, e, ev):
    """Serve global `topk/bottomk(k, range_fn(sel))` from the grid
    cache; grouped topk falls back to the generic engine."""
    from greptimedb_tpu.promql.engine import VectorValue, _empty_vector

    if not isinstance(e, Agg) or e.op not in ("topk", "bottomk"):
        return None
    if e.grouping or e.without:
        return None
    if not isinstance(e.param, NumberLit):
        return None
    k = int(e.param.value)
    if k <= 0:
        return _empty_vector(ev)
    resolved = _resolve_fast_selector(engine, e.expr, ev)
    if resolved is None:
        _FAST_HITS.labels("fallback").inc()
        return None
    if resolved == "empty":
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    entry, table, raw_matchers, fname, fargs, win = resolved
    lo, hi, t_end, range_ticks, range_seconds, l_cells = win
    matchers = engine._to_registry_matchers(raw_matchers, table)
    smask, any_match = _matcher_mask_dev(entry, matchers)
    if not any_match:
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    if entry.s_pad >= (1 << 24):
        # packed winner indices ride as f32 (exact only below 2^24);
        # beyond that the generic engine serves correctly
        return None
    lookback_ticks = max(int(ev.lookback_ms // entry.spec.unit), 1)
    kk = min(k, entry.num_series)
    use_kernel = False
    comm_bytes = 0
    if entry.mesh is not None:
        from greptimedb_tpu.query import planner as qplanner

        qe = getattr(engine.instance, "query_engine", None)
        kdec, kreason = qplanner.decide_kernel(
            "topk", series=entry.num_series, k=kk,
            opts=getattr(qe, "mesh_opts", None),
        )
        use_kernel = kdec == "pallas"
        qplanner.record_kernel_decision("topk", kdec, kreason)
        if use_kernel:
            from greptimedb_tpu.parallel.kernels import topk_comm_bytes

            comm_bytes = topk_comm_bytes(
                int(entry.mesh.devices.size), int(lo.shape[0]), kk
            )
    topk_prog = (_fused_topk if entry.mesh is None
                 else _get_sharded_topk(entry.mesh, kernel=use_kernel))
    _note_mesh_decision(entry)
    from greptimedb_tpu.telemetry import device_trace

    from greptimedb_tpu.query import readback as _readback

    skey = ("topk", entry.mesh is None, use_kernel, fname, kk,
            e.op == "topk",
            range_ticks, range_seconds, l_cells, entry.spec.tps, fargs,
            lookback_ticks, id(smask), id(lo), id(hi), id(t_end))
    with device_trace.device_call(
            "topk", key=("topk", entry.mesh is None, use_kernel, fname,
                         kk,
                         e.op == "topk", range_ticks, range_seconds,
                         l_cells, entry.spec.tps, fargs,
                         lookback_ticks),
            collective=use_kernel, comm_bytes=comm_bytes) as dcall:
        packed_dev = _session_exec(entry, skey, lambda: dcall.run(
            topk_prog,
            entry.vals, entry.has, entry.tsg, smask, lo, hi, t_end,
            fname=fname, k=kk, largest=e.op == "topk",
            range_ticks=range_ticks, range_seconds=range_seconds,
            l_cells=l_cells, tps=entry.spec.tps, fargs=fargs,
            lookback_ticks=lookback_ticks,
        ))
        dcall.executed()
        packed = _readback.read_full(packed_dev)
        dcall.transfer(packed.nbytes, "readback")
    jj = packed.shape[0] // 3
    top_vals = packed[:jj].astype(np.float64)      # (J, k)
    top_idx = packed[jj:2 * jj].astype(np.int64)
    top_pres = packed[2 * jj:] != 0.0
    j = top_vals.shape[0]
    sids = np.unique(top_idx[top_pres])
    if len(sids) == 0:
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    pos = {int(s): i for i, s in enumerate(sids)}
    vals_out = np.zeros((len(sids), j))
    pres_out = np.zeros((len(sids), j), bool)
    steps, ranks = np.nonzero(top_pres)
    rows_ = np.asarray([pos[int(s)] for s in top_idx[steps, ranks]])
    vals_out[rows_, steps] = top_vals[steps, ranks]
    pres_out[rows_, steps] = True
    labels = _series_labels_for(entry, table, sids)
    if fname == "__instant__":
        for lab in labels:
            lab["__name__"] = table.name
    _FAST_HITS.labels("hit").inc()
    return VectorValue(labels, vals_out, pres_out)


# ----------------------------------------------------------------------
# vector <op> vector over the grid cache: label matching on sid codes
# ----------------------------------------------------------------------

_BINARY_FAST_OPS = frozenset({
    "+", "-", "*", "/", "%", "^",
    ">", "<", ">=", "<=", "==", "!=",
})


def _apply_op_dev(op: str, a, b):
    import jax.numpy as jnp

    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return jnp.fmod(a, b)
    if op == "^":
        return jnp.power(a, b)
    return {
        ">": a > b, "<": a < b, ">=": a >= b, "<=": a <= b,
        "==": a == b, "!=": a != b,
    }[op]


@functools.partial(
    jax.jit,
    static_argnames=(
        "fname_l", "fname_r", "op", "bool_mod", "agg_op", "g",
        "range_ticks_l", "range_ticks_r", "range_seconds_l",
        "range_seconds_r", "l_cells_l", "l_cells_r", "tps",
        "fargs_l", "fargs_r", "lookback_ticks",
    ),
)
def _fused_binary(
    vals_l, has_l, tsg_l, smask_l, lo_l, hi_l, t_end_l,
    vals_r, has_r, tsg_r, smask_r, lo_r, hi_r, t_end_r,
    gid, *,
    fname_l: str, fname_r: str, op: str, bool_mod: bool, agg_op: str,
    g: int, range_ticks_l: int, range_ticks_r: int,
    range_seconds_l: float, range_seconds_r: float,
    l_cells_l: int, l_cells_r: int, tps: float,
    fargs_l: tuple, fargs_r: tuple, lookback_ticks: int,
):
    """vector<op>vector (one-to-one, default matching) fused on device:
    both sides share the table's sid space, so label matching IS sid
    alignment — no per-series host work (the reference vectorizes this
    as a DataFusion join on label columns; here the dictionary codes are
    already the join keys). Optional trailing aggregation."""
    import jax.numpy as jnp

    from greptimedb_tpu.ops import promql as K

    out_l, pres_l = _eval_side(
        vals_l, has_l, tsg_l, smask_l, lo_l, hi_l, t_end_l,
        fname=fname_l, range_ticks=range_ticks_l,
        range_seconds=range_seconds_l, l_cells=l_cells_l, tps=tps,
        fargs=fargs_l, lookback_ticks=lookback_ticks,
    )
    out_r, pres_r = _eval_side(
        vals_r, has_r, tsg_r, smask_r, lo_r, hi_r, t_end_r,
        fname=fname_r, range_ticks=range_ticks_r,
        range_seconds=range_seconds_r, l_cells=l_cells_r, tps=tps,
        fargs=fargs_r, lookback_ticks=lookback_ticks,
    )
    pres = pres_l & pres_r
    res = _apply_op_dev(op, out_l, out_r)
    if op in (">", "<", ">=", "<=", "==", "!="):
        if bool_mod:
            out = res.astype(out_l.dtype)
        else:
            # filtering comparison keeps the LEFT operand's sample
            pres = pres & res
            out = out_l
    else:
        out = res.astype(out_l.dtype)
    if agg_op:
        vals_g, pres_g = K.aggregate_across_series(out, pres, gid,
                                                   g + 1, agg_op)
        return jnp.concatenate([
            vals_g[:g], pres_g[:g].astype(vals_g.dtype),
        ])
    return jnp.concatenate([out, pres.astype(out.dtype)])


def _operand_shape_fast(expr) -> bool:
    """Static AST pre-check, BEFORE any entry resolution: a grid build
    can scan the whole table, so reject non-fast shapes for free."""
    if isinstance(expr, VectorSelector):
        return expr.range_ms is None
    return isinstance(expr, Call) and expr.name in _PREFIX_FNS


def _resolve_binary(engine, e, ev):
    """Both operands fast-resolve over the SAME series registry ->
    (entry_l, side_l, entry_r, side_r, table) or "empty" or None."""
    if not isinstance(e, Binary) or e.op not in _BINARY_FAST_OPS:
        return None
    m = e.matching
    if m.explicit or m.labels or m.group or m.include:
        return None  # only default one-to-one matching rides sid codes
    if not (_operand_shape_fast(e.lhs) and _operand_shape_fast(e.rhs)):
        return None
    left = _resolve_fast_selector(engine, e.lhs, ev)
    if left is None:
        return None
    right = _resolve_fast_selector(engine, e.rhs, ev)
    if right is None:
        return None
    if left == "empty" or right == "empty":
        return "empty"
    entry_l, table_l, matchers_l, fname_l, fargs_l, win_l = left
    entry_r, table_r, matchers_r, fname_r, fargs_r, win_r = right
    if entry_l.registry is not entry_r.registry:
        return None  # different sid spaces: generic label matching
    return (left, right, table_l)


def try_fast_binary(engine, e, ev, *, agg=None):
    """Serve `vecL <op> vecR` (and `agg(...)` around it) when both sides
    live on the same table's grid cache. Returns VectorValue or None."""
    from greptimedb_tpu.promql.engine import VectorValue, _empty_vector

    if agg is not None and agg.op not in _SIMPLE_AGGS:
        return None
    resolved = _resolve_binary(engine, e, ev)
    if resolved is None:
        return None
    if resolved == "empty":
        return _empty_vector(ev)
    left, right, table = resolved
    entry_l, _tl, raw_m_l, fname_l, fargs_l, win_l = left
    entry_r, _tr, raw_m_r, fname_r, fargs_r, win_r = right
    agg_op = ""
    gid = None
    g = 1
    labels = None
    if agg is not None:
        labels, gid, g = _grouping_dev(entry_l, table, agg.grouping,
                                       agg.without)
        agg_op = agg.op
    import jax.numpy as jnp

    smask_l, any_l = _matcher_mask_dev(
        entry_l, engine._to_registry_matchers(raw_m_l, table))
    smask_r, any_r = _matcher_mask_dev(
        entry_r, engine._to_registry_matchers(raw_m_r, table))
    if not (any_l and any_r):
        _FAST_HITS.labels("hit").inc()
        return _empty_vector(ev)
    lo_l, hi_l, t_end_l, rt_l, rs_l, lc_l = win_l
    lo_r, hi_r, t_end_r, rt_r, rs_r, lc_r = win_r
    if gid is None:
        gid = jnp.zeros(entry_l.s_pad, jnp.int32)
    lookback_ticks = max(int(ev.lookback_ms // entry_l.spec.unit), 1)
    _note_mesh_decision(entry_l, auto_spmd_site="binary")
    from greptimedb_tpu.telemetry import device_trace

    from greptimedb_tpu.query import readback as _readback

    skey = ("binary", id(entry_r), fname_l, fname_r, e.op,
            bool(e.bool_mod), agg_op, g, rt_l, rt_r, rs_l, rs_r,
            lc_l, lc_r, entry_l.spec.tps, fargs_l, fargs_r,
            lookback_ticks, id(smask_l), id(smask_r), id(gid),
            id(lo_l), id(hi_l), id(t_end_l), id(lo_r), id(hi_r),
            id(t_end_r), entry_r.version)
    with device_trace.device_call(
            "promql_binary", key=("binary", fname_l, fname_r, e.op,
                                  bool(e.bool_mod), agg_op, g, rt_l,
                                  rt_r, rs_l, rs_r, lc_l, lc_r,
                                  entry_l.spec.tps, fargs_l, fargs_r,
                                  lookback_ticks)) as dcall:
        packed = _session_exec(entry_l, skey, lambda: dcall.run(
            _fused_binary,
            entry_l.vals, entry_l.has, entry_l.tsg, smask_l,
            lo_l, hi_l, t_end_l,
            entry_r.vals, entry_r.has, entry_r.tsg, smask_r,
            lo_r, hi_r, t_end_r,
            gid,
            fname_l=fname_l, fname_r=fname_r, op=e.op,
            bool_mod=bool(e.bool_mod), agg_op=agg_op, g=g,
            range_ticks_l=rt_l, range_ticks_r=rt_r,
            range_seconds_l=rs_l, range_seconds_r=rs_r,
            l_cells_l=lc_l, l_cells_r=lc_r, tps=entry_l.spec.tps,
            fargs_l=fargs_l, fargs_r=fargs_r,
            lookback_ticks=lookback_ticks,
        ))
        dcall.executed()
        packed_np = _readback.read_full(packed, np.float64)
        dcall.transfer(packed_np.nbytes, "readback")
    if agg_op:
        vals_np = packed_np[:g]
        pres_np = packed_np[g:] != 0.0
        keep = pres_np.any(axis=1)
        _FAST_HITS.labels("hit").inc()
        if not keep.all():
            idx = np.nonzero(keep)[0]
            return VectorValue(
                [labels[i] for i in idx], vals_np[idx], pres_np[idx]
            )
        return VectorValue(list(labels), vals_np, pres_np)
    s = entry_l.num_series
    s_pad = entry_l.s_pad
    vals_np = packed_np[:s_pad][:s]
    pres_np = packed_np[s_pad:][:s] != 0.0
    keep = pres_np.any(axis=1)
    base = _series_labels(entry_l, table)
    _FAST_HITS.labels("hit").inc()
    if not keep.all():
        idx = np.nonzero(keep)[0]
        return VectorValue(
            [base[int(i)] for i in idx], vals_np[idx], pres_np[idx]
        )
    return VectorValue(list(base), vals_np, pres_np)


def invalidate_cache():
    _CACHE.invalidate()


def drop_table_entries(table):
    """Called by the catalog on DROP TABLE so grids don't pin dead tables."""
    _CACHE.drop_table(table)
