"""PromQL expression parser.

Grammar per the Prometheus spec, mirroring the surface the reference's
PromPlanner consumes (/root/reference/src/query/src/promql/planner.rs:172 —
which uses the promql-parser crate): selectors with matchers, range/offset
modifiers, subqueries, unary/binary operators with bool/on/ignoring/
group_left/group_right, aggregation operators with by/without, functions.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from greptimedb_tpu.errors import InvalidSyntaxError


# ----------------------------------------------------------------------
# AST
# ----------------------------------------------------------------------

@dataclass
class PromExpr:
    pass


@dataclass
class NumberLit(PromExpr):
    value: float


@dataclass
class StringLit(PromExpr):
    value: str


@dataclass
class Matcher:
    name: str
    op: str           # = != =~ !~
    value: str


@dataclass
class VectorSelector(PromExpr):
    name: str | None
    matchers: list[Matcher] = field(default_factory=list)
    range_ms: int | None = None        # set => matrix selector
    offset_ms: int = 0
    at_ms: int | None = None


@dataclass
class Subquery(PromExpr):
    expr: PromExpr
    range_ms: int
    step_ms: int | None               # None => default eval interval
    offset_ms: int = 0


@dataclass
class Unary(PromExpr):
    op: str
    expr: PromExpr


@dataclass
class VectorMatching:
    on: bool = False                   # True: on(...), False: ignoring(...)
    labels: list[str] = field(default_factory=list)
    group: str | None = None           # "left" | "right"
    include: list[str] = field(default_factory=list)
    explicit: bool = False


@dataclass
class Binary(PromExpr):
    op: str
    lhs: PromExpr
    rhs: PromExpr
    bool_mod: bool = False
    matching: VectorMatching = field(default_factory=VectorMatching)


@dataclass
class Agg(PromExpr):
    op: str                            # sum avg min max count topk ...
    expr: PromExpr
    param: PromExpr | None = None      # k for topk, phi for quantile, ...
    grouping: list[str] = field(default_factory=list)
    without: bool = False


@dataclass
class Call(PromExpr):
    name: str
    args: list[PromExpr] = field(default_factory=list)


AGG_OPS = {
    "sum", "avg", "min", "max", "count", "group", "stddev", "stdvar",
    "topk", "bottomk", "quantile", "count_values", "limitk", "limit_ratio",
}
_PARAM_AGGS = {"topk", "bottomk", "quantile", "count_values", "limitk",
               "limit_ratio"}

_DURATION_RE = re.compile(
    r"(?:(\d+)y)?(?:(\d+)w)?(?:(\d+)d)?(?:(\d+)h)?(?:(\d+)m)?"
    r"(?:(\d+)s)?(?:(\d+)ms)?"
)
_UNIT_MS = [
    ("y", 365 * 86400_000), ("w", 7 * 86400_000), ("d", 86400_000),
    ("h", 3600_000), ("m", 60_000), ("s", 1000), ("ms", 1),
]


def parse_duration_ms(text: str) -> int:
    m = _DURATION_RE.fullmatch(text.strip())
    if not m or not any(m.groups()):
        raise InvalidSyntaxError(f"invalid duration: {text!r}")
    total = 0
    for g, (_, ms) in zip(m.groups(), _UNIT_MS):
        if g:
            total += int(g) * ms
    return total


# ----------------------------------------------------------------------
# lexer
# ----------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<duration>\d+(?:y|w|d|h|m(?!s)|s|ms)(?:\d+(?:y|w|d|h|m(?!s)|s|ms))*)
  | (?P<number>
        0x[0-9a-fA-F]+
      | (?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?
      | [iI][nN][fF](?![a-zA-Z0-9_:.])
      | [nN][aA][nN](?![a-zA-Z0-9_:.]))
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<op>=~|!~|==|!=|<=|>=|<|>|=|\+|-|\*|/|%|\^|\(|\)|\{|\}|\[|\]|,|:|@)
  | (?P<ident>[a-zA-Z_:][a-zA-Z0-9_:.]*)
""", re.VERBOSE)


def _tokenize(src: str):
    tokens = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise InvalidSyntaxError(
                f"unexpected character {src[pos]!r} at {pos}"
            )
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append((kind, m.group(), m.start()))
    tokens.append(("eof", "", len(src)))
    return tokens


_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}
_SET_OPS = {"and", "or", "unless"}

# precedence (higher binds tighter)
_PRECEDENCE = {
    "or": 1, "and": 2, "unless": 2,
    "==": 3, "!=": 3, "<": 3, "<=": 3, ">": 3, ">=": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5, "atan2": 5,
    "^": 6,
}


class _Parser:
    def __init__(self, src: str):
        self.tokens = _tokenize(src)
        self.i = 0

    def peek(self, k: int = 0):
        return self.tokens[min(self.i + k, len(self.tokens) - 1)]

    def next(self):
        t = self.tokens[self.i]
        self.i = min(self.i + 1, len(self.tokens) - 1)
        return t

    def at(self, text: str) -> bool:
        return self.peek()[1] == text

    def at_kind(self, kind: str) -> bool:
        return self.peek()[0] == kind

    def eat(self, text: str) -> bool:
        if self.at(text):
            self.next()
            return True
        return False

    def expect(self, text: str):
        t = self.next()
        if t[1] != text:
            raise InvalidSyntaxError(
                f"expected {text!r}, got {t[1]!r} at {t[2]}"
            )

    # ------------------------------------------------------------------
    def parse(self) -> PromExpr:
        e = self.expr(0)
        t = self.peek()
        if t[0] != "eof":
            raise InvalidSyntaxError(f"trailing input at {t[2]}: {t[1]!r}")
        return e

    def expr(self, min_prec: int) -> PromExpr:
        lhs = self.unary()
        while True:
            t = self.peek()
            if t[0] == "op" and t[1] in _PRECEDENCE:
                op = t[1]
            elif t[0] == "ident" and t[1].lower() in (
                "and", "or", "unless", "atan2"
            ):
                op = t[1].lower()
            else:
                break
            prec = _PRECEDENCE[op]
            if prec < min_prec:
                break
            self.next()
            bool_mod = False
            matching = VectorMatching()
            if self.peek()[1] == "bool":
                self.next()
                bool_mod = True
            if self.peek()[1] in ("on", "ignoring"):
                matching.explicit = True
                matching.on = self.next()[1] == "on"
                matching.labels = self._label_list()
            if self.peek()[1] in ("group_left", "group_right"):
                matching.group = self.next()[1].removeprefix("group_")
                if self.at("("):
                    matching.include = self._label_list()
            # ^ is right-associative
            rhs = self.expr(prec + (0 if op == "^" else 1))
            lhs = Binary(op, lhs, rhs, bool_mod, matching)
        return lhs

    def _label_list(self) -> list[str]:
        self.expect("(")
        out = []
        if not self.at(")"):
            out.append(self.next()[1])
            while self.eat(","):
                if self.at(")"):
                    break
                out.append(self.next()[1])
        self.expect(")")
        return out

    def unary(self) -> PromExpr:
        # unary +/- binds between '*' and '^' (Prometheus: -1^2 == -(1^2))
        if self.at("-"):
            self.next()
            return Unary("-", self.expr(_PRECEDENCE["^"]))
        if self.at("+"):
            self.next()
            return self.expr(_PRECEDENCE["^"])
        return self.postfix()

    def postfix(self) -> PromExpr:
        e = self.primary()
        while True:
            if self.at("["):
                e = self._range_or_subquery(e)
            elif self.peek()[1] == "offset":
                self.next()
                neg = self.eat("-")
                d = self._duration()
                off = -d if neg else d
                if isinstance(e, VectorSelector):
                    e.offset_ms = off
                elif isinstance(e, Subquery):
                    e.offset_ms = off
                else:
                    raise InvalidSyntaxError("offset on non-selector")
            elif self.at("@"):
                self.next()
                t = self.next()
                if isinstance(e, VectorSelector):
                    e.at_ms = int(float(t[1]) * 1000)
                else:
                    raise InvalidSyntaxError("@ on non-selector")
            else:
                break
        return e

    def _duration(self) -> int:
        t = self.next()
        if t[0] == "duration":
            return parse_duration_ms(t[1])
        if t[0] == "number":
            return int(float(t[1]) * 1000)
        raise InvalidSyntaxError(f"expected duration at {t[2]}")

    def _range_or_subquery(self, e: PromExpr) -> PromExpr:
        self.expect("[")
        rng = self._duration()
        if self.eat(":"):
            step = None
            if not self.at("]"):
                step = self._duration()
            self.expect("]")
            return Subquery(e, rng, step)
        self.expect("]")
        if not isinstance(e, VectorSelector) or e.range_ms is not None:
            raise InvalidSyntaxError("range on non-vector selector")
        e.range_ms = rng
        return e

    def primary(self) -> PromExpr:
        t = self.peek()
        if t[0] == "number":
            self.next()
            txt = t[1].lower()
            if txt.startswith("0x"):
                return NumberLit(float(int(txt, 16)))
            if txt == "inf":
                return NumberLit(float("inf"))
            if txt == "nan":
                return NumberLit(float("nan"))
            return NumberLit(float(t[1]))
        if t[0] == "string":
            self.next()
            return StringLit(_unquote(t[1]))
        if t[1] == "(":
            self.next()
            e = self.expr(0)
            self.expect(")")
            return e
        if t[1] == "{":
            return VectorSelector(None, self._matchers())
        if t[0] == "ident":
            name = t[1]
            low = name.lower()
            if low in AGG_OPS and self.peek(1)[1] in ("(", "by", "without"):
                return self._aggregation(low)
            self.next()
            if self.at("("):
                return self._call(low)
            matchers = self._matchers() if self.at("{") else []
            return VectorSelector(name, matchers)
        raise InvalidSyntaxError(f"unexpected token {t[1]!r} at {t[2]}")

    def _matchers(self) -> list[Matcher]:
        self.expect("{")
        out = []
        while not self.at("}"):
            name = self.next()[1]
            op = self.next()[1]
            if op not in ("=", "!=", "=~", "!~"):
                raise InvalidSyntaxError(f"bad matcher op {op!r}")
            v = self.next()
            out.append(Matcher(name, op, _unquote(v[1])))
            if not self.eat(","):
                break
        self.expect("}")
        return out

    def _aggregation(self, op: str) -> PromExpr:
        self.next()  # op name
        grouping: list[str] = []
        without = False
        if self.peek()[1] in ("by", "without"):
            without = self.next()[1] == "without"
            grouping = self._label_list()
        self.expect("(")
        args = [self.expr(0)]
        while self.eat(","):
            args.append(self.expr(0))
        self.expect(")")
        if self.peek()[1] in ("by", "without"):
            without = self.next()[1] == "without"
            grouping = self._label_list()
        param = None
        if op in _PARAM_AGGS:
            if len(args) != 2:
                raise InvalidSyntaxError(f"{op} takes (param, vector)")
            param, expr = args
        else:
            if len(args) != 1:
                raise InvalidSyntaxError(f"{op} takes one vector")
            expr = args[0]
        return Agg(op, expr, param, grouping, without)

    def _call(self, name: str) -> PromExpr:
        self.expect("(")
        args = []
        if not self.at(")"):
            args.append(self.expr(0))
            while self.eat(","):
                args.append(self.expr(0))
        self.expect(")")
        return Call(name, args)


_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "\\": "\\", '"': '"', "'": "'",
    "a": "\a", "b": "\b", "f": "\f", "v": "\v",
}


def _unquote(s: str) -> str:
    body = s[1:-1]
    if "\\" not in body:
        return body
    # escape handling that leaves non-ASCII text intact (unicode_escape
    # would decode UTF-8 bytes as Latin-1)
    return re.sub(
        r"\\(u[0-9a-fA-F]{4}|x[0-9a-fA-F]{2}|.)",
        lambda m: (
            chr(int(m.group(1)[1:], 16))
            if m.group(1)[0] in ("u", "x") and len(m.group(1)) > 1
            else _ESCAPES.get(m.group(1), m.group(1))
        ),
        body,
    )


def parse_promql(src: str) -> PromExpr:
    return _Parser(src).parse()
