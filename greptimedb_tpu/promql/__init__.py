from greptimedb_tpu.promql.engine import PromEngine
from greptimedb_tpu.promql.parser import parse_promql

__all__ = ["PromEngine", "parse_promql"]
