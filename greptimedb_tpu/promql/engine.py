"""PromQL evaluation engine over the (series x time) device grid.

Capability counterpart of the reference's PromQL planning + execution
(/root/reference/src/query/src/promql/planner.rs PromPlanner and
/root/reference/src/promql/src/extension_plan/*): selectors scan storage and
scatter onto dense (S, T) grids (ops/grid.py — replacing SeriesDivide/
SeriesNormalize), instant selection and range functions run as device window
kernels (ops/window.py, ops/promql.py — replacing InstantManipulate/
RangeManipulate + the RangeArray UDFs), and cross-series aggregation is a
device segment reduction (aggregate_across_series). Label algebra (vector
matching, by/without grouping, label_replace) stays on the host where the
strings live.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

import numpy as np

from greptimedb_tpu.errors import (
    ExecutionError,
    PlanError,
    TableNotFoundError,
    UnsupportedError,
)
from greptimedb_tpu.promql import parser as P
from greptimedb_tpu.query.expr import compile_matcher
from greptimedb_tpu.promql.parser import (
    Agg,
    Binary,
    Call,
    Matcher,
    NumberLit,
    PromExpr,
    StringLit,
    Subquery,
    Unary,
    VectorSelector,
)

DEFAULT_LOOKBACK_MS = 300_000
_MAX_SERIES_GRID = 4096  # series-axis padding bucket cap per grid


@dataclass
class VectorValue:
    """Instant vector sampled at J aligned steps."""

    labels: list[dict]          # S label dicts
    values: np.ndarray          # (S, J) float64
    present: np.ndarray         # (S, J) bool

    @property
    def num_series(self) -> int:
        return len(self.labels)


@dataclass
class ScalarValue:
    values: np.ndarray          # (J,) float64


@dataclass
class StringValue:
    value: str


@dataclass
class MatrixValue:
    """A matrix selector's device-grid package, consumed by range
    functions."""

    labels: list[dict]
    vals: object                # (S_pad, T) device array
    has: object                 # (S_pad, T) device bool
    tsg: object                 # (S_pad, T) device int32
    windows: object             # ops.window.Windows
    spec: object                # ops.grid.GridSpec
    num_series: int


@dataclass
class EvalParams:
    start_ms: int
    end_ms: int
    step_ms: int
    lookback_ms: int = DEFAULT_LOOKBACK_MS

    @property
    def num_steps(self) -> int:
        return int((self.end_ms - self.start_ms) // self.step_ms) + 1

    @property
    def step_ts(self) -> np.ndarray:
        return (
            self.start_ms
            + np.arange(self.num_steps, dtype=np.int64) * self.step_ms
        )


def _series_bucket(s: int) -> int:
    b = 8
    while b < s and b < _MAX_SERIES_GRID:
        b *= 2
    return max(b, s)  # never truncate; beyond the cap pad exactly


class PromEngine:
    def __init__(self, instance, ctx=None):
        self.instance = instance
        self.ctx = ctx
        self._db = getattr(ctx, "database", "public") if ctx else "public"

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def query_range(self, promql: str, start_ms: int, end_ms: int,
                    step_ms: int, *, lookback_ms: int = DEFAULT_LOOKBACK_MS):
        expr = P.parse_promql(promql)
        ev = EvalParams(start_ms, end_ms, max(int(step_ms), 1), lookback_ms)
        return self._eval(expr, ev), ev

    def query_instant(self, promql: str, time_ms: int, *,
                      lookback_ms: int = DEFAULT_LOOKBACK_MS):
        expr = P.parse_promql(promql)
        ev = EvalParams(time_ms, time_ms, 1000, lookback_ms)
        return self._eval(expr, ev), ev

    def query_range_result(self, promql: str, start_ms: int, end_ms: int,
                           step_ms: int, *,
                           lookback_ms: int = DEFAULT_LOOKBACK_MS):
        """SQL-shaped output for TQL EVAL (ts, labels..., value)."""
        from greptimedb_tpu.query.executor import Col, QueryResult

        value, ev = self.query_range(
            promql, start_ms, end_ms, step_ms, lookback_ms=lookback_ms
        )
        step_ts = ev.step_ts
        if isinstance(value, ScalarValue):
            return QueryResult(
                ["ts", "value"],
                [Col(step_ts), Col(value.values)],
            )
        v = _to_vector(value, ev)
        label_keys = sorted({k for lab in v.labels for k in lab})
        ts_col, val_col = [], []
        lab_cols = {k: [] for k in label_keys}
        for s in range(v.num_series):
            pres = v.present[s]
            idx = np.nonzero(pres)[0]
            ts_col.append(step_ts[idx])
            val_col.append(v.values[s][idx])
            for k in label_keys:
                lab_cols[k].extend([v.labels[s].get(k, "")] * len(idx))
        ts_all = np.concatenate(ts_col) if ts_col else np.zeros(0, np.int64)
        val_all = np.concatenate(val_col) if val_col else np.zeros(0)
        order = np.argsort(ts_all, kind="stable")
        cols = [Col(ts_all[order]), Col(val_all[order])]
        names = ["ts", "value"]
        for k in label_keys:
            names.append(k)
            cols.append(Col(np.asarray(lab_cols[k], object)[order]))
        return QueryResult(names, cols)

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _eval(self, e: PromExpr, ev: EvalParams):
        if isinstance(e, NumberLit):
            return ScalarValue(np.full(ev.num_steps, e.value))
        if isinstance(e, StringLit):
            return StringValue(e.value)
        if isinstance(e, VectorSelector):
            if e.range_ms is not None:
                raise PlanError(
                    "matrix selector must be wrapped in a range function"
                )
            return self._eval_instant_selector(e, ev)
        if isinstance(e, Unary):
            v = self._eval(e.expr, ev)
            if isinstance(v, ScalarValue):
                return ScalarValue(-v.values)
            if isinstance(v, VectorValue):
                return VectorValue(
                    [_drop_name(l) for l in v.labels], -v.values, v.present
                )
            raise PlanError("unary - on strings")
        if isinstance(e, Binary):
            return self._eval_binary(e, ev)
        if isinstance(e, Agg):
            from greptimedb_tpu.promql import fast as F

            hit = F.try_fast(self, e, ev)
            if hit is None:
                hit = F.try_fast_topk(self, e, ev)
            if hit is None and isinstance(e.expr, Binary):
                hit = F.try_fast_binary(self, e.expr, ev, agg=e)
            if hit is not None:
                return hit
            return self._eval_agg(e, ev)
        if isinstance(e, Call):
            return self._eval_call(e, ev)
        if isinstance(e, Subquery):
            raise PlanError(
                "subquery must be consumed by a range function"
            )
        raise UnsupportedError(f"cannot evaluate: {e!r}")

    # ------------------------------------------------------------------
    # selectors
    # ------------------------------------------------------------------
    def _resolve_table(self, sel: VectorSelector):
        name = sel.name
        field_sel = None
        matchers = []
        for m in sel.matchers:
            if m.name == "__name__":
                if m.op != "=":
                    raise UnsupportedError("__name__ supports = only")
                name = m.value
            elif m.name == "__field__":
                field_sel = m.value
            else:
                matchers.append(m)
        if name is None:
            raise PlanError("selector has no metric name")
        table = self.instance.catalog.maybe_table(self._db, name)
        if table is None and self._db != "public":
            table = self.instance.catalog.maybe_table("public", name)
        return table, field_sel, matchers

    def _value_field(self, table, field_sel: str | None) -> str:
        names = table.field_names
        if field_sel is not None:
            if field_sel not in names:
                raise TableNotFoundError(
                    f"field {field_sel!r} not in {table.name}"
                )
            return field_sel
        if "greptime_value" in names:
            return "greptime_value"
        if "value" in names:
            return "value"
        if len(names) == 1:
            return names[0]
        raise PlanError(
            f"table {table.name} has {len(names)} fields; use "
            '{__field__="..."}'
        )

    def _to_registry_matchers(self, matchers: list[Matcher], table):
        out = []
        for m in matchers:
            if m.op == "=":
                out.append((m.name, "eq", m.value))
            elif m.op == "!=":
                out.append((m.name, "ne", m.value))
            elif m.op == "=~":
                out.append((m.name, "re", compile_matcher(m.value)))
            else:
                out.append((m.name, "nre", compile_matcher(m.value)))
        return out

    def _scan_grid(self, sel: VectorSelector, ev: EvalParams,
                   range_ms: int) -> MatrixValue | None:
        """Scan + gridify one selector. Window semantics per PromQL:
        samples in (t - range, t]. Offset shifts the data window."""
        import jax.numpy as jnp

        from greptimedb_tpu.ops import grid as G
        from greptimedb_tpu.ops import window as W

        table, field_sel, raw_matchers = self._resolve_table(sel)
        if table is None:
            return None
        fieldname = self._value_field(table, field_sel)
        off = sel.offset_ms
        start = ev.start_ms - off
        end = ev.end_ms - off
        if sel.at_ms is not None:
            start = end = sel.at_ms
        data = table.scan(
            ts_min=start - range_ms + 1,
            ts_max=end,
            field_names=[fieldname],
            matchers=self._to_registry_matchers(raw_matchers, table) or None,
        )
        if data.rows is None or len(data.rows) == 0:
            spec, windows = W.plan_grid_and_windows(
                start, end, ev.step_ms, range_ms,
            )
            return MatrixValue([], None, None, None, windows, spec, 0)
        rows = data.rows
        # grid resolution must divide the sample interval or samples
        # collapse into one cell per window; derive it from the data
        uniq_ts = np.unique(rows.ts)
        interval = (
            int(np.gcd.reduce(np.diff(uniq_ts))) if len(uniq_ts) > 1 else None
        )
        spec, windows = W.plan_grid_and_windows(
            start, end, ev.step_ms, range_ms, data_interval_ms=interval,
        )
        uniq_sids, compact = np.unique(rows.sid, return_inverse=True)
        s = len(uniq_sids)
        s_pad = _series_bucket(s)
        labels = []
        visible = set(table.tag_names)
        for sid in uniq_sids:
            lab = dict(data.registry.series_tags(int(sid)))
            # only the table's own tags, and never internal (__table_id)
            # columns — a metric-engine logical scan returns the physical
            # registry
            lab = {
                k: v for k, v in lab.items()
                if v != "" and k in visible and not k.startswith("__")
            }
            lab["__name__"] = table.name
            labels.append(lab)

        cell = spec.cell_of(rows.ts).astype(np.int32)
        tsrel = spec.device_ts(rows.ts)
        vals = rows.fields[fieldname].astype(np.float32)
        mask = np.ones(len(rows), bool)
        if rows.field_valid is not None and fieldname in rows.field_valid:
            mask = rows.field_valid[fieldname].copy()
        gvals, ghas, gtsg = G.gridify(
            jnp.asarray(compact.astype(np.int32)),
            jnp.asarray(cell),
            jnp.asarray(tsrel),
            jnp.asarray(vals),
            jnp.asarray(mask),
            s_pad, spec.num_cells,
        )
        return MatrixValue(labels, gvals, ghas, gtsg, windows, spec, s)

    def _eval_instant_selector(self, sel: VectorSelector, ev: EvalParams
                               ) -> VectorValue:
        from greptimedb_tpu.ops import window as W
        import jax.numpy as jnp

        mat = self._scan_grid(sel, ev, ev.lookback_ms)
        if mat is None or mat.num_series == 0:
            return _empty_vector(ev)
        lookback_ticks = max(int(ev.lookback_ms // mat.spec.unit), 1)
        v, p = W.instant_lookback(
            mat.vals, mat.has, mat.tsg,
            jnp.asarray(mat.windows.hi), jnp.asarray(mat.windows.t_end),
            lookback_ticks,
        )
        s = mat.num_series
        return VectorValue(
            mat.labels,
            np.asarray(v, np.float64)[:s],
            np.asarray(p)[:s],
        )

    # ------------------------------------------------------------------
    # range functions & subqueries
    # ------------------------------------------------------------------
    def _eval_matrix(self, e: PromExpr, ev: EvalParams) -> MatrixValue:
        if isinstance(e, VectorSelector):
            if e.range_ms is None:
                raise PlanError("range function needs a matrix selector [d]")
            return self._scan_grid(e, ev, e.range_ms) or MatrixValue(
                [], None, None, None, None, None, 0
            )
        if isinstance(e, Subquery):
            return self._eval_subquery(e, ev)
        raise PlanError(
            "range function argument must be a matrix selector or subquery"
        )

    def _eval_subquery(self, e: Subquery, ev: EvalParams) -> MatrixValue:
        import jax.numpy as jnp

        from greptimedb_tpu.ops import grid as G
        from greptimedb_tpu.ops import window as W

        step = e.step_ms or ev.step_ms
        off = e.offset_ms
        inner_start = ev.start_ms - e.range_ms - off
        # inner steps aligned to the subquery step (Prometheus floors to a
        # multiple of the step)
        inner_start = (inner_start // step) * step
        inner_ev = EvalParams(inner_start, ev.end_ms - off, step,
                              ev.lookback_ms)
        inner = self._eval(e.expr, inner_ev)
        if isinstance(inner, ScalarValue):
            inner = VectorValue([{}], inner.values[None, :],
                                np.ones((1, len(inner.values)), bool))
        if not isinstance(inner, VectorValue):
            raise PlanError("subquery inner expression must be a vector")
        s = inner.num_series
        spec = G.GridSpec.build(inner_start - step, step,
                                inner_ev.num_steps + 1)
        # windows over the inner-step grid for the outer range evaluation
        _, windows = W.plan_grid_and_windows(
            ev.start_ms - off, ev.end_ms - off, ev.step_ms, e.range_ms,
            data_interval_ms=step,
        )
        # rebuild windows against this spec: cell i holds inner step at
        # inner_start + (i-1)*step
        hi = np.minimum(
            ((ev.step_ts - off) - spec.t0) // spec.res, spec.num_cells - 1
        ).astype(np.int32)
        w_cells = max(e.range_ms // step, 1)
        lo = np.maximum(hi - w_cells, 0).astype(np.int32)
        t_end = (((ev.step_ts - off) - spec.t0) // spec.unit).astype(np.int32)
        windows = W.Windows(
            lo=lo, hi=hi, t_end=t_end,
            range_ticks=int(e.range_ms // spec.unit),
            range_seconds=e.range_ms / 1000.0,
        )
        s_pad = _series_bucket(max(s, 1))
        vals = np.zeros((s_pad, spec.num_cells), np.float32)
        has = np.zeros((s_pad, spec.num_cells), bool)
        tsg = np.zeros((s_pad, spec.num_cells), np.int32)
        cells = spec.cell_of(inner_ev.step_ts).astype(np.int64)
        dts = spec.device_ts(inner_ev.step_ts)
        vals[:s, cells] = inner.values.astype(np.float32)
        has[:s, cells] = inner.present
        tsg[:, cells] = dts[None, :]
        return MatrixValue(
            [_drop_name(l) for l in inner.labels],
            jnp.asarray(vals), jnp.asarray(has), jnp.asarray(tsg),
            windows, spec, s,
        )

    def _range_function(self, name: str, e: Call, ev: EvalParams
                        ) -> VectorValue:
        from greptimedb_tpu.ops import promql as K

        vec_arg = e.args[-1]
        args: tuple = ()
        if name == "quantile_over_time":
            args = (self._const_scalar(e.args[0], ev),)
            vec_arg = e.args[1]
        elif name == "predict_linear":
            args = (self._const_scalar(e.args[1], ev),)
            vec_arg = e.args[0]
        elif name == "holt_winters":
            args = (
                self._const_scalar(e.args[1], ev),
                self._const_scalar(e.args[2], ev),
            )
            vec_arg = e.args[0]
        mat = self._eval_matrix(vec_arg, ev)
        if mat.num_series == 0:
            if name == "absent_over_time":
                return _absent_result(vec_arg, ev)
            return _empty_vector(ev)
        s = mat.num_series
        if name == "absent_over_time":
            # joint semantics: 1 where NO matching series had samples
            _, pres_k = K.eval_range_function(
                "present_over_time", mat.vals, mat.has, mat.tsg,
                mat.windows, mat.spec,
            )
            had = np.asarray(pres_k)[:s].any(axis=0)
            return _absent_vector(vec_arg, ev, ~had)
        out, present = K.eval_range_function(
            name, mat.vals, mat.has, mat.tsg, mat.windows, mat.spec,
            args=args,
        )
        vals = np.asarray(out, np.float64)[:s]
        pres = np.asarray(present)[:s]
        labels = [_drop_name(l) for l in mat.labels]
        return VectorValue(labels, vals, pres)

    def _const_scalar(self, e: PromExpr, ev: EvalParams) -> float:
        v = self._eval(e, ev)
        if isinstance(v, ScalarValue):
            return float(v.values[0])
        raise PlanError("expected a scalar parameter")

    # ------------------------------------------------------------------
    # aggregation operators
    # ------------------------------------------------------------------
    def _eval_agg(self, e: Agg, ev: EvalParams) -> VectorValue:
        v = self._eval(e.expr, ev)
        if isinstance(v, ScalarValue):
            v = VectorValue([{}], v.values[None, :],
                            np.ones((1, ev.num_steps), bool))
        if not isinstance(v, VectorValue):
            raise PlanError(f"{e.op} needs an instant vector")
        if v.num_series == 0:
            return _empty_vector(ev)

        out_labels, gid, g = _group_labels(v.labels, e.grouping, e.without)

        if e.op in ("sum", "avg", "min", "max", "count", "group", "stddev",
                    "stdvar"):
            import jax.numpy as jnp

            from greptimedb_tpu.ops.promql import aggregate_across_series

            vals, pres = aggregate_across_series(
                jnp.asarray(v.values), jnp.asarray(v.present),
                jnp.asarray(gid.astype(np.int32)), g, e.op,
            )
            return VectorValue(
                out_labels, np.asarray(vals, np.float64), np.asarray(pres)
            )
        if e.op in ("topk", "bottomk"):
            k = int(self._const_scalar(e.param, ev))
            return _topk(v, gid, g, k, largest=e.op == "topk")
        if e.op == "limitk":
            # k arbitrary series per group, independent of values
            k = int(self._const_scalar(e.param, ev))
            keep_idx = []
            seen: dict[int, int] = {}
            for i in range(v.num_series):
                c = seen.get(int(gid[i]), 0)
                if c < k:
                    keep_idx.append(i)
                    seen[int(gid[i])] = c + 1
            return VectorValue(
                [v.labels[i] for i in keep_idx],
                v.values[keep_idx], v.present[keep_idx],
            )
        if e.op == "limit_ratio":
            r = self._const_scalar(e.param, ev)
            k = max(int(math.ceil(abs(r) * v.num_series)), 1)
            return _topk(v, gid, g, k, largest=r >= 0)
        if e.op == "quantile":
            phi = self._const_scalar(e.param, ev)
            return _quantile_agg(v, out_labels, gid, g, phi)
        if e.op == "count_values":
            label = self._eval(e.param, ev)
            if not isinstance(label, StringValue):
                raise PlanError("count_values needs a label name string")
            return _count_values(v, label.value, e.grouping, e.without, ev)
        raise UnsupportedError(f"aggregation {e.op}")

    # ------------------------------------------------------------------
    # binary operators
    # ------------------------------------------------------------------
    def _eval_binary(self, e: Binary, ev: EvalParams):
        from greptimedb_tpu.promql import fast as F

        hit = F.try_fast_binary(self, e, ev)
        if hit is not None:
            return hit
        lhs = self._eval(e.lhs, ev)
        rhs = self._eval(e.rhs, ev)
        op = e.op
        if isinstance(lhs, ScalarValue) and isinstance(rhs, ScalarValue):
            out = _apply_op(op, lhs.values, rhs.values)
            if op in P._CMP_OPS:
                out = out.astype(np.float64)
            return ScalarValue(out)
        if isinstance(lhs, VectorValue) and isinstance(rhs, ScalarValue):
            return _vector_scalar(e, lhs, rhs.values, scalar_on_right=True)
        if isinstance(lhs, ScalarValue) and isinstance(rhs, VectorValue):
            return _vector_scalar(e, rhs, lhs.values, scalar_on_right=False)
        if isinstance(lhs, VectorValue) and isinstance(rhs, VectorValue):
            if op in ("and", "or", "unless"):
                return _set_op(e, lhs, rhs)
            return _vector_vector(e, lhs, rhs)
        raise PlanError(f"bad operand types for {op}")

    # ------------------------------------------------------------------
    # function calls
    # ------------------------------------------------------------------
    def _eval_call(self, e: Call, ev: EvalParams):
        from greptimedb_tpu.ops.promql import RANGE_FUNCTIONS

        name = e.name
        if name in RANGE_FUNCTIONS:
            return self._range_function(name, e, ev)
        if name == "histogram_quantile":
            phi = self._const_scalar(e.args[0], ev)
            from greptimedb_tpu.promql import fast as _fast

            res = _fast.try_fast_histogram(self, phi, e.args[1], ev)
            if res is not None:
                return res
            v = self._eval(e.args[1], ev)
            return _histogram_quantile(v, phi, ev)
        if name == "scalar":
            v = self._eval(e.args[0], ev)
            if not isinstance(v, VectorValue):
                raise PlanError("scalar() needs a vector")
            out = np.full(ev.num_steps, np.nan)
            if v.num_series:
                one = (v.present.sum(axis=0) == 1)
                idx = np.argmax(v.present, axis=0)
                vals = v.values[idx, np.arange(v.values.shape[1])]
                out = np.where(one, vals, np.nan)
            return ScalarValue(out)
        if name == "vector":
            v = self._eval(e.args[0], ev)
            if isinstance(v, ScalarValue):
                return VectorValue([{}], v.values[None, :],
                                   np.ones((1, ev.num_steps), bool))
            return v
        if name == "time":
            return ScalarValue(ev.step_ts.astype(np.float64) / 1000.0)
        if name == "timestamp":
            v = self._eval(e.args[0], ev)
            if not isinstance(v, VectorValue):
                raise PlanError("timestamp() needs a vector")
            # evaluation-time semantics: the sample's timestamp == step time
            ts = np.broadcast_to(
                ev.step_ts.astype(np.float64) / 1000.0, v.values.shape
            )
            return VectorValue([_drop_name(l) for l in v.labels],
                               ts.copy(), v.present.copy())
        if name == "absent":
            v = self._eval(e.args[0], ev)
            if not isinstance(v, VectorValue):
                raise PlanError("absent() needs a vector")
            if v.num_series == 0:
                absent = np.ones(ev.num_steps, bool)
            else:
                absent = ~v.present.any(axis=0)
            return _absent_vector(e.args[0], ev, absent)
        if name in ("sort", "sort_desc"):
            v = self._eval(e.args[0], ev)
            if not isinstance(v, VectorValue) or v.num_series == 0:
                return v
            key = np.where(v.present[:, -1], v.values[:, -1], -np.inf)
            order = np.argsort(key, kind="stable")
            if name == "sort_desc":
                order = order[::-1]
            return VectorValue(
                [v.labels[i] for i in order], v.values[order],
                v.present[order],
            )
        if name == "label_replace":
            return self._label_replace(e, ev)
        if name == "label_join":
            return self._label_join(e, ev)
        if name in ("round",):
            v = self._eval(e.args[0], ev)
            to = self._const_scalar(e.args[1], ev) if len(e.args) > 1 else 1.0
            return _map_vector(v, lambda x: np.round(x / to) * to)
        if name == "clamp":
            v = self._eval(e.args[0], ev)
            lo = self._const_scalar(e.args[1], ev)
            hi = self._const_scalar(e.args[2], ev)
            return _map_vector(v, lambda x: np.clip(x, lo, hi))
        if name == "clamp_min":
            v = self._eval(e.args[0], ev)
            lo = self._const_scalar(e.args[1], ev)
            return _map_vector(v, lambda x: np.maximum(x, lo))
        if name == "clamp_max":
            v = self._eval(e.args[0], ev)
            hi = self._const_scalar(e.args[1], ev)
            return _map_vector(v, lambda x: np.minimum(x, hi))
        if name in _MATH_FUNCS:
            v = self._eval(e.args[0], ev) if e.args else None
            fn = _MATH_FUNCS[name]
            if v is None:
                raise PlanError(f"{name} needs an argument")
            return _map_vector(v, fn)
        if name in _TIME_COMPONENT_FUNCS:
            fn = _TIME_COMPONENT_FUNCS[name]
            if e.args:
                v = self._eval(e.args[0], ev)
                return _map_vector(v, lambda x: fn(x * 1000.0))
            t = ev.step_ts.astype(np.float64)
            return ScalarValue(fn(t))
        if name == "pi":
            return ScalarValue(np.full(ev.num_steps, math.pi))
        raise UnsupportedError(f"function {name}")

    def _label_replace(self, e: Call, ev: EvalParams) -> VectorValue:
        v = self._eval(e.args[0], ev)
        dst = _expect_str(self._eval(e.args[1], ev))
        repl = _expect_str(self._eval(e.args[2], ev))
        src = _expect_str(self._eval(e.args[3], ev))
        regex = re.compile(_expect_str(self._eval(e.args[4], ev)))
        if not isinstance(v, VectorValue):
            raise PlanError("label_replace needs a vector")
        labels = []
        for lab in v.labels:
            val = lab.get(src, "")
            m = regex.fullmatch(val)
            lab = dict(lab)
            if m:
                new = m.expand(_go_template_to_python(repl))
                if new:
                    lab[dst] = new
                else:
                    lab.pop(dst, None)
            labels.append(lab)
        return VectorValue(labels, v.values.copy(), v.present.copy())

    def _label_join(self, e: Call, ev: EvalParams) -> VectorValue:
        v = self._eval(e.args[0], ev)
        dst = _expect_str(self._eval(e.args[1], ev))
        sep = _expect_str(self._eval(e.args[2], ev))
        srcs = [_expect_str(self._eval(a, ev)) for a in e.args[3:]]
        if not isinstance(v, VectorValue):
            raise PlanError("label_join needs a vector")
        labels = []
        for lab in v.labels:
            lab = dict(lab)
            lab[dst] = sep.join(lab.get(s, "") for s in srcs)
            labels.append(lab)
        return VectorValue(labels, v.values.copy(), v.present.copy())


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------

def _empty_vector(ev: EvalParams) -> VectorValue:
    return VectorValue([], np.zeros((0, ev.num_steps)),
                       np.zeros((0, ev.num_steps), bool))


def _to_vector(v, ev: EvalParams) -> VectorValue:
    if isinstance(v, VectorValue):
        return v
    if isinstance(v, ScalarValue):
        return VectorValue([{}], v.values[None, :],
                           np.ones((1, ev.num_steps), bool))
    raise ExecutionError("expected vector result")


def _drop_name(lab: dict) -> dict:
    return {k: v for k, v in lab.items() if k != "__name__"}


def _group_labels(labels: list[dict], grouping: list[str], without: bool):
    """Group series by by/without label sets. Returns (group label dicts,
    per-series gid, num groups)."""
    keys = []
    out_labels_map: dict[tuple, int] = {}
    gid = np.zeros(len(labels), np.int32)
    out_labels: list[dict] = []
    for i, lab in enumerate(labels):
        if without:
            g = {k: v for k, v in lab.items()
                 if k not in grouping and k != "__name__"}
        else:
            g = {k: lab[k] for k in grouping if k in lab}
        key = tuple(sorted(g.items()))
        j = out_labels_map.get(key)
        if j is None:
            j = len(out_labels)
            out_labels_map[key] = j
            out_labels.append(g)
        gid[i] = j
    return out_labels, gid, len(out_labels)


def _apply_op(op: str, a, b):
    with np.errstate(divide="ignore", invalid="ignore"):
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return np.fmod(a, b)
        if op == "^":
            return np.power(a, b)
        if op == "atan2":
            return np.arctan2(a, b)
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    raise UnsupportedError(f"operator {op}")


def _vector_scalar(e: Binary, v: VectorValue, s: np.ndarray,
                   *, scalar_on_right: bool):
    a = v.values
    b = s[None, :]
    if not scalar_on_right:
        a, b = b, a
    out = _apply_op(e.op, a, b)
    labels = [_drop_name(l) for l in v.labels]
    if e.op in P._CMP_OPS:
        if e.bool_mod:
            return VectorValue(labels, out.astype(np.float64),
                               v.present.copy())
        keep = v.present & np.asarray(out, bool)
        return VectorValue(labels, v.values.copy(), keep)
    return VectorValue(labels, np.asarray(out, np.float64), v.present.copy())


def _match_key(lab: dict, matching) -> tuple:
    if matching.explicit and matching.on:
        return tuple(sorted(
            (k, lab.get(k, "")) for k in matching.labels
        ))
    ignore = set(matching.labels) | {"__name__"}
    return tuple(sorted(
        (k, v) for k, v in lab.items() if k not in ignore
    ))


def _vector_vector(e: Binary, lhs: VectorValue, rhs: VectorValue
                   ) -> VectorValue:
    m = e.matching
    many_side = m.group  # "left" | "right" | None
    one, many = (rhs, lhs) if many_side in (None, "left") else (lhs, rhs)
    one_index: dict[tuple, int] = {}
    for i, lab in enumerate(one.labels):
        k = _match_key(lab, m)
        if k in one_index:
            raise ExecutionError(
                "many-to-many vector matching: duplicate series on the "
                f"'one' side for key {dict(k)}"
            )
        one_index[k] = i
    if many_side is None:
        # one-to-one: duplicate keys on the other side are equally illegal
        seen: set[tuple] = set()
        for lab in many.labels:
            k = _match_key(lab, m)
            if k in seen and k in one_index:
                raise ExecutionError(
                    "many-to-many vector matching: duplicate series on "
                    f"both sides for key {dict(k)}"
                )
            seen.add(k)
    labels, vals, pres = [], [], []
    for i, lab in enumerate(many.labels):
        k = _match_key(lab, m)
        j = one_index.get(k)
        if j is None:
            continue
        li = i if many is lhs else j     # index into lhs
        ri = i if many is rhs else j     # index into rhs
        out = _apply_op(e.op, lhs.values[li], rhs.values[ri])
        p = lhs.present[li] & rhs.present[ri]
        if many_side is None:
            out_lab = dict(k)            # one-to-one: the matched key only
        else:
            out_lab = _drop_name(dict(many.labels[i]))
            for inc in m.include:
                if inc in one.labels[j]:
                    out_lab[inc] = one.labels[j][inc]
                else:
                    out_lab.pop(inc, None)
        if e.op in P._CMP_OPS:
            if e.bool_mod:
                vals.append(out.astype(np.float64))
                pres.append(p)
            else:
                # filtering comparison keeps the LEFT operand's sample
                keep = p & np.asarray(out, bool)
                vals.append(lhs.values[li].astype(np.float64))
                pres.append(keep)
        else:
            vals.append(np.asarray(out, np.float64))
            pres.append(p)
        labels.append(out_lab)
    if not labels:
        j = lhs.values.shape[1]
        return VectorValue([], np.zeros((0, j)), np.zeros((0, j), bool))
    return VectorValue(labels, np.stack(vals), np.stack(pres))


def _set_op(e: Binary, lhs: VectorValue, rhs: VectorValue) -> VectorValue:
    m = e.matching
    rhs_keys: dict[tuple, int] = {}
    for i, lab in enumerate(rhs.labels):
        rhs_keys.setdefault(_match_key(lab, m), i)
    if e.op == "and":
        labels, vals, pres = [], [], []
        for i, lab in enumerate(lhs.labels):
            j = rhs_keys.get(_match_key(lab, m))
            if j is None:
                continue
            labels.append(lab)
            vals.append(lhs.values[i])
            pres.append(lhs.present[i] & rhs.present[j])
        if not labels:
            return VectorValue([], np.zeros((0, lhs.values.shape[1])),
                               np.zeros((0, lhs.values.shape[1]), bool))
        return VectorValue(labels, np.stack(vals), np.stack(pres))
    if e.op == "unless":
        labels, vals, pres = [], [], []
        for i, lab in enumerate(lhs.labels):
            j = rhs_keys.get(_match_key(lab, m))
            p = lhs.present[i].copy()
            if j is not None:
                p &= ~rhs.present[j]
            labels.append(lab)
            vals.append(lhs.values[i])
            pres.append(p)
        if not labels:
            return VectorValue([], np.zeros((0, lhs.values.shape[1])),
                               np.zeros((0, lhs.values.shape[1]), bool))
        return VectorValue(labels, np.stack(vals), np.stack(pres))
    # or: lhs plus rhs series whose key has no present lhs point
    lhs_keys: dict[tuple, int] = {}
    for i, lab in enumerate(lhs.labels):
        lhs_keys.setdefault(_match_key(lab, m), i)
    labels = list(lhs.labels)
    vals = [lhs.values[i] for i in range(lhs.num_series)]
    pres = [lhs.present[i] for i in range(lhs.num_series)]
    for i, lab in enumerate(rhs.labels):
        j = lhs_keys.get(_match_key(lab, m))
        p = rhs.present[i].copy()
        if j is not None:
            p &= ~lhs.present[j]
        if p.any():
            labels.append(lab)
            vals.append(rhs.values[i])
            pres.append(p)
    return VectorValue(labels, np.stack(vals), np.stack(pres))


def _topk(v: VectorValue, gid: np.ndarray, g: int, k: int, *,
          largest: bool) -> VectorValue:
    """Per-step top/bottom k within each group; keeps original series
    labels (Prometheus semantics)."""
    if k <= 0:
        j = v.values.shape[1]
        return VectorValue([], np.zeros((0, j)), np.zeros((0, j), bool))
    keep = np.zeros_like(v.present)
    key = np.where(v.present, v.values, -np.inf if largest else np.inf)
    for grp in range(g):
        sel = np.nonzero(gid == grp)[0]
        if len(sel) == 0:
            continue
        sub = key[sel]  # (Sg, J)
        if largest:
            order = np.argsort(-sub, axis=0, kind="stable")
        else:
            order = np.argsort(sub, axis=0, kind="stable")
        topk_rows = order[:k]  # (k, J)
        cols = np.broadcast_to(
            np.arange(sub.shape[1]), topk_rows.shape
        )
        mask = np.zeros_like(sub, bool)
        mask[topk_rows, cols] = True
        keep[sel] = mask & v.present[sel]
    nz = keep.any(axis=1)
    return VectorValue(
        [v.labels[i] for i in np.nonzero(nz)[0]],
        v.values[nz], keep[nz],
    )


def _quantile_agg(v: VectorValue, out_labels, gid, g, phi) -> VectorValue:
    j = v.values.shape[1]
    out = np.zeros((g, j))
    pres = np.zeros((g, j), bool)
    for grp in range(g):
        sel = gid == grp
        sub = v.values[sel]
        sp = v.present[sel]
        cnt = sp.sum(axis=0)
        pres[grp] = cnt > 0
        masked = np.where(sp, sub, np.inf)
        srt = np.sort(masked, axis=0)
        rank = phi * np.maximum(cnt - 1, 0)
        lo = np.floor(rank).astype(int)
        hi = np.ceil(rank).astype(int)
        cols = np.arange(j)
        n_rows = srt.shape[0]
        v_lo = srt[np.clip(lo, 0, max(n_rows - 1, 0)), cols]
        v_hi = srt[np.clip(hi, 0, max(n_rows - 1, 0)), cols]
        out[grp] = v_lo + (v_hi - v_lo) * (rank - lo)
    return VectorValue(out_labels, out, pres)


def _count_values(v: VectorValue, label: str, grouping, without,
                  ev: EvalParams) -> VectorValue:
    out: dict[tuple, np.ndarray] = {}
    out_labels: dict[tuple, dict] = {}
    base_labels, gid, g = _group_labels(v.labels, grouping, without)
    for i in range(v.num_series):
        for jj in np.nonzero(v.present[i])[0]:
            val = v.values[i, jj]
            sval = _format_value(val)
            lab = dict(base_labels[gid[i]])
            lab[label] = sval
            key = tuple(sorted(lab.items()))
            if key not in out:
                out[key] = np.zeros(ev.num_steps)
                out_labels[key] = lab
            out[key][jj] += 1
    if not out:
        return _empty_vector(ev)
    labels = [out_labels[k] for k in out]
    vals = np.stack([out[k] for k in out])
    return VectorValue(labels, vals, vals > 0)


def _format_value(x: float) -> str:
    if x == int(x) and abs(x) < 1e15:
        return str(int(x))
    return repr(float(x))


def _histogram_quantile(v, phi: float, ev: EvalParams) -> VectorValue:
    import jax.numpy as jnp

    from greptimedb_tpu.ops.promql import histogram_quantile as hq

    if not isinstance(v, VectorValue) or v.num_series == 0:
        return _empty_vector(ev)
    groups: dict[tuple, list[tuple[float, int]]] = {}
    group_labels: dict[tuple, dict] = {}
    for i, lab in enumerate(v.labels):
        le = lab.get("le")
        if le is None:
            continue
        rest = {k: val for k, val in lab.items()
                if k not in ("le", "__name__")}
        key = tuple(sorted(rest.items()))
        try:
            le_v = float(le)
        except ValueError:
            continue
        groups.setdefault(key, []).append((le_v, i))
        group_labels[key] = rest
    if not groups:
        return _empty_vector(ev)
    # batch groups sharing an identical bucket layout
    by_layout: dict[tuple, list[tuple]] = {}
    for key, items in groups.items():
        items.sort()
        layout = tuple(le for le, _ in items)
        by_layout.setdefault(layout, []).append(key)
    labels_out, vals_out, pres_out = [], [], []
    j = v.values.shape[1]
    for layout, keys in by_layout.items():
        le = np.asarray(layout, np.float64)
        if not math.isinf(le[-1]):
            continue  # no +Inf bucket: undefined histogram
        bucket_stack = np.stack([
            np.stack([v.values[i] for _, i in groups[key]], axis=-1)
            for key in keys
        ])  # (G, J, B)
        mask_stack = np.stack([
            np.stack([v.present[i] for _, i in groups[key]], axis=-1)
            for key in keys
        ])
        out, ok = hq(
            jnp.asarray(le), jnp.asarray(bucket_stack),
            jnp.asarray(mask_stack), phi,
        )
        out = np.asarray(out, np.float64)
        ok = np.asarray(ok)
        for gi, key in enumerate(keys):
            labels_out.append(group_labels[key])
            vals_out.append(out[gi])
            pres_out.append(ok[gi])
    if not labels_out:
        return _empty_vector(ev)
    return VectorValue(labels_out, np.stack(vals_out), np.stack(pres_out))


def _absent_result(sel, ev: EvalParams) -> VectorValue:
    return _absent_vector(sel, ev, np.ones(ev.num_steps, bool))


def _absent_vector(sel, ev: EvalParams, absent: np.ndarray) -> VectorValue:
    lab = {}
    if isinstance(sel, VectorSelector):
        for m in sel.matchers:
            if m.op == "=" and m.name not in ("__name__", "__field__"):
                lab[m.name] = m.value
    if not absent.any():
        return _empty_vector(ev)
    return VectorValue([lab], np.ones((1, ev.num_steps)), absent[None, :])


def _map_vector(v, fn):
    if isinstance(v, ScalarValue):
        with np.errstate(invalid="ignore", divide="ignore"):
            return ScalarValue(np.asarray(fn(v.values), np.float64))
    if not isinstance(v, VectorValue):
        raise PlanError("expected vector")
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.asarray(fn(v.values), np.float64)
    return VectorValue([_drop_name(l) for l in v.labels], out,
                       v.present.copy())


def _expect_str(v) -> str:
    if isinstance(v, StringValue):
        return v.value
    raise PlanError("expected a string literal")


def _go_template_to_python(repl: str) -> str:
    """Prometheus uses $1-style references; python re.expand uses \\1."""
    return re.sub(r"\$(\d+)", r"\\\1", re.sub(r"\$\{(\d+)\}", r"\\\1", repl))


_MATH_FUNCS = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor, "exp": np.exp,
    "sqrt": np.sqrt, "ln": np.log, "log2": np.log2, "log10": np.log10,
    "sin": np.sin, "cos": np.cos, "tan": np.tan, "asin": np.arcsin,
    "acos": np.arccos, "atan": np.arctan, "sinh": np.sinh, "cosh": np.cosh,
    "tanh": np.tanh, "asinh": np.arcsinh, "acosh": np.arccosh,
    "atanh": np.arctanh, "deg": np.degrees, "rad": np.radians,
    "sgn": np.sign,
}


def _dt64(ms):
    return np.asarray(ms, "datetime64[ms]")


_TIME_COMPONENT_FUNCS = {
    "minute": lambda ms: ((np.asarray(ms, np.int64) // 60_000) % 60).astype(
        np.float64
    ),
    "hour": lambda ms: ((np.asarray(ms, np.int64) // 3_600_000) % 24).astype(
        np.float64
    ),
    "day_of_week": lambda ms: (
        ((np.asarray(ms, np.int64) // 86_400_000) + 4) % 7
    ).astype(np.float64),
    "day_of_month": lambda ms: (
        (_dt64(np.asarray(ms, np.int64)).astype("datetime64[D]")
         - _dt64(np.asarray(ms, np.int64)).astype("datetime64[M]")
         .astype("datetime64[D]")).astype(np.int64) + 1
    ).astype(np.float64),
    "day_of_year": lambda ms: (
        (_dt64(np.asarray(ms, np.int64)).astype("datetime64[D]")
         - _dt64(np.asarray(ms, np.int64)).astype("datetime64[Y]")
         .astype("datetime64[D]")).astype(np.int64) + 1
    ).astype(np.float64),
    "month": lambda ms: (
        _dt64(np.asarray(ms, np.int64)).astype("datetime64[M]")
        .astype(np.int64) % 12 + 1
    ).astype(np.float64),
    "year": lambda ms: (
        _dt64(np.asarray(ms, np.int64)).astype("datetime64[Y]")
        .astype(np.int64) + 1970
    ).astype(np.float64),
    "days_in_month": lambda ms: (
        ((_dt64(np.asarray(ms, np.int64)).astype("datetime64[M]") + 1)
         .astype("datetime64[D]")
         - _dt64(np.asarray(ms, np.int64)).astype("datetime64[M]")
         .astype("datetime64[D]")).astype(np.int64)
    ).astype(np.float64),
}
