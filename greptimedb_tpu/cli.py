"""Process entry point: `python -m greptimedb_tpu.cli <role> start`.

Counterpart of /root/reference/src/cmd/src/bin/greptime.rs subcommands
(standalone/frontend/datanode/metasrv/flownode start + cli), with the
reference's layered options resolution (src/cmd/src/options.rs):
defaults < --config-file TOML < GREPTIMEDB_TPU__* env < CLI flags
(config.py).

Role topology:
- standalone: everything in one process (engine + all protocol servers
  + flows), like the reference's `greptime standalone start`.
- datanode: storage engine + Arrow Flight data RPC (+ admin HTTP);
  optionally registers and heartbeats against a metasrv.
- frontend: stateless protocol servers (HTTP/MySQL/Postgres) forwarding
  SQL to datanodes over Flight (servers/remote.py).
- metasrv: control plane over HTTP — KV/CAS, registration, heartbeats,
  region routes (servers/meta_http.py).
- flownode: engine + flow manager, ingest-facing HTTP only.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

from greptimedb_tpu.config import load_options

from greptimedb_tpu import concurrency

ROLES = ("standalone", "frontend", "datanode", "metasrv", "flownode")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="greptimedb-tpu")
    sub = ap.add_subparsers(dest="role", required=True)

    for role in ROLES:
        rp = sub.add_parser(role)
        r_sub = rp.add_subparsers(dest="cmd", required=True)
        start = r_sub.add_parser("start")
        start.add_argument("-c", "--config-file", default=None)
        start.add_argument("--data-home", default=None)
        start.add_argument("--http-addr", default=None)
        start.add_argument("--mysql-addr", default=None,
                           help="MySQL wire address ('' disables)")
        start.add_argument("--postgres-addr", default=None,
                           help="PostgreSQL wire address ('' disables)")
        start.add_argument("--flight-addr", default=None,
                           help="Arrow Flight (gRPC) address "
                                "('' disables)")
        start.add_argument("--metasrv-addr", default=None,
                           help="metasrv to register with (datanode) "
                                "or to serve on (metasrv)")
        start.add_argument("--datanode-addrs", default=None,
                           help="comma-separated datanode flight "
                                "addresses (frontend)")
        start.add_argument("--flownode-addr", default=None,
                           help="flownode flight address for flow "
                                "mirroring (frontend)")
        start.add_argument("--node-id", type=int, default=None)
        start.add_argument("--no-flows", action="store_true")

    lint = sub.add_parser(
        "lint", help="run gtlint (AST correctness linter) over the "
                     "given paths; exits non-zero on findings",
    )
    lint.add_argument("paths", nargs="*", default=None)
    lint.add_argument("--format", choices=("text", "json"),
                      default="text")
    lint.add_argument("--baseline", default=None)
    lint.add_argument("--no-baseline", action="store_true")
    lint.add_argument("--write-baseline", action="store_true")
    lint.add_argument("--select", default=None)
    lint.add_argument("--changed", default=None, metavar="REF",
                      help="lint only files differing from this git "
                           "ref (fast pre-commit runs)")
    lint.add_argument("--list-rules", action="store_true")
    lint.add_argument("--contracts-dump", action="store_true",
                      help="emit the extracted whole-program contract "
                           "model (tickets/actions/errors/knobs/"
                           "metrics) as sorted JSON and exit 0")
    lint.add_argument("--explain", default=None, metavar="GTxxx",
                      help="print one rule's doc, examples, and "
                           "suppression syntax (exit 2 on unknown id)")

    san = sub.add_parser(
        "san", help="run a command under the gtsan concurrency "
                    "sanitizer (GTPU_SAN=1) and report lock-order "
                    "cycles, blocking-under-lock, and thread/pool "
                    "leaks; exits non-zero on findings",
    )
    san.add_argument("cmd", nargs=argparse.REMAINDER,
                     help="command to run (prefix with --)")
    san.add_argument("--format", choices=("text", "json"),
                     default="text")
    san.add_argument("--baseline", default=None)
    san.add_argument("--no-baseline", action="store_true")
    san.add_argument("--hold-time-ms", type=float, default=None)
    san.add_argument("--report", default=None)

    cli = sub.add_parser("cli")
    # the real default lives on the parent; subcommand flags use SUPPRESS
    # so `cli --data-home X <cmd>` isn't clobbered by subparser defaults
    cli.add_argument("--data-home", default="./greptimedb_tpu_data")
    cli_sub = cli.add_subparsers(dest="cli_cmd")
    repl = cli_sub.add_parser("repl")
    repl.add_argument("--data-home", default=argparse.SUPPRESS)
    exp = cli_sub.add_parser("export")
    exp.add_argument("--data-home", default=argparse.SUPPRESS)
    exp.add_argument("--output-dir", required=True)
    exp.add_argument("--target", default="all",
                     choices=("all", "schema", "data"))
    exp.add_argument("--database", default=None)
    imp = cli_sub.add_parser("import")
    imp.add_argument("--data-home", default=argparse.SUPPRESS)
    imp.add_argument("--input-dir", required=True)
    imp.add_argument("--database", default=None)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.role == "lint":
        from greptimedb_tpu.tools.lint.runner import main as lint_main

        fwd = list(args.paths or [])
        fwd += ["--format", args.format]
        if args.baseline:
            fwd += ["--baseline", args.baseline]
        for flag in ("no_baseline", "write_baseline", "list_rules",
                     "contracts_dump"):
            if getattr(args, flag):
                fwd.append("--" + flag.replace("_", "-"))
        if args.select:
            fwd += ["--select", args.select]
        if args.changed:
            fwd += ["--changed", args.changed]
        if args.explain:
            fwd += ["--explain", args.explain]
        return lint_main(fwd)
    if args.role == "san":
        from greptimedb_tpu.tools.san.runner import main as san_main

        fwd = []
        if args.format != "text":
            fwd += ["--format", args.format]
        if args.baseline:
            fwd += ["--baseline", args.baseline]
        if args.no_baseline:
            fwd.append("--no-baseline")
        if args.hold_time_ms is not None:
            fwd += ["--hold-time-ms", str(args.hold_time_ms)]
        if args.report:
            fwd += ["--report", args.report]
        cmd = list(args.cmd)
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]
        return san_main(fwd + ["--"] + cmd if cmd else fwd)
    if args.role == "cli":
        cmd = getattr(args, "cli_cmd", None)
        if cmd == "export":
            from greptimedb_tpu.tools import export_data

            report = export_data(args.data_home, args.output_dir,
                                 target=args.target,
                                 database=args.database)
            for db, r in report.items():
                print(f"exported {db}: {r['tables']} tables, "
                      f"{r['rows']} rows")
            return 0
        if cmd == "import":
            from greptimedb_tpu.tools import import_data

            report = import_data(args.data_home, args.input_dir,
                                 database=args.database)
            for db, r in report.items():
                print(f"imported {db}: {r['tables']} statements, "
                      f"{r['rows']} rows")
            return 0
        return _repl(args)
    opts = load_options(
        args.role,
        config_file=args.config_file,
        cli_overrides={
            "data_home": args.data_home,
            "http.addr": args.http_addr,
            "mysql.addr": args.mysql_addr,
            "postgres.addr": args.postgres_addr,
            "grpc.addr": args.flight_addr,
            "metasrv.addr": args.metasrv_addr,
            "datanode.metasrv_addr": args.metasrv_addr,
            "datanode.node_id": args.node_id,
            "frontend.datanode_addrs": (
                args.datanode_addrs.split(",")
                if args.datanode_addrs else None
            ),
            "frontend.flownode_addr": args.flownode_addr,
            "flow.enable": False if args.no_flows else None,
        },
    )
    from greptimedb_tpu.session import set_default_timezone

    # top-level `default_timezone` knob: the timezone new sessions start
    # in until a `SET time_zone` overrides it
    set_default_timezone(opts.get("default_timezone", "UTC"))
    san_sec = opts.section("sanitizer")
    if san_sec.get("enable"):
        # [sanitizer] TOML: enable BEFORE any server builds its locks
        # so every primitive in this process is instrumented, and
        # render the findings to stderr at exit — an instrumented run
        # must never be a silent no-op
        from greptimedb_tpu.tools import san as _san
        from greptimedb_tpu.tools.san.report import attach_exit_report

        attach_exit_report(
            _san.enable(_san.SanConfig.from_options(san_sec)))
    return {
        "standalone": _start_standalone,
        "frontend": _start_frontend,
        "datanode": _start_datanode,
        "metasrv": _start_metasrv,
        "flownode": _start_flownode,
    }[args.role](opts)


def _split(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _serve_until_signal(closers):
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        for c in reversed(closers):
            try:
                c()
            except Exception as e:  # noqa: BLE001
                # keep tearing the rest down, but say what broke
                print(f"# shutdown: {c} failed: {e}", flush=True)
    return 0


def _wire_protocols(inst, opts, closers) -> None:
    """MySQL/Postgres/Flight servers shared by standalone + frontend."""
    if opts.get("mysql.enable", True) and opts.get("mysql.addr"):
        from greptimedb_tpu.servers.mysql import MySqlServer

        mh, mp = _split(opts.get("mysql.addr"))
        srv = MySqlServer(inst, addr=mh, port=mp).start()
        closers.append(srv.close)
        print(f"greptimedb-tpu mysql protocol on {mh}:{srv.port}",
              flush=True)
    if opts.get("postgres.enable", True) and opts.get("postgres.addr"):
        from greptimedb_tpu.servers.postgres import PostgresServer

        ph, pp = _split(opts.get("postgres.addr"))
        srv = PostgresServer(inst, addr=ph, port=pp).start()
        closers.append(srv.close)
        print(f"greptimedb-tpu postgres protocol on {ph}:{srv.port}",
              flush=True)


def _http_server(inst, opts, closers):
    if not (opts.get("http.enable", True) and opts.get("http.addr")):
        return None
    from greptimedb_tpu.servers.http import HttpServer

    hh, hp = _split(opts.get("http.addr"))
    server = HttpServer(
        inst, addr=hh, port=hp,
        tls_cert=opts.get("http.tls.cert_path") or None,
        tls_key=opts.get("http.tls.key_path") or None,
        influxdb_enable=bool(opts.get("influxdb.enable", True)),
        opentsdb_enable=bool(opts.get("opentsdb.enable", True)),
    ).start()
    closers.append(server.stop)
    return server


def _telemetry(opts, closers, *, mode: str):
    if not opts.get("telemetry.enable", False):
        return
    endpoint = opts.get("telemetry.endpoint", "")
    if not endpoint:
        return
    from greptimedb_tpu.telemetry.report import TelemetryTask

    task = TelemetryTask(
        opts.get("data_home"), endpoint=endpoint,
        interval_s=float(opts.get("telemetry.interval_s", 1800.0)),
        mode=mode,
    ).start()
    closers.append(task.stop)


def _export_metrics(inst, opts, closers, *, role: str = ""):
    """Self-import node metrics (independent of the HTTP server; a node
    with http disabled still exports). Series are stamped with
    node/role labels so two roles exporting into the same
    greptime_metrics database never collide into one series."""
    if not opts.get("export_metrics.enable", False):
        return
    if not hasattr(getattr(inst, "catalog", None), "create_database"):
        return  # stateless roles (frontend) have no local storage
    from greptimedb_tpu.telemetry.export import ExportMetricsTask

    task = ExportMetricsTask(
        inst,
        db=opts.get("export_metrics.db", "greptime_metrics"),
        interval_s=float(opts.get("export_metrics.write_interval_s", 30.0)),
        role=role or None,
    ).start()
    closers.append(task.stop)


def _flight_server(inst, opts, closers):
    if not (opts.get("grpc.enable", True) and opts.get("grpc.addr")):
        return None
    try:
        from greptimedb_tpu.servers.flight import FlightFrontend
    except ImportError:
        print("# pyarrow.flight unavailable; flight disabled", flush=True)
        return None
    fh, fp = _split(opts.get("grpc.addr"))
    srv = FlightFrontend(inst, addr=fh, port=fp).start()
    closers.append(srv.close)
    print(f"greptimedb-tpu arrow flight on {fh}:{srv.server.port}",
          flush=True)
    return srv


def _advertise_addr(opts, srv) -> str | None:
    """The address peers should dial: grpc.advertise_addr if set, else
    the bind address with the RESOLVED port (port 0 binds ephemerally)
    and a routable host when bound to a wildcard."""
    adv = opts.get("grpc.advertise_addr")
    if adv:
        return adv
    if srv is None:
        return opts.get("grpc.addr") or None
    host = srv.addr
    if host in ("", "0.0.0.0", "::"):
        import socket as _socket

        host = _socket.gethostbyname(_socket.gethostname())
    return f"{host}:{srv.server.port}"


def _result_path_options(inst, opts):
    """[sessions] + [result_cache] knobs: the device-resident result
    path (persistent query sessions, frontend result-set cache)."""
    from greptimedb_tpu.query import sessions as _sessions
    from greptimedb_tpu.query.result_cache import ResultCache

    _sessions.configure(opts.section("sessions"))
    inst.result_cache = ResultCache.from_options(
        opts.section("result_cache")
    )
    inst.catalog.result_cache = inst.result_cache


def _make_instance(opts):
    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.storage.engine import EngineConfig
    from greptimedb_tpu.storage.object_store import (
        object_store_from_options,
    )
    from greptimedb_tpu.storage.compaction import compaction_options_from
    from greptimedb_tpu.storage.recovery import recovery_options_from

    store = None
    storage = opts.section("storage")
    if (str(storage.get("type", "fs")).lower() != "fs"
            or storage.get("root")):
        store = object_store_from_options(storage, opts.get("data_home"))
    # dedicated cold-tier store ([storage.cold]); absent, regions fall
    # back to the primary store beneath any local read cache
    cold_store = None
    cold_cfg = storage.get("cold")
    if isinstance(cold_cfg, dict) and cold_cfg:
        import os as _os

        cold_store = object_store_from_options(
            cold_cfg, _os.path.join(opts.get("data_home"), "cold")
        )
    # process-wide query mesh ([mesh] knobs): built once from the
    # visible devices and threaded into every QueryEngine this process
    # creates (the replicate-vs-shard planner gates per-query use)
    from greptimedb_tpu.parallel import mesh as mesh_mod

    mesh_opts = mesh_mod.mesh_options_from(opts.section("mesh"))
    mesh = mesh_mod.configure(mesh_opts)
    # [tracing] knobs: sampling + ring capacity for this process
    from greptimedb_tpu.telemetry import tracing as _tracing

    _tracing.configure(opts.section("tracing"))
    # [memory] knobs: global device watermark + census cadence
    from greptimedb_tpu.telemetry import memory as _memory

    _memory.configure(opts.section("memory"))
    # [stmt_stats] knobs: fingerprint registry size + metric label cap
    from greptimedb_tpu.telemetry import stmt_stats as _stmt_stats

    _stmt_stats.configure(opts.section("stmt_stats"))
    # [fleet] knobs: heartbeat telemetry cadence + cluster fan-out
    # bounds + federated-scrape cache TTL (dist/fleet.py)
    from greptimedb_tpu.dist import fleet as _fleet

    _fleet.configure(opts.section("fleet"))
    # [profiling] knobs: device-program registry + roofline peaks
    from greptimedb_tpu.telemetry import device_programs as _dev_prog

    _dev_prog.configure(opts.section("profiling"))
    # [index] knobs: secondary tag-index dataplane (postings caches +
    # the HBM-resident label plane)
    from greptimedb_tpu import index as _index

    _index.configure(opts.section("index"))
    prefer_device = opts.get("query.prefer_device")
    inst = Standalone(
        mesh=mesh, mesh_opts=mesh_opts,
        prefer_device=(None if prefer_device is None
                       else bool(prefer_device)),
        engine_config=EngineConfig(
            data_root=opts.get("data_home"),
            enable_background=opts.get("engine.enable_background", True),
            background_interval_s=opts.get(
                "engine.background_interval_s", 5.0
            ),
            wal_backend=opts.get("wal.backend", "fs"),
            wal_topics=int(opts.get("wal.topics", 4)),
            recovery=recovery_options_from(opts.section("recovery")),
            compaction=compaction_options_from(
                opts.section("compaction")
            ),
        ),
        store=store,
        cold_store=cold_store,
    )
    if opts.get("flow.enable", True):
        try:
            inst.enable_flows(
                tick_interval_s=opts.get("flow.tick_interval_s", 1.0)
            )
        except Exception as e:  # noqa: BLE001
            # the node still serves reads/writes without flows
            print(f"# flows disabled: {e}", flush=True)
    from greptimedb_tpu.sched import AdmissionController, SchedulerConfig

    inst.scheduler = AdmissionController(
        SchedulerConfig.from_options(opts.section("scheduler"))
    )
    _result_path_options(inst, opts)
    from greptimedb_tpu.telemetry.slow_query import SlowQueryLog

    inst.slow_query_log = SlowQueryLog(
        enable=bool(opts.get("logging.slow_query.enable", True)),
        threshold_s=float(opts.get("logging.slow_query.threshold_s", 5.0)),
        sample_ratio=float(
            opts.get("logging.slow_query.sample_ratio", 1.0)
        ),
    )
    # [autotune] knobs: apply AFTER the scheduler/result-cache swaps
    # above so the controllers tune the operator-configured objects;
    # the knob registry reads through `inst` attributes, so the swapped
    # instances are what set_config and the controllers see
    inst.autotune.apply_options(opts.section("autotune"))
    inst.autotune.start()
    return inst


def _start_standalone(opts):
    inst = _make_instance(opts)
    closers = [inst.close]
    server = _http_server(inst, opts, closers)
    if server is not None:
        inst.node_addr = f"{server.addr}:{server.port}"
    _export_metrics(inst, opts, closers, role="standalone")
    _telemetry(opts, closers, mode="standalone")
    _wire_protocols(inst, opts, closers)
    _flight_server(inst, opts, closers)
    print(
        f"greptimedb-tpu standalone listening on http://{server.addr}:"
        f"{server.port}", flush=True,
    )
    return _serve_until_signal(closers)


def _start_datanode(opts):
    inst = _make_instance(opts)
    inst.node_role = "datanode"
    closers = [inst.close]
    # region-server surface: per-region open/write/scan/partial-SQL for
    # the distributed topology (dist/region_server.py)
    from greptimedb_tpu.dist.region_server import RegionServer

    inst.region_server = RegionServer(
        inst.engine, opts.get("data_home"),
        scan_cache_bytes=opts.get("dist_query.scan_cache_bytes"),
        region_scan_parallelism=opts.get(
            "dist_query.region_scan_parallelism"
        ),
    )
    flight_srv = _flight_server(inst, opts, closers)
    _http_server(inst, opts, closers)
    _export_metrics(inst, opts, closers, role="datanode")
    _telemetry(opts, closers, mode="datanode")
    meta_addr = opts.get("datanode.metasrv_addr") or ""
    if meta_addr:
        from greptimedb_tpu.dist import fleet

        fleet.configure(opts.section("fleet"))
        node_id = int(opts.get("datanode.node_id", 0))
        inst.node_id = node_id
        closers.append(fleet.start_heartbeat(
            meta_addr, node_id, inst, role="datanode",
            addr=_advertise_addr(opts, flight_srv),
        ))
    print(
        f"greptimedb-tpu datanode (node {opts.get('datanode.node_id')}) "
        f"flight on {opts.get('grpc.addr')}", flush=True,
    )
    return _serve_until_signal(closers)


def _start_frontend(opts):
    from greptimedb_tpu.telemetry import device_programs as _dev_prog
    from greptimedb_tpu.telemetry import memory as _memory
    from greptimedb_tpu.telemetry import stmt_stats as _stmt_stats
    from greptimedb_tpu.telemetry import tracing as _tracing

    _tracing.configure(opts.section("tracing"))
    _memory.configure(opts.section("memory"))
    # the frontend owns statement execution in a dist topology, so the
    # statement-statistics registry lives here ([stmt_stats] knobs)
    _stmt_stats.configure(opts.section("stmt_stats"))
    # frontends rarely dispatch programs themselves, but the registry
    # still profiles any local device path ([profiling] knobs)
    _dev_prog.configure(opts.section("profiling"))
    # [index] knobs: the frontend's merged-registry matcher lookups
    # ride the same secondary-index path as the datanodes
    from greptimedb_tpu import index as _index

    _index.configure(opts.section("index"))
    meta_addr = opts.get("metasrv.addr") or ""
    if meta_addr:
        # distributed frontend: catalog in the metasrv kv, regions on
        # datanode processes, full SQL engine here (dist/frontend.py)
        from greptimedb_tpu.dist.frontend import DistInstance

        inst = DistInstance(
            opts.get("data_home"), meta_addr,
            flownode_addr=opts.get("frontend.flownode_addr") or None,
            ingest_options=opts.section("ingest"),
            dist_query_options=opts.section("dist_query"),
            scheduler_options=opts.section("scheduler"),
        )
        _result_path_options(inst, opts)
        target = f"metasrv {meta_addr}"
    else:
        # legacy single-datanode proxy: forward statements over Flight
        from greptimedb_tpu.servers.remote import RemoteInstance

        addrs = opts.get("frontend.datanode_addrs") or []
        if isinstance(addrs, str):
            addrs = [a for a in addrs.split(",") if a]
        inst = RemoteInstance(addrs)
        target = f"datanodes {addrs}"
    inst.node_role = "frontend"
    closers = [inst.close]
    _wire_protocols(inst, opts, closers)
    server = _http_server(inst, opts, closers)
    if server is not None:
        inst.node_addr = f"{server.addr}:{server.port}"
    if meta_addr:
        # the frontend heartbeats too: the fleet plane needs ITS
        # uptime/memory/query counters on cluster_node_stats, and the
        # metasrv's phi verdict covers every role, not just datanodes
        from greptimedb_tpu.dist import fleet

        fleet.configure(opts.section("fleet"))
        inst.node_id = fleet.derive_node_id(
            "frontend", inst.node_addr or f"pid:{os.getpid()}"
        )
        closers.append(fleet.start_heartbeat(
            meta_addr, inst.node_id, inst, role="frontend",
            addr=inst.node_addr or None,
        ))
    _telemetry(opts, closers, mode="frontend")
    print(
        f"greptimedb-tpu frontend -> {target} on "
        f"http://{server.addr}:{server.port}", flush=True,
    )
    return _serve_until_signal(closers)


def _start_metasrv(opts):
    from greptimedb_tpu.servers.meta_http import MetasrvServer

    mh, mp = _split(opts.get("metasrv.addr"))
    srv = MetasrvServer(
        addr=mh, port=mp, data_home=opts.get("data_home"),
        selector=opts.get("metasrv.selector", "round_robin"),
        phi_threshold=float(opts.get("metasrv.phi_threshold", 8.0)),
        acceptable_pause_ms=float(
            opts.get("metasrv.acceptable_pause_ms", 10000.0)
        ),
        stats_history=int(opts.get("fleet.history", 32)),
    ).start()
    closers = [srv.close]
    _telemetry(opts, closers, mode="metasrv")
    print(f"greptimedb-tpu metasrv on {mh}:{srv.port}", flush=True)
    return _serve_until_signal(closers)


def _start_flownode(opts):
    meta_addr = opts.get("metasrv.addr") or ""
    if meta_addr:
        # flow evals dispatch device programs (flow/device_state.py),
        # so the dist flownode configures the profiler too (the
        # standalone path rides _make_instance below)
        from greptimedb_tpu.telemetry import (
            device_programs as _dev_prog,
        )

        _dev_prog.configure(opts.section("profiling"))
        # distributed flownode: shared-kv catalog (source/sink tables
        # are RemoteTables over the datanodes), flows local, mirrored
        # deltas arrive over Flight (dist/frontend.py flow mirroring)
        from greptimedb_tpu.dist.frontend import DistInstance

        inst = DistInstance(opts.get("data_home"), meta_addr,
                            ingest_options=opts.section("ingest"))
        inst.node_role = "flownode"
        inst.enable_flows(
            tick_interval_s=opts.get("flow.tick_interval_s", 1.0)
        )
        closers = [inst.close]
        flight_srv = _flight_server(inst, opts, closers)
        # register in the metasrv flownode book so frontends place
        # flows and route mirrors here (dist/frontend.py). Keyed by the
        # ADVERTISED ADDRESS: two flownodes without explicit node ids
        # must not overwrite each other's registration
        try:
            from greptimedb_tpu.dist.client import MetaClient
            from greptimedb_tpu.dist.frontend import DistInstance as _DI

            adv = _advertise_addr(opts, flight_srv) or ""
            if adv:
                MetaClient(meta_addr).kv_put(
                    f"{_DI.FLOWNODE_PREFIX}{adv}", adv
                )
        except Exception as e:  # noqa: BLE001 - registration best-effort
            print(f"# flownode registration failed: {e}", flush=True)
        # heartbeat as a fleet member too: liveness + node-stats ride
        # the same channel as every other role
        from greptimedb_tpu.dist import fleet

        fleet.configure(opts.section("fleet"))
        fl_addr = _advertise_addr(opts, flight_srv) or ""
        inst.node_id = fleet.derive_node_id(
            "flownode", fl_addr or f"pid:{os.getpid()}"
        )
        closers.append(fleet.start_heartbeat(
            meta_addr, inst.node_id, inst, role="flownode",
            addr=fl_addr or None,
        ))
        server = _http_server(inst, opts, closers)
        print(
            f"greptimedb-tpu flownode (dist, metasrv {meta_addr}) "
            f"flight on {opts.get('grpc.addr')}", flush=True,
        )
        _telemetry(opts, closers, mode="flownode")
        return _serve_until_signal(closers)
    inst = _make_instance(opts)   # flows on by default
    closers = [inst.close]
    server = _http_server(inst, opts, closers)
    _telemetry(opts, closers, mode="flownode")
    print(
        f"greptimedb-tpu flownode on http://{server.addr}:{server.port}",
        flush=True,
    )
    return _serve_until_signal(closers)


def _repl(args):
    from greptimedb_tpu.instance import Standalone

    inst = Standalone(args.data_home)
    print("greptimedb-tpu REPL; end statements with ';', \\q to quit")
    buf = []
    while True:
        try:
            line = input("greptime> " if not buf else "      -> ")
        except EOFError:
            break
        if line.strip() in ("\\q", "exit", "quit"):
            break
        buf.append(line)
        if not line.rstrip().endswith(";"):
            continue
        sql = "\n".join(buf)
        buf = []
        try:
            res = inst.sql(sql.rstrip(";"))
            _print_result(res)
        except Exception as e:
            print(f"error: {e}")
    inst.close()
    return 0


def _print_result(res):
    if not res.names:
        print("OK")
        return
    widths = [
        max(len(str(n)), *(len(str(r[i])) for r in res.rows()), 1)
        if res.num_rows else len(str(n))
        for i, n in enumerate(res.names)
    ]

    def fmt(row):
        return " | ".join(str(v).ljust(w) for v, w in zip(row, widths))

    print(fmt(res.names))
    print("-+-".join("-" * w for w in widths))
    for row in res.rows():
        print(fmt(row))
    print(f"({res.num_rows} rows)")


if __name__ == "__main__":
    sys.exit(main())
