"""Process entry point: `python -m greptimedb_tpu.cli standalone start`.

Counterpart of /root/reference/src/cmd/src/bin/greptime.rs subcommands.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="greptimedb-tpu")
    sub = ap.add_subparsers(dest="role", required=True)

    standalone = sub.add_parser("standalone")
    s_sub = standalone.add_subparsers(dest="cmd", required=True)
    start = s_sub.add_parser("start")
    start.add_argument("--data-home", default="./greptimedb_tpu_data")
    start.add_argument("--http-addr", default="127.0.0.1:4000")
    start.add_argument("--mysql-addr", default="127.0.0.1:4002",
                       help="MySQL wire protocol address ('' disables)")
    start.add_argument("--flight-addr", default="127.0.0.1:4001",
                       help="Arrow Flight (gRPC) address ('' disables)")
    start.add_argument("--postgres-addr", default="127.0.0.1:4003",
                       help="PostgreSQL wire protocol address "
                            "('' disables)")
    start.add_argument("--no-flows", action="store_true")

    repl = sub.add_parser("cli")
    repl.add_argument("--data-home", default="./greptimedb_tpu_data")

    args = ap.parse_args(argv)
    if args.role == "standalone":
        return _start_standalone(args)
    if args.role == "cli":
        return _repl(args)
    ap.error("unknown role")


def _start_standalone(args):
    from greptimedb_tpu.instance import Standalone
    from greptimedb_tpu.servers.http import HttpServer
    from greptimedb_tpu.storage.engine import EngineConfig

    host, _, port = args.http_addr.rpartition(":")
    inst = Standalone(
        engine_config=EngineConfig(
            data_root=args.data_home, enable_background=True,
        )
    )
    if not args.no_flows:
        try:
            inst.enable_flows()
        except Exception:
            pass
    server = HttpServer(inst, addr=host or "127.0.0.1",
                        port=int(port)).start()
    extra = []
    if args.mysql_addr:
        from greptimedb_tpu.servers.mysql import MySqlServer

        mh, _, mp = args.mysql_addr.rpartition(":")
        extra.append(MySqlServer(
            inst, addr=mh or "127.0.0.1", port=int(mp)
        ).start())
        print(f"greptimedb-tpu mysql protocol on {args.mysql_addr}",
              flush=True)
    if getattr(args, "postgres_addr", ""):
        from greptimedb_tpu.servers.postgres import PostgresServer

        ph, _, pp = args.postgres_addr.rpartition(":")
        extra.append(PostgresServer(
            inst, addr=ph or "127.0.0.1", port=int(pp)
        ).start())
        print(f"greptimedb-tpu postgres protocol on {args.postgres_addr}",
              flush=True)
    if args.flight_addr:
        try:
            from greptimedb_tpu.servers.flight import FlightFrontend

            fh, _, fp = args.flight_addr.rpartition(":")
            extra.append(FlightFrontend(
                inst, addr=fh or "127.0.0.1", port=int(fp)
            ).start())
            print(f"greptimedb-tpu arrow flight on {args.flight_addr}",
                  flush=True)
        except ImportError:
            print("# pyarrow.flight unavailable; flight disabled",
                  flush=True)
    print(
        f"greptimedb-tpu standalone listening on http://{server.addr}:"
        f"{server.port}", flush=True,
    )

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        for s in extra:
            s.close()
        server.stop()
        inst.close()
    return 0


def _repl(args):
    from greptimedb_tpu.instance import Standalone

    inst = Standalone(args.data_home)
    print("greptimedb-tpu REPL; end statements with ';', \\q to quit")
    buf = []
    while True:
        try:
            line = input("greptime> " if not buf else "      -> ")
        except EOFError:
            break
        if line.strip() in ("\\q", "exit", "quit"):
            break
        buf.append(line)
        if not line.rstrip().endswith(";"):
            continue
        sql = "\n".join(buf)
        buf = []
        try:
            res = inst.sql(sql.rstrip(";"))
            _print_result(res)
        except Exception as e:
            print(f"error: {e}")
    inst.close()
    return 0


def _print_result(res):
    if not res.names:
        print("OK")
        return
    widths = [
        max(len(str(n)), *(len(str(r[i])) for r in res.rows()), 1)
        if res.num_rows else len(str(n))
        for i, n in enumerate(res.names)
    ]
    def fmt(row):
        return " | ".join(str(v).ljust(w) for v, w in zip(row, widths))
    print(fmt(res.names))
    print("-+-".join("-" * w for w in widths))
    for row in res.rows():
        print(fmt(row))
    print(f"({res.num_rows} rows)")


if __name__ == "__main__":
    sys.exit(main())
