"""Segmented (group-by) reductions.

The TPU-native replacement for the reference's hash-aggregate operators
(DataFusion's aggregate execs reached from
/root/reference/src/query/src/datafusion.rs): group keys become dense int32
codes (tags are already dictionary-encoded, see datatypes.batch.Dictionary),
and every aggregate is a `jax.ops.segment_*` reduction — which XLA lowers to
sorted scatter-adds that tile well on TPU.

Two paths:
- dense path: when the product of key cardinalities is small enough, the
  combined code IS the segment id (num_segments = prod(cards), static).
- sort path: otherwise rows are sorted by code on device; run boundaries
  give compact per-batch segment ids with num_segments = N (static).

All kernels take a row-validity mask (padding rows and filtered rows are
masked out) and are jit-safe: shapes depend only on (N, num_segments).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG = -(2**31 - 1)
_POS = 2**31 - 1


def combine_codes(codes: list[jax.Array], cards: list[int]) -> tuple[jax.Array, int]:
    """Mixed-radix combine of per-column int32 codes into one code.

    Returns (combined_code, total_cardinality)."""
    assert len(codes) == len(cards) and codes
    out = codes[0].astype(jnp.int32)
    total = cards[0]
    for c, n in zip(codes[1:], cards[1:]):
        out = out * jnp.int32(n) + c.astype(jnp.int32)
        total *= n
    return out, total


def split_codes(code, cards: list[int]):
    """Inverse of combine_codes; works on numpy or jax arrays."""
    parts = []
    for n in reversed(cards):
        parts.append(code % n)
        code = code // n
    return list(reversed(parts))


def _masked_seg(seg: jax.Array, mask: jax.Array, num_segments: int) -> jax.Array:
    """Route masked-out rows to a trash segment (num_segments)."""
    return jnp.where(mask, seg, jnp.int32(num_segments)).astype(jnp.int32)


def seg_sum(values, seg, mask, num_segments: int):
    s = _masked_seg(seg, mask, num_segments)
    v = jnp.where(mask, values, jnp.zeros((), values.dtype))
    return jax.ops.segment_sum(v, s, num_segments=num_segments + 1)[:-1]


def seg_count(seg, mask, num_segments: int):
    s = _masked_seg(seg, mask, num_segments)
    return jax.ops.segment_sum(
        mask.astype(jnp.int32), s, num_segments=num_segments + 1
    )[:-1]


def seg_min(values, seg, mask, num_segments: int):
    s = _masked_seg(seg, mask, num_segments)
    if jnp.issubdtype(values.dtype, jnp.floating):
        fill = jnp.array(jnp.inf, values.dtype)
    else:
        fill = jnp.array(jnp.iinfo(values.dtype).max, values.dtype)
    v = jnp.where(mask, values, fill)
    return jax.ops.segment_min(v, s, num_segments=num_segments + 1)[:-1]


def seg_max(values, seg, mask, num_segments: int):
    s = _masked_seg(seg, mask, num_segments)
    if jnp.issubdtype(values.dtype, jnp.floating):
        fill = jnp.array(-jnp.inf, values.dtype)
    else:
        fill = jnp.array(jnp.iinfo(values.dtype).min, values.dtype)
    v = jnp.where(mask, values, fill)
    return jax.ops.segment_max(v, s, num_segments=num_segments + 1)[:-1]


def seg_mean(values, seg, mask, num_segments: int):
    s = seg_sum(values, seg, mask, num_segments)
    c = seg_count(seg, mask, num_segments)
    return s / jnp.maximum(c, 1).astype(s.dtype), c


def seg_var(values, seg, mask, num_segments: int, *, ddof: int = 0):
    """Population (ddof=0) or sample (ddof=1) variance per segment.

    Mean-shifted by the segment's own first value for numerical stability in
    f32 (the raw sum-of-squares formula cancels catastrophically)."""
    first_idx = seg_last_index(seg, mask, num_segments, take_first=True)
    shift = jnp.where(
        first_idx >= 0, values[jnp.maximum(first_idx, 0)], jnp.zeros((), values.dtype)
    )
    sv = values - shift[seg]
    s1 = seg_sum(sv, seg, mask, num_segments)
    s2 = seg_sum(sv * sv, seg, mask, num_segments)
    n = seg_count(seg, mask, num_segments).astype(values.dtype)
    denom = jnp.maximum(n - ddof, 1)
    var = (s2 - s1 * s1 / jnp.maximum(n, 1)) / denom
    return jnp.maximum(var, 0.0), n.astype(jnp.int32)


def seg_last_index(seg, mask, num_segments: int, *, take_first: bool = False):
    """Index of the last (or first) valid row per segment, -1 if empty.

    'last' means highest row index — callers wanting time order must feed
    time-sorted rows (the storage scan guarantees (series, ts) order)."""
    n = seg.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    s = _masked_seg(seg, mask, num_segments)
    if take_first:
        key = jnp.where(mask, idx, jnp.int32(_POS))
        out = jax.ops.segment_min(key, s, num_segments=num_segments + 1)[:-1]
        return jnp.where(out == _POS, jnp.int32(-1), out)
    key = jnp.where(mask, idx, jnp.int32(-1))
    return jax.ops.segment_max(key, s, num_segments=num_segments + 1)[:-1]


def seg_last(values, seg, mask, num_segments: int, *, take_first: bool = False):
    """Last (by row order) valid value per segment, plus presence mask."""
    li = seg_last_index(seg, mask, num_segments, take_first=take_first)
    present = li >= 0
    safe = jnp.maximum(li, 0)
    return values[safe], present


def seg_argmax(values, seg, mask, num_segments: int, *, argmin: bool = False):
    """Row index attaining the max (min) per segment; -1 if empty. Ties break
    to the lowest row index (matching typical SQL semantics)."""
    best = seg_min(values, seg, mask, num_segments) if argmin else seg_max(
        values, seg, mask, num_segments
    )
    hit = mask & (values == best[seg])
    return seg_last_index(seg, hit, num_segments, take_first=True)


def sort_groups(code_cols: list[jax.Array], mask: jax.Array):
    """Sort-based grouping for unbounded key spaces (cardinality product too
    large for the dense path). Lexicographically sorts rows by the int32 code
    columns — no combined code, so no overflow.

    Returns (order, seg_ids, starts, num_groups_device):
    - order: permutation putting valid rows first, sorted by keys
    - seg_ids: compact segment id per *sorted* row (0..num_groups-1);
      invalid rows get segment N (use num_segments=N+1 then drop the tail)
    - starts: bool per sorted row, True at each group's first valid row
    - num_groups: device scalar (int32)"""
    assert code_cols
    n = code_cols[0].shape[0]
    # jnp.lexsort: LAST key is primary. Significance order (most -> least):
    # !mask (so invalid rows sort after every valid row), then code_cols in
    # declaration order.
    keys = [c.astype(jnp.int32) for c in reversed(code_cols)] + [
        (~mask).astype(jnp.int32)
    ]
    order = jnp.lexsort(keys)
    smask = mask[order]
    changed = jnp.zeros((n,), dtype=bool)
    for c in code_cols:
        sc = c.astype(jnp.int32)[order]
        prev = jnp.concatenate([jnp.full((1,), _NEG, jnp.int32), sc[:-1]])
        changed = changed | (sc != prev)
    starts = smask & (changed | (jnp.arange(n) == 0))
    seg_ids = jnp.cumsum(starts.astype(jnp.int32)) - 1
    seg_ids = jnp.where(smask, jnp.maximum(seg_ids, 0), jnp.int32(n))
    num_groups = jnp.sum(starts.astype(jnp.int32))
    return order, seg_ids, starts, num_groups


@functools.partial(jax.jit, static_argnames=("num_segments", "ops"))
def multi_aggregate(values, seg, mask, num_segments: int, ops: tuple[str, ...]):
    """Run several aggregates over the same segmentation in one jit program
    (the common SELECT agg1, agg2, ... GROUP BY shape). `values` is a dict
    name -> (N,) array; ops is a tuple of (op, name) pairs flattened as
    'op:name' strings for hashability."""
    results = {}
    for spec in ops:
        op, _, name = spec.partition(":")
        v = values[name]
        if op == "sum":
            results[spec] = seg_sum(v, seg, mask, num_segments)
        elif op == "count":
            results[spec] = seg_count(seg, mask, num_segments)
        elif op == "min":
            results[spec] = seg_min(v, seg, mask, num_segments)
        elif op == "max":
            results[spec] = seg_max(v, seg, mask, num_segments)
        elif op == "mean":
            results[spec] = seg_mean(v, seg, mask, num_segments)[0]
        else:
            raise ValueError(f"unknown aggregate op: {op}")
    return results


# ----------------------------------------------------------------------
# segmented scans (window-function running frames)
# ----------------------------------------------------------------------

@jax.jit
def segmented_cumsum(values: jax.Array, reset: jax.Array) -> jax.Array:
    """Per-segment running sum: `reset[i]` marks the first row of a
    segment (partition). One associative_scan — O(log n) depth on
    device, the running-aggregate half of SQL window frames
    (ref: DataFusion WindowAggExec via src/query/src/datafusion.rs)."""
    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf

    v, _ = jax.lax.associative_scan(comb, (values, reset))
    return v


@functools.partial(jax.jit, static_argnames=("take_max",))
def segmented_cumextreme(values: jax.Array, reset: jax.Array,
                         *, take_max: bool) -> jax.Array:
    """Per-segment running max (or min) via one associative scan."""
    def comb(a, b):
        av, af = a
        bv, bf = b
        merged = jnp.maximum(av, bv) if take_max else jnp.minimum(av, bv)
        return jnp.where(bf, bv, merged), af | bf

    v, _ = jax.lax.associative_scan(comb, (values, reset))
    return v


@jax.jit
def segmented_cumsum_compensated_packed(v_hi: jax.Array, v_lo: jax.Array,
                                        reset: jax.Array) -> jax.Array:
    """(2, N) stacked (sum, comp): ONE device buffer = one host
    readback (the dev-tunnel pays a full RTT per fetched buffer)."""
    s, c = segmented_cumsum_compensated(v_hi, v_lo, reset)
    return jnp.stack([s, c])


@jax.jit
def segmented_cumsum_compensated(v_hi: jax.Array, v_lo: jax.Array,
                                 reset: jax.Array):
    """Neumaier-compensated per-segment running sum over two-float f32
    input (v_hi + v_lo ~= the f64 value): the no-x64 device path for SQL
    window running sums. Each element enters with its split low part as
    the initial compensation; the combine two-sums the high parts and
    accumulates the rounding residue, so sum+comp recovers the f64
    running sum to ~1 ulp (the pattern proven by flow/device_state.py's
    Neumaier state slots). Returns (sum, comp) f32 arrays."""
    def comb(a, b):
        a_s, a_c, a_f = a
        b_s, b_c, b_f = b
        t = a_s + b_s
        e = jnp.where(jnp.abs(a_s) >= jnp.abs(b_s),
                      (a_s - t) + b_s, (b_s - t) + a_s)
        return (jnp.where(b_f, b_s, t),
                jnp.where(b_f, b_c, a_c + b_c + e),
                a_f | b_f)

    s, c, _ = jax.lax.associative_scan(comb, (v_hi, v_lo, reset))
    return s, c


@functools.partial(jax.jit, static_argnames=("take_max",))
def segmented_cumextreme2(v_hi: jax.Array, v_lo: jax.Array,
                          reset: jax.Array, *, take_max: bool):
    """Per-segment running extreme over two-float (hi, lo) pairs:
    lexicographic compare keeps f64 ordering without x64 (values whose
    f32 roundings tie are ordered by their low parts). Returns the
    winning (hi, lo) pair arrays."""
    def comb(a, b):
        ah, al, af = a
        bh, bl, bf = b
        if take_max:
            pick_a = (ah > bh) | ((ah == bh) & (al >= bl))
        else:
            pick_a = (ah < bh) | ((ah == bh) & (al <= bl))
        mh = jnp.where(pick_a, ah, bh)
        ml = jnp.where(pick_a, al, bl)
        return (jnp.where(bf, bh, mh), jnp.where(bf, bl, ml), af | bf)

    h, l, _ = jax.lax.associative_scan(comb, (v_hi, v_lo, reset))
    return h, l
