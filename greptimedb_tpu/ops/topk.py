"""Top-k / bottom-k selection kernels (PromQL topk/bottomk, SQL ORDER BY +
LIMIT over aggregates)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k", "largest"))
def topk(values: jax.Array, mask: jax.Array, k: int, *, largest: bool = True):
    """Top-k along the last axis with invalid entries excluded.
    Returns (values, indices, valid)."""
    dt = values.dtype
    fill = jnp.asarray(-jnp.inf if largest else jnp.inf, dt)
    v = jnp.where(mask, values, fill)
    if not largest:
        v = -v
    top_v, top_i = jax.lax.top_k(v, k)
    if not largest:
        top_v = -top_v
    valid = jnp.take_along_axis(mask, top_i, axis=-1)
    return jnp.where(valid, top_v, 0), top_i, valid
