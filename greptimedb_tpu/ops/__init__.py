"""Device kernel library.

Everything in here is pure, shape-static jax.numpy (or Pallas) code designed
for the TPU execution model: no data-dependent shapes, masks instead of
nulls, segment/prefix/gather formulations instead of per-row loops.

- segment.py : segmented (group-by) reductions for SQL aggregation
- grid.py    : scatter of (series, ts, value) rows onto dense (S, T) grids
- window.py  : prefix-sum and gather window kernels over grids
- promql.py  : PromQL range/instant function semantics on top of window.py
- topk.py    : top-k/bottom-k selection
- filter.py  : predicate mask evaluation
"""
