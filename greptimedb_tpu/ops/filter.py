"""Predicate evaluation kernels: comparisons/boolean algebra over device
columns producing row masks (the device half of WHERE pushdown; the host
half — time-range and tag pruning — lives in storage/ and index/)."""

from __future__ import annotations

import jax.numpy as jnp

_CMP = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
}


def compare(op: str, left, right):
    return _CMP[op](left, right)


def combine(op: str, *masks):
    assert masks
    out = masks[0]
    for m in masks[1:]:
        if op == "and":
            out = out & m
        elif op == "or":
            out = out | m
        else:
            raise ValueError(op)
    return out


def between(values, low, high):
    return (values >= low) & (values <= high)


def isin(values, candidates):
    out = jnp.zeros(values.shape, dtype=bool)
    for c in candidates:
        out = out | (values == c)
    return out
