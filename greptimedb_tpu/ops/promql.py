"""PromQL function semantics over grid windows.

The TPU reimplementation of the reference's range-function kernel set
(/root/reference/src/promql/src/functions/: extrapolate_rate.rs,
aggr_over_time.rs, changes.rs, resets.rs, idelta.rs, deriv.rs,
predict_linear.rs, holt_winters.rs, quantile.rs) plus histogram_quantile
folding (/root/reference/src/promql/src/extension_plan/histogram_fold.rs).

Each function maps (vals, has, tsg) grids + Windows onto (S, J) outputs with
presence masks. Dispatch is by name so the PromQL planner stays declarative.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from greptimedb_tpu.ops import window as W

RANGE_FUNCTIONS = frozenset({
    "rate", "increase", "delta", "idelta", "irate",
    "avg_over_time", "sum_over_time", "count_over_time", "min_over_time",
    "max_over_time", "last_over_time", "first_over_time",
    "present_over_time", "absent_over_time",
    "stddev_over_time", "stdvar_over_time", "quantile_over_time",
    "mad_over_time",
    "changes", "resets", "deriv", "predict_linear", "holt_winters",
})


def eval_range_function(
    name: str, vals, has, tsg, windows: W.Windows, spec, *, args: tuple = ()
):
    """Evaluate one range function over all windows. Returns (out, present)
    shaped (S, J). `args` carries scalar parameters (quantile phi, sf/tf,
    predict_linear horizon seconds)."""
    lo = jnp.asarray(windows.lo)
    hi = jnp.asarray(windows.hi)
    t_end = jnp.asarray(windows.t_end)
    l_cells = windows.num_cells_per_window
    tps = spec.tps

    if name in ("rate", "increase", "delta"):
        return W.extrapolated_rate(
            vals, has, tsg, lo, hi, t_end, windows.range_ticks, tps,
            is_counter=name != "delta", is_rate=name == "rate",
        )
    if name == "idelta":
        return W.instant_delta(vals, has, tsg, lo, hi, tps, is_rate=False)
    if name == "irate":
        return W.instant_delta(vals, has, tsg, lo, hi, tps, is_rate=True)
    if name == "sum_over_time":
        return W.window_sum(vals, has, lo, hi)
    if name == "count_over_time":
        cnt = W.window_count(has, lo, hi)
        return cnt.astype(vals.dtype), cnt > 0
    if name == "avg_over_time":
        return W.window_avg(vals, has, lo, hi)
    if name == "min_over_time":
        return W.window_minmax(vals, has, tsg, hi, l_cells, "min")
    if name == "max_over_time":
        return W.window_minmax(vals, has, tsg, hi, l_cells, "max")
    if name == "last_over_time":
        v, _, p = W.window_last(vals, has, tsg, lo, hi)
        return jnp.where(p, v, 0), p
    if name == "first_over_time":
        v, _, p = W.window_first(vals, has, tsg, lo, hi)
        return jnp.where(p, v, 0), p
    if name == "present_over_time":
        cnt = W.window_count(has, lo, hi)
        p = cnt > 0
        return p.astype(vals.dtype), p
    if name == "absent_over_time":
        cnt = W.window_count(has, lo, hi)
        absent = cnt == 0
        return absent.astype(vals.dtype), absent
    if name == "stddev_over_time":
        _, sd, p = W.window_stdvar(vals, has, tsg, hi, l_cells)
        return sd, p
    if name == "stdvar_over_time":
        var, _, p = W.window_stdvar(vals, has, tsg, hi, l_cells)
        return var, p
    if name == "quantile_over_time":
        (phi,) = args
        return W.window_quantile(vals, has, tsg, hi, l_cells, phi)
    if name == "mad_over_time":
        med, p = W.window_quantile(vals, has, tsg, hi, l_cells, 0.5)
        g_vals, g_has, _ = W.gather_windows(vals, has, tsg, hi, l_cells)
        dev = jnp.abs(g_vals - med[:, :, None])
        dev = jnp.where(g_has, dev, jnp.inf)
        sorted_dev = jnp.sort(dev, axis=2)
        n = jnp.sum(g_has, axis=2)
        rank = 0.5 * jnp.maximum(n - 1, 0).astype(vals.dtype)
        lo_i = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, l_cells - 1)
        hi_i = jnp.clip(jnp.ceil(rank).astype(jnp.int32), 0, l_cells - 1)
        v_lo = jnp.take_along_axis(sorted_dev, lo_i[:, :, None], axis=2)[:, :, 0]
        v_hi = jnp.take_along_axis(sorted_dev, hi_i[:, :, None], axis=2)[:, :, 0]
        out = v_lo + (v_hi - v_lo) * (rank - jnp.floor(rank))
        return jnp.where(p, out, 0), p
    if name == "changes":
        return W.window_pair_count(vals, has, lo, hi, count_changes=True)
    if name == "resets":
        return W.window_pair_count(vals, has, lo, hi, count_changes=False)
    if name == "deriv":
        slope, _, n = W.window_linear_fit(vals, has, tsg, hi, t_end, l_cells, tps)
        p = n >= 2
        return jnp.where(p, slope, 0), p
    if name == "predict_linear":
        (horizon_s,) = args
        slope, intercept, n = W.window_linear_fit(
            vals, has, tsg, hi, t_end, l_cells, tps
        )
        p = n >= 2
        out = intercept + slope * jnp.asarray(horizon_s, vals.dtype)
        return jnp.where(p, out, 0), p
    if name == "holt_winters":
        sf, tf = args
        return W.window_holt_winters(vals, has, tsg, hi, l_cells, sf, tf)
    raise ValueError(f"unsupported range function: {name}")


# ----------------------------------------------------------------------
# histogram_quantile
# ----------------------------------------------------------------------

@jax.jit
def histogram_quantile(le: jax.Array, buckets: jax.Array, mask: jax.Array, q):
    """Prometheus histogram_quantile over pre-grouped buckets.

    le:      (B,) ascending bucket upper bounds, last must be +inf
    buckets: (..., B) cumulative bucket values (one histogram per leading
             index; typically (G, J, B) for G series-groups x J steps)
    mask:    (..., B) bucket presence
    q:       quantile in [0, 1]

    Semantics follow Prometheus bucketQuantile (monotonicity enforced via a
    running max; rank interpolated linearly within the located bucket; the
    lowest bucket interpolates from 0 when its bound is positive)."""
    dt = buckets.dtype
    q = jnp.asarray(q, dt)
    b = jnp.where(mask, buckets, 0)
    # enforce cumulative monotonicity (client-side counter skew)
    b = jax.lax.cummax(b, axis=b.ndim - 1)
    total = b[..., -1]
    ok = jnp.any(mask, axis=-1) & (total > 0)
    rank = q * total
    # first bucket index with cum >= rank
    idx = jnp.sum((b < rank[..., None]).astype(jnp.int32), axis=-1)
    nb = le.shape[0]
    idx = jnp.clip(idx, 0, nb - 1)
    # +inf bucket: clamp result to highest finite bound
    in_inf = idx >= nb - 1
    idx_lo = jnp.maximum(idx - 1, 0)
    ub = le[idx]
    lb = jnp.where(idx > 0, le[idx_lo], jnp.zeros((), dt))
    # if lowest bucket has non-positive bound, no interpolation from zero
    lb = jnp.where((idx == 0) & (le[0] <= 0), le[0], lb)
    cum_lo = jnp.where(
        idx > 0, jnp.take_along_axis(b, idx_lo[..., None], axis=-1)[..., 0], 0
    )
    cum_hi = jnp.take_along_axis(b, idx[..., None], axis=-1)[..., 0]
    width = cum_hi - cum_lo
    frac = (rank - cum_lo) / jnp.where(width == 0, 1, width)
    out = lb + (ub - lb) * frac
    highest_finite = le[jnp.maximum(nb - 2, 0)]
    out = jnp.where(in_inf, highest_finite, out)
    out = jnp.where(q < 0, -jnp.inf, out)
    out = jnp.where(q > 1, jnp.inf, out)
    return jnp.where(ok, out, jnp.zeros((), dt)), ok


# ----------------------------------------------------------------------
# cross-series aggregation (sum/avg/min/max/topk... by (...) semantics)
# ----------------------------------------------------------------------

# above this series count, linear group reductions run as one-hot matmuls
# on the MXU instead of segment scatters (TPU scatter serializes per index:
# at 1M series a segment_sum costs ~1s, the equivalent (G,S)x(S,J) matmul
# costs <1ms). Min/max are not linear and stay on the scatter path.
_MATMUL_MIN_SERIES = 4096
_MATMUL_MAX_ONEHOT_ELEMS = 1 << 28  # 1 GB f32 one-hot ceiling


def _group_matmul(x, onehot_t):
    """(G, S) @ (S, J) with full f32 accumulation (one-hot entries are
    exact in any precision; the data must not round through bf16)."""
    return jax.lax.dot(onehot_t, x, precision=jax.lax.Precision.HIGHEST)


def aggregate_across_series_blocked(
    vals, present, group_ids, num_groups: int, op: str, *,
    total_series: int, blocks: int | None = None, ctx=None,
):
    """Series aggregation with a fixed blocked-combine structure: the
    series axis splits into `blocks` aligned blocks whose partials are
    combined in one unrolled left fold. Run single-device (ctx =
    LocalFoldCtx) or per-shard inside shard_map (ctx = ShardFoldCtx) it
    performs the SAME additions in the SAME order, so the mesh fast path
    (promql/fast.py) matches the unsharded fast path bit-for-bit.
    `total_series` is the GLOBAL padded series count (local shape *
    shards inside shard_map) — it keeps the matmul-vs-scatter choice
    identical across shardings."""
    from greptimedb_tpu.parallel.dist import LocalFoldCtx, left_fold_sum
    from greptimedb_tpu.parallel.mesh import FOLD_BLOCKS

    if blocks is None:
        blocks = FOLD_BLOCKS  # the ONE cross-path fold-block contract
    if ctx is None:
        ctx = LocalFoldCtx()
    dt = vals.dtype
    gid = group_ids.astype(jnp.int32)
    s_loc = vals.shape[0]
    bl = max(blocks // ctx.shards, 1)
    aligned = (blocks % ctx.shards == 0 and s_loc % bl == 0
               and s_loc >= bl)
    linear = op in ("sum", "avg", "count", "group", "stddev", "stdvar")
    use_matmul = (
        linear
        and total_series >= _MATMUL_MIN_SERIES
        and num_groups * total_series <= _MATMUL_MAX_ONEHOT_ELEMS
    )

    def bsum(x):
        """Blocked exact-structured group sum of an (S_loc, J) matrix."""
        if not aligned:
            return ctx.psum(jax.ops.segment_sum(
                x, gid, num_segments=num_groups
            ))
        per = s_loc // bl
        if use_matmul:
            parts = []
            grange = jnp.arange(num_groups, dtype=jnp.int32)[:, None]
            for b in range(bl):
                sl = slice(b * per, (b + 1) * per)
                onehot_t = (gid[sl][None, :] == grange).astype(dt)
                parts.append(_group_matmul(x[sl], onehot_t))
            partial = jnp.stack(parts)              # (bl, G, J)
        else:
            bid = (jnp.arange(s_loc, dtype=jnp.int32)
                   // jnp.int32(per))
            seg = bid * jnp.int32(num_groups) + gid
            p = jax.ops.segment_sum(
                x, seg, num_segments=bl * num_groups
            )
            partial = p.reshape(bl, num_groups, -1)
        return left_fold_sum(ctx.gather(partial))

    cnt = bsum(present.astype(dt))
    any_present = cnt > 0
    if op in ("sum", "avg"):
        s = bsum(jnp.where(present, vals, 0))
        if op == "avg":
            s = s / jnp.maximum(cnt, 1)
        return jnp.where(any_present, s, 0), any_present
    if op == "count":
        return cnt, any_present
    if op == "group":
        return any_present.astype(dt), any_present
    if op == "min":
        v = jnp.where(present, vals, jnp.inf)
        m = ctx.pext(jax.ops.segment_min(v, gid, num_segments=num_groups),
                     take_max=False)
        return jnp.where(any_present, m, 0), any_present
    if op == "max":
        v = jnp.where(present, vals, -jnp.inf)
        m = ctx.pext(jax.ops.segment_max(v, gid, num_segments=num_groups),
                     take_max=True)
        return jnp.where(any_present, m, 0), any_present
    if op in ("stddev", "stdvar"):
        s = bsum(jnp.where(present, vals, 0))
        n = jnp.maximum(cnt, 1)
        mean = s / n
        dev = jnp.where(present, vals - mean[gid], 0)
        var = bsum(dev * dev) / n
        out = var if op == "stdvar" else jnp.sqrt(var)
        return jnp.where(any_present, out, 0), any_present
    raise ValueError(f"unsupported aggregation: {op}")


@functools.partial(jax.jit, static_argnames=("op", "num_groups"))
def aggregate_across_series(vals, present, group_ids, num_groups: int, op: str):
    """PromQL aggregation operators over the series axis of an (S, J) matrix.
    group_ids (S,) int32 maps each series to its output group (built on host
    from label sets). Returns (G, J) values + presence."""
    dt = vals.dtype
    gid = group_ids.astype(jnp.int32)
    linear = op in ("sum", "avg", "count", "group", "stddev", "stdvar")
    # the (G, S) one-hot must stay bounded: high-cardinality group-bys
    # (G ~ S) would materialize G*S floats, so those keep the scatter path
    use_matmul = (
        linear
        and vals.shape[0] >= _MATMUL_MIN_SERIES
        and num_groups * vals.shape[0] <= _MATMUL_MAX_ONEHOT_ELEMS
    )

    if use_matmul:
        onehot_t = (
            gid[None, :] == jnp.arange(num_groups, dtype=jnp.int32)[:, None]
        ).astype(dt)                                    # (G, S)
        cnt_f = _group_matmul(present.astype(dt), onehot_t)
        any_present = cnt_f > 0
        masked = jnp.where(present, vals, 0)
        if op in ("sum", "avg"):
            s = _group_matmul(masked, onehot_t)
            if op == "avg":
                s = s / jnp.maximum(cnt_f, 1)
            return jnp.where(any_present, s, 0), any_present
        if op == "count":
            return cnt_f, any_present
        if op == "group":
            return any_present.astype(dt), any_present
        # stddev / stdvar: two-pass for stability (matches the scatter path)
        n = jnp.maximum(cnt_f, 1)
        mean = _group_matmul(masked, onehot_t) / n
        dev = jnp.where(present, vals - jnp.take(mean, gid, axis=0), 0)
        var = _group_matmul(dev * dev, onehot_t) / n
        out = var if op == "stdvar" else jnp.sqrt(var)
        return jnp.where(any_present, out, 0), any_present

    cnt = jax.ops.segment_sum(
        present.astype(jnp.int32), gid, num_segments=num_groups
    )
    any_present = cnt > 0
    if op in ("sum", "avg"):
        s = jax.ops.segment_sum(
            jnp.where(present, vals, 0), gid, num_segments=num_groups
        )
        if op == "avg":
            s = s / jnp.maximum(cnt, 1).astype(dt)
        return jnp.where(any_present, s, 0), any_present
    if op == "count":
        return cnt.astype(dt), any_present
    if op == "min":
        v = jnp.where(present, vals, jnp.inf)
        m = jax.ops.segment_min(v, gid, num_segments=num_groups)
        return jnp.where(any_present, m, 0), any_present
    if op == "max":
        v = jnp.where(present, vals, -jnp.inf)
        m = jax.ops.segment_max(v, gid, num_segments=num_groups)
        return jnp.where(any_present, m, 0), any_present
    if op == "group":
        return any_present.astype(dt), any_present
    if op in ("stddev", "stdvar"):
        s = jax.ops.segment_sum(
            jnp.where(present, vals, 0), gid, num_segments=num_groups
        )
        n = jnp.maximum(cnt, 1).astype(dt)
        mean = s / n
        dev = jnp.where(present, vals - mean[gid], 0)
        var = jax.ops.segment_sum(dev * dev, gid, num_segments=num_groups) / n
        out = var if op == "stdvar" else jnp.sqrt(var)
        return jnp.where(any_present, out, 0), any_present
    raise ValueError(f"unsupported aggregation: {op}")
