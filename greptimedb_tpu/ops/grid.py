"""Dense (series x time) grids from (sid, ts, value) rows.

This is the load-bearing layout decision of the whole TPU design (SURVEY.md
§5 "long-context" analog): the (series, time) plane is the matrix we shard
and window over. Rows coming off a storage scan are scattered onto a dense
grid of T cells of resolution `res`; every PromQL range/instant kernel then
operates on regular windows of grid cells (ops/window.py).

Cell convention: cell i holds samples with ts in (t0 + (i-1)*res, t0 + i*res]
— half-open on the left so that PromQL's (start, end] window semantics align
exactly with cell boundaries whenever `res` divides the query step and range.

Timestamps on device are int32 offsets from t0 in `unit` ticks (unit chosen
by the host so the whole grid span fits in int32 — avoids int64 on TPU).

When a cell receives multiple samples, the one with the greatest row index
wins; scans yield rows in (series, ts) order, so that is the latest sample
(same winner as the reference's last-row dedup,
/root/reference/src/mito2/src/read/dedup.rs).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class GridSpec:
    """Host-side description of a device grid."""

    t0: int          # absolute origin timestamp (exclusive lower bound), ms
    res: int         # cell resolution, ms
    num_cells: int   # T
    unit: int        # device ts tick size in ms (1 unless span overflows int32)
    tps: float       # device ts ticks per second (1000/unit)

    @staticmethod
    def build(t0: int, res: int, num_cells: int) -> "GridSpec":
        span = res * num_cells
        unit = 1
        while span // unit >= 2**31 - 1:
            unit *= 2
        return GridSpec(t0=t0, res=res, num_cells=num_cells, unit=unit,
                        tps=1000.0 / unit)

    def cell_of(self, ts: np.ndarray | int):
        """Cell index for absolute ts: ceil((ts - t0) / res), so a sample at
        exactly a cell boundary belongs to the cell ending there."""
        return -((-(np.asarray(ts) - self.t0)) // self.res)

    def device_ts(self, ts: np.ndarray) -> np.ndarray:
        return ((np.asarray(ts) - self.t0) // self.unit).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("num_series", "num_cells"))
def gridify(
    sid: jax.Array,      # (N,) int32 series ids in [0, num_series)
    cell: jax.Array,     # (N,) int32 cell index (may be out of range)
    tsrel: jax.Array,    # (N,) int32 device ts (ticks from t0)
    values: jax.Array,   # (N,) float
    mask: jax.Array,     # (N,) bool row validity
    num_series: int,
    num_cells: int,
):
    """Scatter rows to a dense grid. Returns (vals, has, tsg):
    vals (S,T) float, has (S,T) bool, tsg (S,T) int32 (0 where empty)."""
    out, has, tsg = gridify_multi(
        sid, cell, tsrel, {"v": values}, mask, num_series, num_cells
    )
    return out["v"], has, tsg


@functools.partial(jax.jit, static_argnames=("num_series", "num_cells"))
def gridify_multi(
    sid, cell, tsrel, value_cols: dict, mask, num_series: int, num_cells: int
):
    """gridify for several value columns sharing one (sid, cell) scatter —
    one winner computation, k gathers (the multi-field table case)."""
    n = sid.shape[0]
    in_range = mask & (cell >= 0) & (cell < num_cells) & (sid >= 0) & (
        sid < num_series
    )
    flat = jnp.where(
        in_range, sid * num_cells + cell, jnp.int32(num_series * num_cells)
    )
    idx = jnp.arange(n, dtype=jnp.int32)
    winner = jax.ops.segment_max(
        jnp.where(in_range, idx, jnp.int32(-1)),
        flat,
        num_segments=num_series * num_cells + 1,
    )[:-1]
    has = winner >= 0
    safe = jnp.maximum(winner, 0)
    shape = (num_series, num_cells)
    out = {}
    for name, v in value_cols.items():
        out[name] = jnp.where(has, v[safe], jnp.zeros((), v.dtype)).reshape(shape)
    tsg = jnp.where(has, tsrel[safe], jnp.int32(0)).reshape(shape)
    return out, has.reshape(shape), tsg
