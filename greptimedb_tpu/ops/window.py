"""Window kernels over (series x time) grids.

Replaces the reference's per-series streaming window materialization
(/root/reference/src/promql/src/extension_plan/range_manipulate.rs and the
RangeArray ragged view, /root/reference/src/promql/src/range_array.rs) with
two TPU-friendly formulations:

- prefix path: window aggregates as differences of per-series prefix sums
  (O(S*T) memory, no per-window gather). Used for sum/count/avg, the
  extrapolated rate family, changes/resets, first/last/idelta/irate.
- gather path: materialize (S, J, L) window tensors by gathering L cells per
  output step. Used for order statistics and sequential folds (min/max/
  quantile/stddev/holt_winters/deriv/predict_linear).

Window j covers grid cells [lo_j+1 .. hi_j] (samples with ts in
(t_end_j - range, t_end_j]), matching PromQL's half-open window.

All kernels return (values, present_mask) pairs shaped (S, J).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from greptimedb_tpu.ops.grid import GridSpec


@dataclass
class Windows:
    """Host-built window description for a range evaluation.

    Built so that every window boundary lands exactly on a cell boundary:
    res divides step, range and (start - t0)."""

    lo: np.ndarray        # (J,) int32 cell index, window = cells (lo, hi]
    hi: np.ndarray        # (J,) int32
    t_end: np.ndarray     # (J,) int32 window end, device ticks from t0
    range_ticks: int      # window length in device ticks
    range_seconds: float

    @property
    def num_steps(self) -> int:
        return len(self.hi)

    @property
    def num_cells_per_window(self) -> int:
        return int(self.hi[0] - self.lo[0])


def plan_grid_and_windows(
    start_ms: int, end_ms: int, step_ms: int, range_ms: int,
    *, max_cells: int = 4_000_000, data_interval_ms: int | None = None,
) -> tuple[GridSpec, Windows]:
    """Choose a grid resolution + origin so windows align with cells.

    res = gcd(step, range[, data_interval]) — windows then cover whole cells
    exactly. If that produces too many cells, coarsen to a divisor-free fit
    (approximation documented in ops/grid.py)."""
    step_ms = max(int(step_ms), 1)
    range_ms = max(int(range_ms), 1)
    res = int(np.gcd(step_ms, range_ms))
    if data_interval_ms and data_interval_ms > 0:
        res = int(np.gcd(res, int(data_interval_ms)))
    span = (end_ms - start_ms) + range_ms
    while span // res > max_cells:
        res *= 2  # coarsen: sacrifices exact boundary alignment on huge spans
    t0 = start_ms - range_ms
    # cells are (t0+(i-1)res, t0+i*res]; a sample at exactly end_ms maps to
    # cell span//res, so the grid needs span//res + 1 cells (cell 0 holds
    # only ts == t0, which every window's half-open lower bound excludes).
    num_cells = span // res + 1
    spec = GridSpec.build(t0, res, num_cells)
    steps = np.arange(start_ms, end_ms + 1, step_ms, dtype=np.int64)
    hi = np.minimum((steps - t0) // res, num_cells - 1).astype(np.int32)
    w_cells = max(range_ms // res, 1)
    lo = np.maximum(hi - w_cells, 0).astype(np.int32)
    t_end = ((steps - t0) // spec.unit).astype(np.int32)
    return spec, Windows(
        lo=lo, hi=hi, t_end=t_end,
        range_ticks=int(range_ms // spec.unit),
        range_seconds=range_ms / 1000.0,
    )


# ----------------------------------------------------------------------
# prefix helpers (all (S, T) -> (S, T+1) or (S, T))
# ----------------------------------------------------------------------

def _prefix(x: jax.Array) -> jax.Array:
    """P[:, i] = sum of cells < i; shape (S, T+1)."""
    c = jnp.cumsum(x, axis=1)
    return jnp.pad(c, ((0, 0), (1, 0)))


def _last_present_idx(has: jax.Array) -> jax.Array:
    """lastidx[:, i] = greatest cell j <= i with a sample, else -1."""
    t = has.shape[1]
    i = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), has.shape)
    return jax.lax.cummax(jnp.where(has, i, jnp.int32(-1)), axis=1)


def _first_present_idx(has: jax.Array) -> jax.Array:
    """firstidx[:, i] = least cell j >= i with a sample, else T."""
    t = has.shape[1]
    rev = jnp.flip(has, axis=1)
    i = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), has.shape)
    lp = jax.lax.cummax(jnp.where(rev, i, jnp.int32(-1)), axis=1)
    return jnp.flip(jnp.int32(t - 1) - lp, axis=1)


def _prev_present_idx(lastidx: jax.Array) -> jax.Array:
    """prev[:, i] = greatest cell j < i with a sample, else -1."""
    return jnp.pad(lastidx[:, :-1], ((0, 0), (1, 0)), constant_values=-1)


def _gather_steps(arr: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather (S, T') array at per-step indices. idx is (J,) -> (S, J) or
    (S, J) -> (S, J)."""
    if idx.ndim == 1:
        return arr[:, idx]
    return _take_cells(arr, idx)


# per-row gathers (take_along_axis with (S, J) indices) lower to
# scatter-like HLO that serializes on TPU (~110ms at 1M series x 12 cells);
# when the cell axis is small, a broadcast-compare + masked reduction is
# pure fused VPU work (~10x faster). Above the threshold the (S, J, T)
# virtual intermediate stops fusing profitably and take_along_axis wins.
_TAKE_CELLS_MAX_T = 128


def _take_cells(arr: jax.Array, idx: jax.Array) -> jax.Array:
    """take_along_axis(arr, idx, axis=1) for (S, T) arr and (S, J) idx,
    TPU-reformulated for small T."""
    t = arr.shape[1]
    if t > _TAKE_CELLS_MAX_T:
        return jnp.take_along_axis(arr, idx, axis=1)
    oh = idx[:, :, None] == jnp.arange(t, dtype=jnp.int32)[None, None, :]
    return jnp.sum(
        jnp.where(oh, arr[:, None, :], jnp.zeros((), arr.dtype)), axis=2
    )


# ----------------------------------------------------------------------
# prefix-path kernels
# ----------------------------------------------------------------------

@jax.jit
def window_count(has, lo, hi):
    c = _prefix(has.astype(jnp.int32))
    return _gather_steps(c, hi + 1) - _gather_steps(c, lo + 1)


@jax.jit
def window_sum(vals, has, lo, hi):
    p = _prefix(jnp.where(has, vals, jnp.zeros((), vals.dtype)))
    s = _gather_steps(p, hi + 1) - _gather_steps(p, lo + 1)
    cnt = window_count(has, lo, hi)
    return s, cnt > 0


@jax.jit
def window_avg(vals, has, lo, hi):
    s, _ = window_sum(vals, has, lo, hi)
    cnt = window_count(has, lo, hi)
    return s / jnp.maximum(cnt, 1).astype(s.dtype), cnt > 0


@jax.jit
def window_last(vals, has, tsg, lo, hi):
    """Most recent sample in each window: (value, ts, present)."""
    li = _gather_steps(_last_present_idx(has), hi)
    present = li > lo[None, :]
    safe = jnp.maximum(li, 0)
    v = _take_cells(vals, safe)
    t = _take_cells(tsg, safe)
    return v, t, present


@jax.jit
def window_first(vals, has, tsg, lo, hi):
    fi = _gather_steps(_first_present_idx(has), lo + 1)
    present = fi <= hi[None, :]
    t_max = vals.shape[1] - 1
    safe = jnp.minimum(fi, t_max)
    v = _take_cells(vals, safe)
    t = _take_cells(tsg, safe)
    return v, t, present


def _pair_indicator(vals, has, pred):
    """Per-cell indicator over (prev_sample, sample) pairs; pred(prev, cur)."""
    lastidx = _last_present_idx(has)
    pl = _prev_present_idx(lastidx)
    safe = jnp.maximum(pl, 0)
    prev_val = _take_cells(vals, safe)
    pair = has & (pl >= 0)
    return pair, prev_val


@functools.partial(jax.jit, static_argnames=("is_counter", "is_rate"))
def extrapolated_rate(
    vals, has, tsg, lo, hi, t_end, range_ticks, tps,
    *, is_counter: bool, is_rate: bool,
):
    """Prometheus rate/increase/delta with the extrapolation rules of
    functions.go (semantics per /root/reference/src/promql/src/functions/
    extrapolate_rate.rs:120-205). Returns (value, present) shaped (S, J)."""
    dt = vals.dtype
    lastidx = _last_present_idx(has)
    firstidx = _first_present_idx(has)
    li = _gather_steps(lastidx, hi)          # (S, J)
    fi = _gather_steps(firstidx, lo + 1)     # (S, J)
    t_max = vals.shape[1] - 1
    li_s = jnp.maximum(li, 0)
    fi_s = jnp.minimum(fi, t_max)
    valid = (li > lo[None, :]) & (fi <= hi[None, :]) & (fi < li)
    v_last = _take_cells(vals, li_s)
    v_first = _take_cells(vals, fi_s)
    t_last = _take_cells(tsg, li_s).astype(dt)
    t_first = _take_cells(tsg, fi_s).astype(dt)

    delta = v_last - v_first
    if is_counter:
        pair, prev_val = _pair_indicator(vals, has, None)
        drop = jnp.where(pair & (vals < prev_val), prev_val, jnp.zeros((), dt))
        d = _prefix(drop)
        corr = _gather_steps(d, hi + 1) - _take_cells(d, fi_s + 1)
        delta = delta + corr

    cnt = window_count(has, lo, hi).astype(dt)
    t_end_f = t_end[None, :].astype(dt)
    tps = jnp.asarray(tps, dt)
    dur_start = (t_first - (t_end_f - jnp.asarray(range_ticks, dt))) / tps
    dur_end = (t_end_f - t_last) / tps
    sampled = (t_last - t_first) / tps
    avg_dur = sampled / jnp.maximum(cnt - 1, 1)

    if is_counter:
        # avoid extrapolating a counter below zero
        dur_zero = jnp.where(
            (delta > 0) & (v_first >= 0),
            sampled * (v_first / jnp.where(delta == 0, 1, delta)),
            jnp.asarray(jnp.inf, dt),
        )
        dur_start = jnp.minimum(dur_start, dur_zero)

    thresh = avg_dur * jnp.asarray(1.1, dt)
    extr = sampled
    extr = extr + jnp.where(dur_start < thresh, dur_start, avg_dur / 2)
    extr = extr + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
    factor = extr / jnp.where(sampled == 0, 1, sampled)
    out = delta * factor
    if is_rate:
        out = out / jnp.asarray(range_ticks / tps, dt)
    return jnp.where(valid, out, jnp.zeros((), dt)), valid


@functools.partial(jax.jit, static_argnames=("count_changes",))
def window_pair_count(vals, has, lo, hi, *, count_changes: bool):
    """changes() (value differs from previous) or resets() (value drops)
    over each window. Pairs are (prev sample, sample) with both inside the
    window. Returns (count_float, present)."""
    dt = vals.dtype
    pair, prev_val = _pair_indicator(vals, has, None)
    if count_changes:
        ind = pair & (vals != prev_val)
    else:
        ind = pair & (vals < prev_val)
    p = _prefix(ind.astype(jnp.int32))
    firstidx = _first_present_idx(has)
    fi = _gather_steps(firstidx, lo + 1)
    t_max = vals.shape[1] - 1
    fi_s = jnp.minimum(fi, t_max)
    in_w = fi <= hi[None, :]
    cnt = _gather_steps(p, hi + 1) - _take_cells(p, fi_s + 1)
    cnt = jnp.where(in_w, cnt, 0)
    return cnt.astype(dt), in_w


@functools.partial(jax.jit, static_argnames=("is_rate",))
def instant_delta(vals, has, tsg, lo, hi, tps, *, is_rate: bool):
    """idelta (last two samples' value difference) / irate (per-second,
    with counter-reset handling)."""
    dt = vals.dtype
    lastidx = _last_present_idx(has)
    pl = _prev_present_idx(lastidx)
    li = _gather_steps(lastidx, hi)
    t_max = vals.shape[1] - 1
    li_s = jnp.maximum(li, 0)
    # previous present cell strictly before li
    pi = _take_cells(pl, li_s)
    pi_s = jnp.maximum(pi, 0)
    valid = (li > lo[None, :]) & (pi > lo[None, :]) & (pi >= 0)
    v1 = _take_cells(vals, pi_s)
    v2 = _take_cells(vals, li_s)
    t1 = _take_cells(tsg, pi_s).astype(dt)
    t2 = _take_cells(tsg, li_s).astype(dt)
    if is_rate:
        dv = jnp.where(v2 < v1, v2, v2 - v1)  # counter reset: use raw value
        dtm = jnp.maximum(t2 - t1, 1) / jnp.asarray(tps, dt)
        out = dv / dtm
    else:
        out = v2 - v1
    return jnp.where(valid, out, jnp.zeros((), dt)), valid


# ----------------------------------------------------------------------
# gather-path kernels
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_cells",))
def gather_windows(vals, has, tsg, hi, num_cells: int):
    """Materialize (S, J, L) window tensors: cell hi_j - k for k in [0, L).
    Cells are in reverse time order (k=0 is the window end)."""
    k = jnp.arange(num_cells, dtype=jnp.int32)
    idx = hi[None, :, None] - k[None, None, :]        # (1, J, L)
    ok = idx >= 0
    idx_s = jnp.maximum(idx, 0)
    g_vals = jnp.take(vals, idx_s[0], axis=1)          # (S, J, L)
    g_has = jnp.take(has, idx_s[0], axis=1) & ok[0]
    g_ts = jnp.take(tsg, idx_s[0], axis=1)
    return g_vals, g_has, g_ts


@functools.partial(jax.jit, static_argnames=("num_cells", "op"))
def window_minmax(vals, has, tsg, hi, num_cells: int, op: str):
    g_vals, g_has, _ = gather_windows(vals, has, tsg, hi, num_cells)
    dt = vals.dtype
    if op == "min":
        fill = jnp.asarray(jnp.inf, dt)
        out = jnp.min(jnp.where(g_has, g_vals, fill), axis=2)
    else:
        fill = jnp.asarray(-jnp.inf, dt)
        out = jnp.max(jnp.where(g_has, g_vals, fill), axis=2)
    present = jnp.any(g_has, axis=2)
    return jnp.where(present, out, jnp.zeros((), dt)), present


@functools.partial(jax.jit, static_argnames=("num_cells", "sample_var"))
def window_stdvar(vals, has, tsg, hi, num_cells: int, *, sample_var: bool = False):
    """Population stddev/stdvar over each window (Prometheus semantics).
    Returns (var, stddev, present)."""
    g_vals, g_has, _ = gather_windows(vals, has, tsg, hi, num_cells)
    dt = vals.dtype
    n = jnp.sum(g_has, axis=2).astype(dt)
    n1 = jnp.maximum(n, 1)
    mean = jnp.sum(jnp.where(g_has, g_vals, 0), axis=2) / n1
    dev = jnp.where(g_has, g_vals - mean[:, :, None], 0)
    denom = jnp.maximum(n - 1, 1) if sample_var else n1
    var = jnp.sum(dev * dev, axis=2) / denom
    present = n > 0
    return var, jnp.sqrt(var), present


def _small_sort_lanes(x, length: int):
    """Ascending sort along the last axis via an odd-even transposition
    network: for the short windows quantile_over_time sees (a handful of
    cells), ~L^2/2 vectorized min/max exchanges on (S, J) planes beat
    XLA's general variadic sort by a wide margin at 1M series."""
    cols = [x[:, :, i] for i in range(length)]
    for p in range(length):
        for i in range(p % 2, length - 1, 2):
            a, b = cols[i], cols[i + 1]
            # NaN-last exchange (jnp.sort parity): a min/max pair would
            # smear one NaN into BOTH lanes
            a_first = (a <= b) | jnp.isnan(b)
            cols[i] = jnp.where(a_first, a, b)
            cols[i + 1] = jnp.where(a_first, b, a)
    return jnp.stack(cols, axis=2)


@functools.partial(jax.jit, static_argnames=("num_cells",))
def window_quantile(vals, has, tsg, hi, num_cells: int, q):
    """phi-quantile with linear interpolation (Prometheus
    quantile_over_time). q may be a scalar or (J,) array."""
    g_vals, g_has, _ = gather_windows(vals, has, tsg, hi, num_cells)
    dt = vals.dtype
    fill = jnp.asarray(jnp.inf, dt)
    masked = jnp.where(g_has, g_vals, fill)
    if num_cells <= 16:
        sorted_vals = _small_sort_lanes(masked, num_cells)
    else:
        sorted_vals = jnp.sort(masked, axis=2)
    n = jnp.sum(g_has, axis=2)
    present = n > 0
    q = jnp.asarray(q, dt)
    rank = q * jnp.maximum(n - 1, 0).astype(dt)
    lo_i = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, num_cells - 1)
    hi_i = jnp.clip(jnp.ceil(rank).astype(jnp.int32), 0, num_cells - 1)
    if num_cells <= _TAKE_CELLS_MAX_T:
        # data-dependent take_along_axis lowers to a serializing
        # scatter on TPU (~250ms at 1M series); a one-hot masked
        # reduction over the tiny lane axis is fused VPU work
        lanes = jnp.arange(num_cells, dtype=jnp.int32)[None, None, :]
        z = jnp.zeros((), dt)
        v_lo = jnp.sum(jnp.where(lanes == lo_i[:, :, None],
                                 sorted_vals, z), axis=2)
        v_hi = jnp.sum(jnp.where(lanes == hi_i[:, :, None],
                                 sorted_vals, z), axis=2)
    else:
        v_lo = jnp.take_along_axis(
            sorted_vals, lo_i[:, :, None], axis=2)[:, :, 0]
        v_hi = jnp.take_along_axis(
            sorted_vals, hi_i[:, :, None], axis=2)[:, :, 0]
    frac = rank - jnp.floor(rank)
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(present, out, jnp.zeros((), dt)), present


@functools.partial(jax.jit, static_argnames=("num_cells",))
def window_linear_fit(vals, has, tsg, hi, t_end, num_cells: int, tps):
    """Least-squares line over window samples; t is seconds relative to the
    window end (small, f32-safe). Returns (slope, intercept_at_end, n)."""
    g_vals, g_has, g_ts = gather_windows(vals, has, tsg, hi, num_cells)
    dt = vals.dtype
    t = (g_ts.astype(dt) - t_end[None, :, None].astype(dt)) / jnp.asarray(tps, dt)
    m = g_has.astype(dt)
    n = jnp.sum(m, axis=2)
    st = jnp.sum(t * m, axis=2)
    sv = jnp.sum(jnp.where(g_has, g_vals, 0), axis=2)
    stt = jnp.sum(t * t * m, axis=2)
    stv = jnp.sum(t * jnp.where(g_has, g_vals, 0), axis=2)
    n1 = jnp.maximum(n, 1)
    denom = n1 * stt - st * st
    slope = (n1 * stv - st * sv) / jnp.where(denom == 0, 1, denom)
    intercept = (sv - slope * st) / n1
    return slope, intercept, n


@functools.partial(jax.jit, static_argnames=("num_cells",))
def window_holt_winters(vals, has, tsg, hi, num_cells: int, sf, tf):
    """Double exponential smoothing (Prometheus holt_winters semantics:
    s0 = x0, b0 = x1 - x0, then s_i = sf*x_i + (1-sf)*(s+b),
    b_i = tf*(s_i - s_prev) + (1-tf)*b). Sequential over window samples,
    vectorized over (S, J) via lax.scan along the window axis."""
    g_vals, g_has, _ = gather_windows(vals, has, tsg, hi, num_cells)
    dt = vals.dtype
    # ascending time order: k = L-1 .. 0
    xs_vals = jnp.flip(g_vals, axis=2)
    xs_has = jnp.flip(g_has, axis=2)
    sf = jnp.asarray(sf, dt)
    tf = jnp.asarray(tf, dt)

    def step(carry, xs):
        s, b, x_first, cnt = carry
        x, present = xs
        # cnt: number of samples consumed so far
        new_s1 = x  # when this is the first sample
        new_b1 = jnp.zeros_like(x)
        # second sample: s = x, b = x - x_first  (Prometheus init)
        new_s2 = sf * x + (1 - sf) * (s + b)
        new_b2 = tf * (new_s2 - s) + (1 - tf) * b
        s_out = jnp.where(
            present,
            jnp.where(cnt == 0, new_s1, jnp.where(cnt == 1, x, new_s2)),
            s,
        )
        b_out = jnp.where(
            present,
            jnp.where(cnt == 0, new_b1, jnp.where(cnt == 1, x - x_first, new_b2)),
            b,
        )
        x_first = jnp.where(present & (cnt == 0), x, x_first)
        cnt = cnt + present.astype(jnp.int32)
        return (s_out, b_out, x_first, cnt), None

    shape = g_vals.shape[:2]
    init = (
        jnp.zeros(shape, dt), jnp.zeros(shape, dt), jnp.zeros(shape, dt),
        jnp.zeros(shape, jnp.int32),
    )
    (s, b, _, cnt), _ = jax.lax.scan(
        step, init,
        (jnp.moveaxis(xs_vals, 2, 0), jnp.moveaxis(xs_has, 2, 0)),
    )
    present = cnt >= 2
    return jnp.where(present, s, jnp.zeros((), dt)), present


# ----------------------------------------------------------------------
# instant (lookback) selection
# ----------------------------------------------------------------------

@jax.jit
def instant_lookback(vals, has, tsg, hi, t_end, lookback_ticks):
    """Per step, the most recent sample at or before t_end within the
    lookback delta — PromQL instant-vector selection (reference:
    /root/reference/src/promql/src/extension_plan/instant_manipulate.rs)."""
    dt = vals.dtype
    lastidx = _last_present_idx(has)
    li = _gather_steps(lastidx, hi)
    safe = jnp.maximum(li, 0)
    v = _take_cells(vals, safe)
    t = _take_cells(tsg, safe)
    # int32-safe freshness test: ts is <= t_end by construction, so the
    # difference is small and non-positive.
    age = t_end[None, :] - t
    fresh = age < jnp.int32(lookback_ticks)
    present = (li >= 0) & fresh
    return jnp.where(present, v, jnp.zeros((), dt)), present
