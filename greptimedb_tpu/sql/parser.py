"""Recursive-descent SQL parser over sql.lexer tokens.

Statement surface mirrors the reference's dialect
(/root/reference/src/sql/src/parser.rs and statements/): CREATE TABLE with
TIME INDEX + PRIMARY KEY tag semantics, RANGE queries via ALIGN, TQL, flows,
views, COPY, SHOW/DESCRIBE/EXPLAIN, USE, and the DML core.
"""

from __future__ import annotations

import re

from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.errors import InvalidSyntaxError
from greptimedb_tpu.sql import ast as A
from greptimedb_tpu.sql.lexer import Tok, Token, tokenize

_INTERVAL_RE = re.compile(
    r"^\s*(-?\s*\d+(?:\.\d+)?)\s*(nanosecond|microsecond|millisecond|second|minute|"
    r"hour|day|week|month|year|ns|us|ms|s|m|h|d|w|y)s?\s*$",
    re.IGNORECASE,
)

_UNIT_MS = {
    "nanosecond": 1e-6, "ns": 1e-6,
    "microsecond": 1e-3, "us": 1e-3,
    "millisecond": 1.0, "ms": 1.0,
    "second": 1000.0, "s": 1000.0,
    "minute": 60_000.0, "m": 60_000.0,
    "hour": 3_600_000.0, "h": 3_600_000.0,
    "day": 86_400_000.0, "d": 86_400_000.0,
    "week": 604_800_000.0, "w": 604_800_000.0,
    "month": 2_592_000_000.0, "year": 31_536_000_000.0, "y": 31_536_000_000.0,
}


def parse_interval_ms(text: str) -> int:
    """'5 minutes', '1h', '30s', also compound '1 hour 30 minutes';
    per-part signs carry through ('-1 day' < 0, '1 day -1 hour'),
    including a space-separated sign ('- 1 day')."""
    total = 0.0
    parts = re.findall(
        r"(-?\s*\d+(?:\.\d+)?)\s*([a-zA-Z]+)", text
    )
    if not parts:
        raise InvalidSyntaxError(f"bad interval: {text!r}")
    parts = [(num.replace(" ", ""), unit) for num, unit in parts]
    for num, unit in parts:
        unit = unit.lower().rstrip("s") if unit.lower() not in ("s", "ns", "us", "ms") else unit.lower()
        if unit not in _UNIT_MS:
            unit2 = unit + "s" if unit + "s" in _UNIT_MS else None
            if unit2 is None:
                raise InvalidSyntaxError(f"bad interval unit: {unit!r}")
            unit = unit2
        total += float(num) * _UNIT_MS[unit]
    return int(total)


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0

    # ---- token helpers ------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != Tok.EOF:
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == Tok.IDENT and t.upper in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.eat_kw(kw):
            raise InvalidSyntaxError(
                f"expected {kw} at {self.peek().pos}: got {self.peek().text!r}"
            )

    def at_op(self, op: str) -> bool:
        t = self.peek()
        return t.kind == Tok.OP and t.text == op

    def eat_op(self, op: str) -> bool:
        if self.at_op(op):
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.eat_op(op):
            raise InvalidSyntaxError(
                f"expected {op!r} at {self.peek().pos}: got {self.peek().text!r}"
            )

    def ident(self) -> str:
        t = self.next()
        if t.kind not in (Tok.IDENT, Tok.QIDENT):
            raise InvalidSyntaxError(f"expected identifier at {t.pos}")
        return t.text

    def qualified_name(self) -> str:
        parts = [self.ident()]
        while self.eat_op("."):
            parts.append(self.ident())
        return ".".join(parts)

    # ---- entry --------------------------------------------------------
    @staticmethod
    def parse_sql(sql: str) -> list[A.Statement]:
        p = Parser(sql)
        stmts = []
        while p.peek().kind != Tok.EOF:
            stmts.append(p.statement())
            while p.eat_op(";"):
                pass
        return stmts

    def statement(self) -> A.Statement:
        t = self.peek()
        if t.kind != Tok.IDENT:
            raise InvalidSyntaxError(f"expected statement at {t.pos}")
        kw = t.upper
        if kw in ("SELECT", "WITH"):
            return self.select_or_setop()
        if kw == "CREATE":
            return self.create()
        if kw == "DROP":
            return self.drop()
        if kw == "INSERT":
            return self.insert()
        if kw == "DELETE":
            return self.delete()
        if kw == "SHOW":
            return self.show()
        if kw in ("DESCRIBE", "DESC"):
            self.next()
            self.eat_kw("TABLE")
            return A.DescribeTable(self.qualified_name())
        if kw == "EXPLAIN":
            self.next()
            analyze = self.eat_kw("ANALYZE")
            self.eat_kw("VERBOSE")
            return A.Explain(self.statement(), analyze=analyze)
        if kw == "USE":
            self.next()
            return A.Use(self.ident())
        if kw == "TQL":
            return self.tql()
        if kw == "ALTER":
            return self.alter()
        if kw == "TRUNCATE":
            self.next()
            self.eat_kw("TABLE")
            return A.TruncateTable(self.qualified_name())
        if kw == "COPY":
            return self.copy()
        if kw == "ADMIN":
            return self.admin()
        if kw == "SET":
            return self.set_variable()
        if kw == "KILL":
            self.next()
            # MySQL: KILL [QUERY | CONNECTION] <id>
            if self.at_kw("QUERY") or self.at_kw("CONNECTION"):
                self.next()
            return A.Admin("kill", [self.expr()])
        if kw == "PREPARE":
            return self.prepare()
        if kw == "EXECUTE":
            return self.execute_stmt()
        if kw == "DEALLOCATE":
            self.next()
            self.eat_kw("PREPARE")
            if self.at_kw("ALL"):
                self.next()
                return A.Deallocate("all")
            return A.Deallocate(self.ident())
        raise InvalidSyntaxError(f"unsupported statement {t.text!r} at {t.pos}")

    def prepare(self) -> A.Statement:
        self.expect_kw("PREPARE")
        name = self.ident()
        if self.eat_kw("FROM"):
            t = self.next()
            if t.kind != Tok.STRING:
                raise InvalidSyntaxError(
                    f"PREPARE ... FROM expects a string at {t.pos}"
                )
            return A.Prepare(name, t.text)
        self.expect_kw("AS")
        start = self.peek().pos
        while self.peek().kind != Tok.EOF and not self.at_op(";"):
            self.next()
        t = self.peek()
        end = t.pos if t.kind != Tok.EOF else len(self.sql)
        text = self.sql[start:end].strip()
        if not text:
            raise InvalidSyntaxError("empty PREPARE body")
        return A.Prepare(name, text)

    def execute_stmt(self) -> A.Statement:
        self.expect_kw("EXECUTE")
        name = self.ident()
        args: list[A.Expr] = []
        if self.eat_op("("):
            if not self.eat_op(")"):
                while True:
                    args.append(self.expr())
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
        elif self.eat_kw("USING"):
            while True:
                args.append(self.expr())
                if not self.eat_op(","):
                    break
        return A.Execute(name, args)

    def admin(self) -> A.Statement:
        self.expect_kw("ADMIN")
        func = self.ident()
        args: list[A.Expr] = []
        if self.eat_op("("):
            if not self.eat_op(")"):
                while True:
                    args.append(self.expr())
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
        return A.Admin(func.lower(), args)

    def set_variable(self) -> A.Statement:
        self.expect_kw("SET")
        scope = "session"
        if self.eat_kw("SESSION"):
            scope = "session"
        elif self.eat_kw("GLOBAL"):
            scope = "global"
        elif self.eat_kw("LOCAL"):
            scope = "session"
        name = self.ident()
        # postgres `SET TIME ZONE 'x'`
        if name.upper() == "TIME" and self.at_kw("ZONE"):
            self.next()
            return A.SetVariable([("time_zone", self._set_value())], scope)
        # `SET NAMES <charset> [COLLATE <collation>]`
        if name.upper() == "NAMES" and not self.at_op("="):
            charset = self._set_value()
            assignments = [("names", charset)]
            if self.eat_kw("COLLATE"):
                assignments.append(
                    ("collation_connection", self._set_value())
                )
            return A.SetVariable(assignments, scope)
        # `SET [SESSION|GLOBAL] TRANSACTION ISOLATION LEVEL <levels>` /
        # `SET TRANSACTION READ ONLY|WRITE`
        if name.upper() == "TRANSACTION" and not self.at_op("="):
            assignments = []
            while True:
                if self.eat_kw("ISOLATION"):
                    self.expect_kw("LEVEL")
                    first = self.ident().upper()
                    # each level's word count is fixed; a greedy loop
                    # would eat the READ of a following "READ ONLY"
                    if first == "READ":
                        words = [first, self.ident().upper()]
                    elif first == "REPEATABLE":
                        self.expect_kw("READ")
                        words = [first, "READ"]
                    else:  # SERIALIZABLE
                        words = [first]
                    assignments.append((
                        "transaction_isolation", A.Literal("-".join(words)),
                    ))
                elif self.eat_kw("READ"):
                    mode = self.ident().upper()  # ONLY | WRITE
                    assignments.append(
                        ("transaction_read_only",
                         A.Literal("ON" if mode == "ONLY" else "OFF"))
                    )
                else:
                    break
                # clauses may be comma-separated (MySQL) or juxtaposed
                # (postgres: "... SERIALIZABLE READ ONLY")
                self.eat_op(",")
            if not assignments:
                raise InvalidSyntaxError(
                    f"expected ISOLATION or READ at {self.peek().pos}"
                )
            return A.SetVariable(assignments, scope)
        assignments = []
        while True:
            if not self.eat_op("="):
                self.eat_kw("TO")
            assignments.append((name.lower(), self._set_value()))
            if not self.eat_op(","):
                break
            name = self.ident()
        return A.SetVariable(assignments, scope)

    def _set_value(self) -> A.Expr:
        """A SET value: bare identifiers are string values, not column
        references (MySQL `SET NAMES utf8mb4`, `SET sql_mode = ANSI`)."""
        t = self.peek()
        if t.kind in (Tok.IDENT, Tok.QIDENT) and t.upper not in (
            "TRUE", "FALSE", "NULL", "DEFAULT",
        ):
            nxt = self.peek(1)
            if nxt.kind != Tok.OP or nxt.text in (",", ";"):
                self.next()
                return A.Literal(t.text)
        if self.eat_kw("DEFAULT"):
            return A.Literal("DEFAULT")
        return self.expr()

    # ---- DDL ----------------------------------------------------------
    def create(self) -> A.Statement:
        self.expect_kw("CREATE")
        if self.eat_kw("DATABASE") or self.eat_kw("SCHEMA"):
            ine = self._if_not_exists()
            return A.CreateDatabase(self.ident(), if_not_exists=ine)
        if self.at_kw("OR"):
            self.next()
            self.expect_kw("REPLACE")
            self.expect_kw("VIEW")
            name = self.qualified_name()
            self.expect_kw("AS")
            q, text = self._query_with_text()
            return A.CreateView(name, q, or_replace=True, text=text)
        if self.eat_kw("VIEW"):
            name = self.qualified_name()
            self.expect_kw("AS")
            q, text = self._query_with_text()
            return A.CreateView(name, q, text=text)
        if self.eat_kw("FLOW"):
            ine = self._if_not_exists()
            name = self.qualified_name()
            self.expect_kw("SINK")
            self.expect_kw("TO")
            sink = self.qualified_name()
            expire = None
            if self.eat_kw("EXPIRE"):
                self.expect_kw("AFTER")
                expire_ms = parse_interval_ms(self._interval_text())
                if expire_ms <= 0:
                    raise InvalidSyntaxError(
                        "EXPIRE AFTER interval must be positive"
                    )
                # ceil so a positive sub-second interval stays positive
                expire = (expire_ms + 999) // 1000
            comment = None
            if self.eat_kw("COMMENT"):
                comment = self.next().text
            self.expect_kw("AS")
            return A.CreateFlow(name, sink, self.select(), if_not_exists=ine,
                                expire_after_s=expire, comment=comment)
        if self.eat_kw("TABLE"):
            return self.create_table()
        if self.eat_kw("EXTERNAL"):
            self.expect_kw("TABLE")
            return self.create_table(external=True)
        raise InvalidSyntaxError(f"unsupported CREATE at {self.peek().pos}")

    def _query_with_text(self) -> tuple[A.Statement, str]:
        """Parse a select/compound and return it with its raw SQL text
        (what the catalog persists for views)."""
        start = self.peek().pos
        q = self.select_or_setop()
        t = self.peek()
        end = t.pos if t.kind != Tok.EOF else len(self.sql)
        return q, self.sql[start:end].strip()

    def _if_not_exists(self) -> bool:
        if self.at_kw("IF"):
            self.next()
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def create_table(self, external: bool = False) -> A.CreateTable:
        ine = self._if_not_exists()
        name = self.qualified_name()
        if self.at_kw("LIKE"):
            self.next()
            src = self.qualified_name()
            return A.CreateTable(
                name, [], None, [], if_not_exists=ine, like_table=src
            )
        columns: list[A.ColumnDef] = []
        time_index: str | None = None
        primary_keys: list[str] = []
        if self.eat_op("("):
            while not self.at_op(")"):
                if self.at_kw("TIME"):
                    self.next()
                    self.expect_kw("INDEX")
                    self.expect_op("(")
                    time_index = self.ident()
                    self.expect_op(")")
                elif self.at_kw("PRIMARY"):
                    self.next()
                    self.expect_kw("KEY")
                    self.expect_op("(")
                    primary_keys.append(self.ident())
                    while self.eat_op(","):
                        primary_keys.append(self.ident())
                    self.expect_op(")")
                else:
                    columns.append(self.column_def())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        for c in columns:
            if c.time_index and time_index is None:
                time_index = c.name
            if c.primary_key and c.name not in primary_keys:
                primary_keys.append(c.name)
        engine = "file" if external else "mito"
        options: dict = {}
        partition_cols: list[str] = []
        partitions: list[A.Expr] = []
        while True:
            if self.eat_kw("ENGINE"):
                self.expect_op("=")
                engine = self.ident()
            elif self.at_kw("PARTITION"):
                self.next()
                self.expect_kw("ON")
                self.expect_kw("COLUMNS")
                self.expect_op("(")
                partition_cols.append(self.ident())
                while self.eat_op(","):
                    partition_cols.append(self.ident())
                self.expect_op(")")
                self.expect_op("(")
                depth = 1
                # partition exprs parsed as generic expressions separated
                # by commas at depth 1
                while depth > 0 and self.peek().kind != Tok.EOF:
                    if self.at_op(")") and depth == 1:
                        break
                    partitions.append(self.expr())
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
            elif self.eat_kw("WITH"):
                self.expect_op("(")
                while not self.at_op(")"):
                    key = self.next().text
                    self.expect_op("=")
                    val = self.next().text
                    options[key.lower()] = val
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
            else:
                break
        return A.CreateTable(
            name=name, columns=columns, time_index=time_index,
            primary_keys=primary_keys, if_not_exists=ine, engine=engine,
            options=options, partitions=partitions,
            partition_columns=partition_cols,
        )

    def column_def(self) -> A.ColumnDef:
        name = self.ident()
        dtype = self.data_type()
        col = A.ColumnDef(name=name, data_type=dtype)
        while True:
            if self.eat_kw("NOT"):
                self.expect_kw("NULL")
                col.nullable = False
            elif self.eat_kw("NULL"):
                col.nullable = True
            elif self.at_kw("DEFAULT"):
                self.next()
                e = self.expr()
                col.default = e.value if isinstance(e, A.Literal) else e
            elif self.at_kw("PRIMARY"):
                self.next()
                self.expect_kw("KEY")
                col.primary_key = True
            elif self.at_kw("TIME"):
                self.next()
                self.expect_kw("INDEX")
                col.time_index = True
                col.nullable = False
            elif self.at_kw("FULLTEXT"):
                self.next()
                if self.eat_op("("):  # FULLTEXT(with options)
                    while not self.eat_op(")"):
                        self.next()
                col.fulltext = True
            elif self.at_kw("COMMENT"):
                self.next()
                self.next()
            else:
                break
        return col

    def data_type(self) -> ConcreteDataType:
        base = self.ident().lower()
        if self.eat_op("("):
            args = [self.next().text]
            while self.eat_op(","):
                args.append(self.next().text)
            self.expect_op(")")
            base = f"{base}({','.join(args)})"
        if self.at_kw("UNSIGNED"):
            self.next()
            base = f"{base} unsigned"
        return ConcreteDataType.from_name(base)

    def drop(self) -> A.Statement:
        self.expect_kw("DROP")
        if self.eat_kw("DATABASE") or self.eat_kw("SCHEMA"):
            ie = self._if_exists()
            return A.DropDatabase(self.ident(), if_exists=ie)
        if self.eat_kw("FLOW"):
            ie = self._if_exists()
            return A.DropFlow(self.qualified_name(), if_exists=ie)
        if self.eat_kw("VIEW"):
            ie = self._if_exists()
            return A.DropView(self.qualified_name(), if_exists=ie)
        self.eat_kw("TABLE")
        ie = self._if_exists()
        names = [self.qualified_name()]
        while self.eat_op(","):
            names.append(self.qualified_name())
        return A.DropTable(names, if_exists=ie)

    def _if_exists(self) -> bool:
        if self.at_kw("IF"):
            self.next()
            self.expect_kw("EXISTS")
            return True
        return False

    def alter(self) -> A.AlterTable:
        self.expect_kw("ALTER")
        self.expect_kw("TABLE")
        name = self.qualified_name()
        if self.eat_kw("ADD"):
            self.eat_kw("COLUMN")
            col = self.column_def()
            return A.AlterTable(name, "add_column", column=col)
        if self.eat_kw("DROP"):
            self.eat_kw("COLUMN")
            return A.AlterTable(name, "drop_column",
                                old_name=self.ident())
        if self.eat_kw("RENAME"):
            self.eat_kw("TO")
            return A.AlterTable(name, "rename", new_name=self.ident())
        raise InvalidSyntaxError(f"unsupported ALTER at {self.peek().pos}")

    def copy(self) -> A.Copy:
        self.expect_kw("COPY")
        table = self.qualified_name()
        if self.eat_kw("TO"):
            direction = "to"
        else:
            self.expect_kw("FROM")
            direction = "from"
        path = self.next().text
        fmt = "parquet"
        options: dict = {}
        if self.eat_kw("WITH"):
            self.expect_op("(")
            while not self.at_op(")"):
                key = self.next().text.lower()
                self.expect_op("=")
                val = self.next().text
                options[key] = val
                if not self.eat_op(","):
                    break
            self.expect_op(")")
            fmt = options.get("format", fmt).lower()
        return A.Copy(table, direction, path, format=fmt, options=options)

    # ---- TQL ----------------------------------------------------------
    def tql(self) -> A.Tql:
        self.expect_kw("TQL")
        t = self.next()
        kind = t.upper.lower()
        if kind not in ("eval", "evaluate", "explain", "analyze"):
            raise InvalidSyntaxError(f"unsupported TQL {t.text!r}")
        if kind == "evaluate":
            kind = "eval"
        lookback = None
        if kind in ("explain", "analyze") and not self.at_op("("):
            # TQL EXPLAIN/ANALYZE accept a bare expression (the
            # reference defaults the range to a single instant at 0)
            start = end = A.Literal(0)
            step = A.Literal("5m")
        else:
            self.expect_op("(")
            start = self.expr()
            self.expect_op(",")
            end = self.expr()
            self.expect_op(",")
            step = self.expr()
            if self.eat_op(","):
                lookback = self.expr()
            self.expect_op(")")
        # the rest of the statement text is the raw PromQL query
        t0 = self.peek()
        query = self.sql[t0.pos:].strip().rstrip(";")
        # consume remaining tokens
        while self.peek().kind != Tok.EOF and not self.at_op(";"):
            self.next()
        return A.Tql(kind=kind, start=start, end=end, step=step,
                     query=query, lookback=lookback)

    # ---- DML ----------------------------------------------------------
    def insert(self) -> A.Insert:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.qualified_name()
        columns: list[str] = []
        if self.eat_op("("):
            columns.append(self.ident())
            while self.eat_op(","):
                columns.append(self.ident())
            self.expect_op(")")
        if self.at_kw("SELECT"):
            return A.Insert(table, columns, [], select=self.select())
        self.expect_kw("VALUES")
        values: list[list[A.Expr]] = []
        while True:
            self.expect_op("(")
            row = [self.expr()]
            while self.eat_op(","):
                row.append(self.expr())
            self.expect_op(")")
            values.append(row)
            if not self.eat_op(","):
                break
        return A.Insert(table, columns, values)

    def delete(self) -> A.Delete:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        table = self.qualified_name()
        where = self.expr() if self.eat_kw("WHERE") else None
        return A.Delete(table, where)

    # ---- SHOW ---------------------------------------------------------
    def show(self) -> A.Statement:
        self.expect_kw("SHOW")
        full = self.eat_kw("FULL")
        if self.eat_kw("DATABASES") or self.eat_kw("SCHEMAS"):
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().text
            return A.ShowDatabases(like=like)
        if self.eat_kw("TABLES"):
            like = None
            db = None
            if self.eat_kw("FROM") or self.eat_kw("IN"):
                db = self.ident()
            if self.eat_kw("LIKE"):
                like = self.next().text
            return A.ShowTables(like=like, database=db, full=full)
        if self.eat_kw("FLOWS"):
            return A.ShowFlows()
        if self.eat_kw("VIEWS"):
            return A.ShowViews()
        if self.eat_kw("CREATE"):
            if self.eat_kw("VIEW"):
                return A.ShowCreateView(self.qualified_name())
            if self.eat_kw("FLOW"):
                return A.ShowCreateFlow(self.qualified_name())
            self.expect_kw("TABLE")
            return A.ShowCreateTable(self.qualified_name())
        if self.eat_kw("VARIABLES"):
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().text
            return A.ShowVariables(like=like)
        if self.eat_kw("COLUMNS") or self.eat_kw("FIELDS"):
            self.expect_kw("FROM")
            table, db = self._show_table_target()
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().text
            return A.ShowColumns(table, database=db, like=like, full=full)
        if self.eat_kw("INDEX") or self.eat_kw("INDEXES") or self.eat_kw("KEYS"):
            self.expect_kw("FROM")
            table, db = self._show_table_target()
            return A.ShowIndex(table, database=db)
        if self.at_kw("GLOBAL") or self.at_kw("SESSION"):
            self.next()
            if self.eat_kw("STATUS"):
                return A.ShowStatus()
            self.expect_kw("VARIABLES")
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().text
            return A.ShowVariables(like=like)
        if self.eat_kw("STATUS"):
            return A.ShowStatus()
        if self.eat_kw("CHARSET") or self.eat_kw("CHARACTER"):
            self.eat_kw("SET")
            return A.ShowCharset()
        if self.eat_kw("COLLATION"):
            return A.ShowCollation()
        if self.eat_kw("PROCESSLIST"):
            return A.ShowProcesslist(full=full)
        raise InvalidSyntaxError(f"unsupported SHOW at {self.peek().pos}")

    def _show_table_target(self) -> tuple[str, str | None]:
        """`tbl [FROM|IN db]` or `db.tbl` (MySQL qualified form)."""
        name = self.ident()
        db = None
        if self.eat_op("."):
            db, name = name, self.ident()
        elif self.eat_kw("FROM") or self.eat_kw("IN"):
            db = self.ident()
        return name, db

    # ---- SELECT -------------------------------------------------------
    def select_or_setop(self) -> A.Statement:
        """[WITH ...] select-core (UNION|INTERSECT|EXCEPT [ALL] core)*.
        A trailing ORDER BY / LIMIT on the last core applies to the whole
        compound (standard SQL)."""
        ctes: list[tuple[str, A.Statement]] = []
        if self.eat_kw("WITH"):
            while True:
                name = self.ident()
                self.expect_kw("AS")
                self.expect_op("(")
                q = self.select_or_setop()
                self.expect_op(")")
                ctes.append((name, q))
                if not self.eat_op(","):
                    break
        left, l_paren = self._intersect_level()
        had_setop = False
        last_paren = l_paren
        while self.at_kw("UNION", "EXCEPT"):
            op = self.next().upper.lower()
            all_ = self.eat_kw("ALL")
            self.eat_kw("DISTINCT")
            self._check_core_clean(left, l_paren or had_setop)
            right, r_paren = self._intersect_level()
            left = A.SetOp(op=op, all=all_, left=left, right=right)
            had_setop = True
            last_paren = r_paren
        if isinstance(left, A.SetOp):
            # trailing order/limit of the last UNPARENTHESIZED core binds
            # to the whole compound (standard SQL); a parenthesized
            # operand keeps its own ORDER BY / LIMIT
            last = left.right
            if isinstance(last, (A.Select, A.SetOp)) and not last_paren \
                    and not left.order_by and left.limit is None \
                    and (last.order_by or last.limit is not None):
                left.order_by = last.order_by
                left.limit = last.limit
                left.offset = last.offset
                last.order_by = []
                last.limit = last.offset = None
            # a parenthesized last operand keeps its own clauses; the
            # compound's ORDER BY / LIMIT can still follow the parens
            if not left.order_by and self.eat_kw("ORDER"):
                self.expect_kw("BY")
                left.order_by = [self.order_item()]
                while self.eat_op(","):
                    left.order_by.append(self.order_item())
            if left.limit is None and self.eat_kw("LIMIT"):
                left.limit = int(self.next().text)
            if left.offset is None and self.eat_kw("OFFSET"):
                left.offset = int(self.next().text)
        if ctes:
            left.ctes = ctes
        return left

    def _intersect_level(self) -> tuple[A.Statement, bool]:
        """INTERSECT binds tighter than UNION/EXCEPT (standard SQL).
        Returns (stmt, last operand was parenthesized)."""
        left, l_paren = self.select_core()
        had = False
        last_paren = l_paren
        while self.at_kw("INTERSECT"):
            self.next()
            all_ = self.eat_kw("ALL")
            self.eat_kw("DISTINCT")
            self._check_core_clean(left, l_paren or had)
            right, r_paren = self.select_core()
            left = A.SetOp(op="intersect", all=all_, left=left, right=right)
            had = True
            last_paren = r_paren
        if isinstance(left, A.SetOp) and had:
            last = left.right
            if isinstance(last, A.Select) and not last_paren:
                left.order_by = last.order_by
                left.limit = last.limit
                left.offset = last.offset
                last.order_by = []
                last.limit = last.offset = None
        return left, last_paren and not had

    def _check_core_clean(self, core, parenthesized: bool):
        if parenthesized:
            return
        if isinstance(core, A.Select) and (
            core.order_by or core.limit is not None
        ):
            raise InvalidSyntaxError(
                "ORDER BY / LIMIT before a set operator — "
                "parenthesize the subquery"
            )

    def select_core(self) -> tuple[A.Select | A.SetOp, bool]:
        """Returns (select, was_parenthesized)."""
        if self.at_op("("):
            # parenthesized select as a set-operation operand
            save = self.i
            self.next()
            if self.at_kw("SELECT", "WITH"):
                q = self.select_or_setop()
                self.expect_op(")")
                return q, True
            self.i = save
        return self.select(), False

    def select(self) -> A.Select:
        self.expect_kw("SELECT")
        distinct = self.eat_kw("DISTINCT")
        items = [self.select_item()]
        while self.eat_op(","):
            items.append(self.select_item())
        from_table = None
        source = None
        if self.eat_kw("FROM"):
            source = self.from_source()
            if isinstance(source, A.TableName):
                from_table = source.name
        where = self.expr() if self.eat_kw("WHERE") else None
        range_clause = None
        if self.at_kw("ALIGN"):
            range_clause = self.align_clause()
        group_by: list[A.Expr] = []
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.expr())
            while self.eat_op(","):
                group_by.append(self.expr())
        having = self.expr() if self.eat_kw("HAVING") else None
        if range_clause is None and self.at_kw("ALIGN"):
            range_clause = self.align_clause()
        order_by: list[A.OrderItem] = []
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.order_item())
            while self.eat_op(","):
                order_by.append(self.order_item())
        limit = offset = None
        if self.eat_kw("LIMIT"):
            limit = int(self.next().text)
            # MySQL `LIMIT offset, count`
            if self.eat_op(","):
                offset, limit = limit, int(self.next().text)
        if self.eat_kw("OFFSET"):
            offset = int(self.next().text)
        if limit is None and offset is not None and self.eat_kw("LIMIT"):
            limit = int(self.next().text)  # postgres `OFFSET n LIMIT m`
        return A.Select(
            items=items, from_table=from_table, where=where,
            group_by=group_by, having=having, order_by=order_by,
            limit=limit, offset=offset, range_clause=range_clause,
            distinct=distinct, source=source,
        )

    # ---- FROM sources -------------------------------------------------
    _ALIAS_STOP = (
        "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "ALIGN",
        "UNION", "INTERSECT", "EXCEPT", "JOIN", "INNER", "LEFT", "RIGHT",
        "FULL", "CROSS", "ON", "USING", "AS", "FILL", "BY", "TO", "SET",
    )

    def _maybe_alias(self) -> str | None:
        if self.eat_kw("AS"):
            return self.ident()
        t = self.peek()
        if t.kind in (Tok.IDENT, Tok.QIDENT) and not self.at_kw(
            *self._ALIAS_STOP
        ):
            return self.ident()
        return None

    def table_factor(self):
        if self.at_op("("):
            save = self.i
            self.next()
            if self.at_kw("SELECT", "WITH"):
                q = self.select_or_setop()
                self.expect_op(")")
                alias = self._maybe_alias()
                if alias is None:
                    raise InvalidSyntaxError("FROM subquery needs an alias")
                return A.SubquerySource(q, alias)
            # parenthesized join tree
            src = self.from_source()
            self.expect_op(")")
            return src
        name = self.qualified_name()
        return A.TableName(name, self._maybe_alias())

    def from_source(self):
        left = self.table_factor()
        while True:
            if self.at_kw("CROSS"):
                self.next()
                self.expect_kw("JOIN")
                left = A.JoinSource(left, self.table_factor(), "cross")
                continue
            kind = None
            if self.at_kw("JOIN", "INNER"):
                self.eat_kw("INNER")
                kind = "inner"
            elif self.at_kw("LEFT"):
                self.next()
                self.eat_kw("OUTER")
                kind = "left"
            elif self.at_kw("RIGHT"):
                self.next()
                self.eat_kw("OUTER")
                kind = "right"
            elif self.at_kw("FULL"):
                self.next()
                self.eat_kw("OUTER")
                kind = "full"
            elif self.eat_op(","):
                left = A.JoinSource(left, self.table_factor(), "cross")
                continue
            else:
                return left
            self.expect_kw("JOIN")
            right = self.table_factor()
            on = None
            using = None
            if self.eat_kw("ON"):
                on = self.expr()
            elif self.eat_kw("USING"):
                self.expect_op("(")
                using = [self.ident()]
                while self.eat_op(","):
                    using.append(self.ident())
                self.expect_op(")")
            left = A.JoinSource(left, right, kind, on, using)

    def align_clause(self) -> A.RangeClause:
        self.expect_kw("ALIGN")
        align_ms = parse_interval_ms(self._interval_text())
        if align_ms <= 0:
            raise InvalidSyntaxError(
                "ALIGN interval must be positive"
            )
        to = None
        if self.eat_kw("TO"):
            to = self.next().text
        by = None
        if self.eat_kw("BY"):
            self.expect_op("(")
            # BY () = one global group (reference range_select semantics)
            by = []
            if not self.eat_op(")"):
                by = [self.expr()]
                while self.eat_op(","):
                    by.append(self.expr())
                self.expect_op(")")
        fill = None
        if self.eat_kw("FILL"):
            fill = self.next().text.lower()
        return A.RangeClause(align_ms=align_ms, to=to, by=by, fill=fill)

    def _interval_text(self) -> str:
        t = self.next()
        if t.kind in (Tok.STRING, Tok.NUMBER, Tok.IDENT):
            # '5m' | '5 minutes' | 5m (ident-number mix)
            if t.kind == Tok.NUMBER and self.peek().kind == Tok.IDENT:
                return t.text + self.next().text
            return t.text
        raise InvalidSyntaxError(f"expected interval at {t.pos}")

    def select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.next()
            return A.SelectItem(A.Star())
        e = self.expr()
        alias = None
        if self.eat_kw("AS"):
            alias = self.ident()
        elif self.peek().kind in (Tok.IDENT, Tok.QIDENT) and not self.at_kw(
            "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET",
            "ALIGN", "UNION", "INTERSECT", "EXCEPT", "FILL", "BY", "TO",
        ):
            alias = self.ident()
        return A.SelectItem(e, alias)

    def order_item(self) -> A.OrderItem:
        e = self.expr()
        asc = True
        if self.eat_kw("DESC"):
            asc = False
        else:
            self.eat_kw("ASC")
        nulls_first = None
        if self.eat_kw("NULLS"):
            if self.eat_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return A.OrderItem(e, asc, nulls_first)

    # ---- expressions (precedence climbing) ----------------------------
    def expr(self) -> A.Expr:
        return self.or_expr()

    def or_expr(self) -> A.Expr:
        left = self.and_expr()
        while self.at_kw("OR"):
            self.next()
            left = A.BinaryOp("or", left, self.and_expr())
        return left

    def and_expr(self) -> A.Expr:
        left = self.not_expr()
        while self.at_kw("AND"):
            self.next()
            left = A.BinaryOp("and", left, self.not_expr())
        return left

    def not_expr(self) -> A.Expr:
        if self.at_kw("NOT"):
            save = self.i
            self.next()
            if self.at_kw("EXISTS"):
                self.next()
                self.expect_op("(")
                q = self.select_or_setop()
                self.expect_op(")")
                return A.Exists(q, negated=True)
            self.i = save
            self.next()
            return A.UnaryOp("not", self.not_expr())
        if self.at_kw("EXISTS"):
            self.next()
            self.expect_op("(")
            q = self.select_or_setop()
            self.expect_op(")")
            return A.Exists(q)
        return self.cmp_expr()

    def cmp_expr(self) -> A.Expr:
        left = self.add_expr()
        t = self.peek()
        if t.kind == Tok.OP and t.text in ("=", "!=", "<>", "<", "<=", ">",
                                           ">=", "=~", "!~"):
            self.next()
            op = {"<>": "!=", "=~": "like"}.get(t.text, t.text)
            return A.BinaryOp(op, left, self.add_expr())
        if self.at_kw("LIKE"):
            self.next()
            return A.BinaryOp("like", left, self.add_expr())
        if self.at_kw("BETWEEN"):
            self.next()
            low = self.add_expr()
            self.expect_kw("AND")
            return A.Between(left, low, self.add_expr())
        if self.at_kw("IN"):
            self.next()
            self.expect_op("(")
            if self.at_kw("SELECT", "WITH"):
                q = self.select_or_setop()
                self.expect_op(")")
                return A.InSubquery(left, q)
            items = [self.expr()]
            while self.eat_op(","):
                items.append(self.expr())
            self.expect_op(")")
            return A.InList(left, items)
        if self.at_kw("NOT"):
            save = self.i
            self.next()
            if self.eat_kw("BETWEEN"):
                low = self.add_expr()
                self.expect_kw("AND")
                return A.Between(left, low, self.add_expr(), negated=True)
            if self.eat_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    q = self.select_or_setop()
                    self.expect_op(")")
                    return A.InSubquery(left, q, negated=True)
                items = [self.expr()]
                while self.eat_op(","):
                    items.append(self.expr())
                self.expect_op(")")
                return A.InList(left, items, negated=True)
            if self.eat_kw("LIKE"):
                return A.UnaryOp(
                    "not", A.BinaryOp("like", left, self.add_expr())
                )
            self.i = save
        if self.at_kw("IS"):
            self.next()
            negated = self.eat_kw("NOT")
            self.expect_kw("NULL")
            return A.IsNull(left, negated=negated)
        return left

    def add_expr(self) -> A.Expr:
        left = self.mul_expr()
        while True:
            t = self.peek()
            if t.kind == Tok.OP and t.text in ("+", "-", "||"):
                self.next()
                left = A.BinaryOp(t.text, left, self.mul_expr())
            else:
                return left

    def mul_expr(self) -> A.Expr:
        left = self.unary()
        while True:
            t = self.peek()
            if t.kind == Tok.OP and t.text in ("*", "/", "%"):
                self.next()
                left = A.BinaryOp(t.text, left, self.unary())
            else:
                return left

    def unary(self) -> A.Expr:
        if self.at_op("-"):
            self.next()
            return A.UnaryOp("-", self.unary())
        if self.at_op("+"):
            self.next()
            return self.unary()
        return self.postfix()

    def postfix(self) -> A.Expr:
        e = self.primary()
        while self.eat_op("::"):
            e = A.Cast(e, self.data_type())
        return e

    def primary(self) -> A.Expr:
        t = self.peek()
        if t.kind == Tok.NUMBER:
            self.next()
            text = t.text
            if "." in text or "e" in text or "E" in text:
                return A.Literal(float(text))
            return A.Literal(int(text))
        if t.kind == Tok.STRING:
            self.next()
            if _INTERVAL_RE.match(t.text):
                return A.IntervalLit(parse_interval_ms(t.text), t.text)
            return A.Literal(t.text)
        if self.eat_op("("):
            if self.at_kw("SELECT", "WITH"):
                q = self.select_or_setop()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if self.at_op("*"):
            self.next()
            return A.Star()
        if t.kind in (Tok.IDENT, Tok.QIDENT):
            up = t.upper
            if up == "NULL":
                self.next()
                return A.Literal(None)
            if up == "TRUE":
                self.next()
                return A.Literal(True)
            if up == "FALSE":
                self.next()
                return A.Literal(False)
            if up == "INTERVAL":
                self.next()
                text = self._interval_text()
                return A.IntervalLit(parse_interval_ms(text), text)
            if up in ("TIMESTAMP", "DATE", "TIME") \
                    and self.peek(1).kind == Tok.STRING:
                # typed literals: TIMESTAMP '2024-01-01 00:00:00'
                self.next()
                lit = self.next().text
                if up == "TIMESTAMP":
                    return A.Cast(
                        A.Literal(lit),
                        ConcreteDataType.timestamp_millisecond(),
                    )
                if up == "DATE":
                    return A.Cast(A.Literal(lit), ConcreteDataType.date())
                return A.Literal(lit)
            if up == "CASE":
                return self.case_expr()
            if up == "CAST":
                self.next()
                self.expect_op("(")
                e = self.expr()
                self.expect_kw("AS")
                to = self.data_type()
                self.expect_op(")")
                return A.Cast(e, to)
            name = self.qualified_name()
            if self.at_op("("):
                return self.func_call(name)
            if "." in name:
                parts = name.rsplit(".", 1)
                return A.Column(parts[1], table=parts[0])
            return A.Column(name)
        raise InvalidSyntaxError(
            f"unexpected token {t.text!r} at {t.pos}"
        )

    def case_expr(self) -> A.Expr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.expr()
        whens = []
        while self.eat_kw("WHEN"):
            cond = self.expr()
            self.expect_kw("THEN")
            whens.append((cond, self.expr()))
        else_ = None
        if self.eat_kw("ELSE"):
            else_ = self.expr()
        self.expect_kw("END")
        return A.Case(operand, whens, else_)

    def func_call(self, name: str) -> A.Expr:
        self.expect_op("(")
        # EXTRACT(part FROM expr) standard form -> extract('part', expr)
        if name.lower() in ("extract", "date_part") \
                and self.peek().kind == Tok.IDENT \
                and self.peek(1).kind == Tok.IDENT \
                and self.peek(1).upper == "FROM":
            part = self.ident()
            self.expect_kw("FROM")
            operand = self.expr()
            self.expect_op(")")
            return A.FuncCall(name.lower(),
                              [A.Literal(part.lower()), operand])
        distinct = self.eat_kw("DISTINCT")
        args: list[A.Expr] = []
        order_by: list[A.OrderItem] = []
        if not self.at_op(")"):
            args.append(self.expr())
            while self.eat_op(","):
                args.append(self.expr())
            if self.eat_kw("ORDER"):
                self.expect_kw("BY")
                order_by.append(self.order_item())
                while self.eat_op(","):
                    order_by.append(self.order_item())
        self.expect_op(")")
        fc = A.FuncCall(name.lower(), args, distinct=distinct,
                        order_by=order_by)
        if self.at_kw("WITHIN"):
            # percentile_cont(f) WITHIN GROUP (ORDER BY x) -> quantile
            # agg; ORDER BY x DESC means the fraction counts from the
            # top, i.e. the ascending (1 - f) quantile
            self.next()
            self.expect_kw("GROUP")
            self.expect_op("(")
            self.expect_kw("ORDER")
            self.expect_kw("BY")
            target = self.order_item()
            self.expect_op(")")
            args = list(fc.args)
            if not target.asc and args:
                args[0] = A.BinaryOp("-", A.Literal(1.0), args[0])
            fc = A.FuncCall(fc.name, args + [target.expr],
                            distinct=fc.distinct)
        if self.at_kw("FILTER"):
            # SQL:2003 aggregate filter: agg(x) FILTER (WHERE cond)
            self.next()
            self.expect_op("(")
            self.expect_kw("WHERE")
            fc.filter = self.expr()
            self.expect_op(")")
        if self.at_kw("OVER"):
            self.next()
            fc.over = self.window_spec()
            if fc.filter is not None:
                raise InvalidSyntaxError(
                    "FILTER on window functions is not supported"
                )
        if self.at_kw("RANGE") and fc.over is None:
            if fc.filter is not None:
                raise InvalidSyntaxError(
                    "FILTER is not supported on RANGE aggregates"
                )
            self.next()
            range_ms = parse_interval_ms(self._interval_text())
            if range_ms <= 0:
                raise InvalidSyntaxError(
                    "RANGE interval must be positive"
                )
            fill = None
            if self.at_kw("FILL"):
                self.next()
                fill = self.next().text.lower()
            return A.RangeFunc(fc, range_ms, fill)
        return fc

    def window_spec(self) -> A.WindowSpec:
        self.expect_op("(")
        spec = A.WindowSpec()
        if self.eat_kw("PARTITION"):
            self.expect_kw("BY")
            spec.partition_by.append(self.expr())
            while self.eat_op(","):
                spec.partition_by.append(self.expr())
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            spec.order_by.append(self.order_item())
            while self.eat_op(","):
                spec.order_by.append(self.order_item())
        if self.at_kw("ROWS", "RANGE", "GROUPS"):
            words = [self.next().upper]
            while not self.at_op(")"):
                t = self.next()
                words.append(t.upper if t.kind == Tok.IDENT else t.text)
            spec.frame = " ".join(words)
        self.expect_op(")")
        return spec


def parse_sql(sql: str) -> list[A.Statement]:
    return Parser.parse_sql(sql)
