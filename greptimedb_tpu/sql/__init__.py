"""SQL dialect: hand-written lexer + recursive-descent parser.

Capability counterpart of the reference's sqlparser-rs based dialect
(/root/reference/src/sql/src/parser.rs): CREATE TABLE with TIME INDEX and
tag PRIMARY KEY, range queries (ALIGN), TQL, flows, SHOW/DESCRIBE/EXPLAIN,
and the DML/DQL core."""
