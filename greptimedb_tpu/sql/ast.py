"""SQL AST node definitions (statements + expressions)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from greptimedb_tpu.datatypes.types import ConcreteDataType


# ----------------------------------------------------------------------
# expressions
# ----------------------------------------------------------------------

@dataclass
class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any          # int | float | str | bool | None


@dataclass
class IntervalLit(Expr):
    ms: int
    raw: str


@dataclass
class Column(Expr):
    name: str
    table: str | None = None


@dataclass
class Star(Expr):
    pass


@dataclass
class BinaryOp(Expr):
    op: str             # + - * / % = != < <= > >= and or like
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str             # - not
    operand: Expr


@dataclass
class FuncCall(Expr):
    name: str
    args: list[Expr] = field(default_factory=list)
    distinct: bool = False
    order_by: list["OrderItem"] = field(default_factory=list)
    over: "WindowSpec | None" = None   # window function when set
    filter: "Expr | None" = None       # agg FILTER (WHERE ...) clause


@dataclass
class WindowSpec:
    """OVER ([PARTITION BY ...] [ORDER BY ...] [frame]). frame is the
    normalized frame text; None means the SQL default (whole partition
    without ORDER BY, running peer-frame with it)."""

    partition_by: list[Expr] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)
    frame: str | None = None


@dataclass
class RangeFunc(Expr):
    """`agg(x) RANGE '10s' [FILL v]` — per-item range window in a RANGE
    select (reference: src/query/src/range_select/plan.rs RangeFn)."""

    func: "FuncCall"
    range_ms: int
    fill: str | None = None


@dataclass
class Cast(Expr):
    operand: Expr
    to: ConcreteDataType


@dataclass
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    operand: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclass
class Case(Expr):
    operand: Optional[Expr]
    whens: list[tuple[Expr, Expr]]
    else_: Optional[Expr]


@dataclass
class ScalarSubquery(Expr):
    """(SELECT ...) used as a scalar value (uncorrelated)."""

    query: "Statement"


@dataclass
class InSubquery(Expr):
    """x [NOT] IN (SELECT ...) (uncorrelated)."""

    operand: Expr
    query: "Statement"
    negated: bool = False


@dataclass
class Exists(Expr):
    """[NOT] EXISTS (SELECT ...) (uncorrelated)."""

    query: "Statement"
    negated: bool = False


# ----------------------------------------------------------------------
# FROM sources
# ----------------------------------------------------------------------

@dataclass
class TableName:
    """A (possibly aliased) base table, CTE, or view reference."""

    name: str
    alias: str | None = None


@dataclass
class SubquerySource:
    """(SELECT ...) AS alias in FROM."""

    query: "Statement"              # Select | SetOp
    alias: str


@dataclass
class JoinSource:
    left: object                    # TableName | SubquerySource | JoinSource
    right: object
    kind: str                       # inner | left | right | full | cross
    on: Expr | None = None
    using: list[str] | None = None


# ----------------------------------------------------------------------
# statements
# ----------------------------------------------------------------------

@dataclass
class Statement:
    pass


@dataclass
class ColumnDef:
    name: str
    data_type: ConcreteDataType
    nullable: bool = True
    default: Any = None
    primary_key: bool = False
    time_index: bool = False
    fulltext: bool = False


@dataclass
class CreateTable(Statement):
    name: str
    columns: list[ColumnDef]
    time_index: str | None
    primary_keys: list[str]
    if_not_exists: bool = False
    engine: str = "mito"
    options: dict = field(default_factory=dict)
    partitions: list[Expr] = field(default_factory=list)
    partition_columns: list[str] = field(default_factory=list)
    like_table: str | None = None   # CREATE TABLE t LIKE source


@dataclass
class CreateDatabase(Statement):
    name: str
    if_not_exists: bool = False


@dataclass
class DropTable(Statement):
    names: list[str]
    if_exists: bool = False


@dataclass
class DropDatabase(Statement):
    name: str
    if_exists: bool = False


@dataclass
class TruncateTable(Statement):
    name: str


@dataclass
class AlterTable(Statement):
    name: str
    action: str                     # add_column | drop_column | rename
    column: ColumnDef | None = None
    old_name: str | None = None
    new_name: str | None = None


@dataclass
class Insert(Statement):
    table: str
    columns: list[str]
    values: list[list[Expr]]
    select: Optional["Select"] = None


@dataclass
class Delete(Statement):
    table: str
    where: Expr | None


@dataclass
class OrderItem:
    expr: Expr
    asc: bool = True
    nulls_first: bool | None = None


@dataclass
class SelectItem:
    expr: Expr
    alias: str | None = None


@dataclass
class RangeClause:
    """GreptimeDB RANGE query: ALIGN <interval> [TO ...] [BY (...)] [FILL ...]"""

    align_ms: int
    to: str | None = None
    by: list[Expr] | None = None
    fill: str | None = None


@dataclass
class Select(Statement):
    items: list[SelectItem]
    from_table: str | None = None   # set when FROM is one plain table
    where: Expr | None = None
    group_by: list[Expr] = field(default_factory=list)
    having: Expr | None = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: int | None = None
    offset: int | None = None
    range_clause: RangeClause | None = None
    distinct: bool = False
    source: object | None = None    # TableName | SubquerySource | JoinSource
    ctes: list[tuple[str, "Statement"]] = field(default_factory=list)


@dataclass
class SetOp(Statement):
    """UNION / INTERSECT / EXCEPT compound select. Trailing ORDER BY /
    LIMIT apply to the combined result."""

    op: str                         # union | intersect | except
    all: bool
    left: Statement                 # Select | SetOp
    right: Statement
    order_by: list[OrderItem] = field(default_factory=list)
    limit: int | None = None
    offset: int | None = None
    ctes: list[tuple[str, "Statement"]] = field(default_factory=list)


@dataclass
class Use(Statement):
    database: str


@dataclass
class ShowDatabases(Statement):
    like: str | None = None


@dataclass
class ShowTables(Statement):
    like: str | None = None
    database: str | None = None
    full: bool = False


@dataclass
class ShowCreateTable(Statement):
    name: str


@dataclass
class ShowFlows(Statement):
    pass


@dataclass
class ShowViews(Statement):
    pass


@dataclass
class ShowCreateView(Statement):
    name: str


@dataclass
class ShowCreateFlow(Statement):
    name: str


@dataclass
class DescribeTable(Statement):
    name: str


@dataclass
class Explain(Statement):
    statement: Statement
    analyze: bool = False


@dataclass
class Tql(Statement):
    """TQL EVAL (start, end, step) <promql> | TQL ANALYZE ... | TQL EXPLAIN"""

    kind: str                       # eval | explain | analyze
    start: Expr
    end: Expr
    step: Expr
    query: str
    lookback: Expr | None = None


@dataclass
class CreateFlow(Statement):
    name: str
    sink_table: str
    query: Select
    if_not_exists: bool = False
    expire_after_s: int | None = None
    comment: str | None = None


@dataclass
class DropFlow(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateView(Statement):
    name: str
    query: Statement                # Select | SetOp
    or_replace: bool = False
    text: str | None = None         # raw SQL of the query (persisted)


@dataclass
class DropView(Statement):
    name: str
    if_exists: bool = False


@dataclass
class Copy(Statement):
    table: str
    direction: str                  # to | from
    path: str
    format: str = "parquet"
    options: dict = field(default_factory=dict)


@dataclass
class Admin(Statement):
    """ADMIN func(args...) — maintenance functions callable from SQL
    (reference: src/sql/src/statements/admin.rs + the admin function set
    in src/common/function/src/{flush_flow,system}/)."""

    func: str
    args: list[Expr] = field(default_factory=list)


@dataclass
class SetVariable(Statement):
    """SET [SESSION|GLOBAL] name = value [, name = value ...]
    (reference: src/operator/src/statement/set.rs)."""

    assignments: list  # list[tuple[str, Expr]]
    scope: str = "session"


@dataclass
class ShowVariables(Statement):
    name: str | None = None         # SHOW VARIABLES LIKE 'x' / SHOW VARIABLES
    like: str | None = None


@dataclass
class ShowColumns(Statement):
    table: str
    database: str | None = None
    like: str | None = None
    full: bool = False


@dataclass
class ShowIndex(Statement):
    table: str
    database: str | None = None


@dataclass
class ShowStatus(Statement):
    pass


@dataclass
class ShowCharset(Statement):
    pass


@dataclass
class ShowCollation(Statement):
    pass


@dataclass
class ShowProcesslist(Statement):
    full: bool = False


@dataclass
class Prepare(Statement):
    """PREPARE name FROM '<sql>' (MySQL) | PREPARE name AS <stmt> (PG).
    The statement text is stored per-session with ?/$n placeholders."""

    name: str
    sql_text: str


@dataclass
class Execute(Statement):
    """EXECUTE name [(args...)] | EXECUTE name USING args..."""

    name: str
    args: list[Expr] = field(default_factory=list)


@dataclass
class Deallocate(Statement):
    name: str
