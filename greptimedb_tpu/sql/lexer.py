"""SQL tokenizer."""

from __future__ import annotations

import enum
from dataclasses import dataclass

from greptimedb_tpu.errors import InvalidSyntaxError


class Tok(enum.Enum):
    IDENT = "ident"
    QIDENT = "qident"        # "quoted" or `backtick` identifier
    NUMBER = "number"
    STRING = "string"
    OP = "op"
    EOF = "eof"


@dataclass
class Token:
    kind: Tok
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", "||", "::", "->", "=~", "!~"}
_ONE_CHAR_OPS = set("+-*/%(),.;=<>[]{}@:?$^")  # ^ rides for TQL pow


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            while i < n and sql[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i + 1
            seen_dot = c == "."
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)
                             or sql[j] in "eE"
                             or (sql[j] in "+-" and sql[j - 1] in "eE")):
                if sql[j] == ".":
                    seen_dot = True
                j += 1
            out.append(Token(Tok.NUMBER, sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            out.append(Token(Tok.IDENT, sql[i:j], i))
            i = j
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                elif sql[j] == "\\" and j + 1 < n:
                    esc = sql[j + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r"}.get(esc, esc))
                    j += 2
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise InvalidSyntaxError(f"unterminated string at {i}")
            out.append(Token(Tok.STRING, "".join(buf), i))
            i = j + 1
            continue
        if c in ('"', "`"):
            close = c
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == close and j + 1 < n and sql[j + 1] == close:
                    buf.append(close)  # doubled quote escapes itself
                    j += 2
                elif sql[j] == close:
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise InvalidSyntaxError(f"unterminated identifier at {i}")
            out.append(Token(Tok.QIDENT, "".join(buf), i))
            i = j + 1
            continue
        if sql[i:i + 2] in _TWO_CHAR_OPS:
            out.append(Token(Tok.OP, sql[i:i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            out.append(Token(Tok.OP, c, i))
            i += 1
            continue
        raise InvalidSyntaxError(f"unexpected character {c!r} at {i}")
    out.append(Token(Tok.EOF, "", n))
    return out
