"""Continuous aggregation (flow) engine.

Capability counterpart of the reference's flownode
(/root/reference/src/flow/: FlowWorkerManager adapter.rs:118, Hydroflow
render pipeline compute/render/reduce.rs, DiffRow deltas repr.rs:36-48),
restructured TPU-first:

- inserts into a flow's source table are mirrored to the flow
  (operator/src/insert.rs:284 mirror semantics) as columnar deltas;
- each flow keeps ACCUMULABLE per-group state (count/sum/min/max/... —
  ReducePlan::Accumulable analog) updated by a vectorized numpy/device
  segment reduction over the delta batch;
- a tick (run_available analog, adapter.rs:550) finalizes dirty groups and
  upserts them into the sink table through the normal write path — the
  storage engine's last-write-wins dedup makes writeback idempotent;
- EXPIRE AFTER drops state (and emission) for windows older than the
  horizon.
"""

from __future__ import annotations

import json
import logging

import time

import numpy as np

from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.errors import (
    FlowAlreadyExistsError,
    FlowNotFoundError,
    PlanError,
    UnsupportedError,
)
from greptimedb_tpu.query.executor import Col, DictSource
from greptimedb_tpu.query.expr import eval_expr
from greptimedb_tpu.query.planner import plan_select
from greptimedb_tpu.sql import ast as A
from greptimedb_tpu.sql.parser import parse_sql

from greptimedb_tpu import concurrency

_log = logging.getLogger("greptimedb_tpu.flow.manager")

FLOWS_PATH = "meta/flows.json"

_ACC_OPS = {"count", "count_distinct", "sum", "mean", "min", "max",
            "first_value", "last_value", "var_pop", "var_samp",
            "stddev_pop", "stddev_samp"}


class _GroupState:
    """Accumulable state for one group: per agg spec a small dict."""

    __slots__ = ("accs", "dirty")

    def __init__(self, n_aggs: int):
        self.accs = [None] * n_aggs
        self.dirty = True


class Flow:
    def __init__(self, name: str, stmt: A.CreateFlow, source_table: str,
                 db: str):
        self.name = name
        self.db = db
        self.stmt = stmt
        self.source_table = source_table
        self.sink_table = stmt.sink_table
        self.expire_after_s = stmt.expire_after_s
        self.comment = stmt.comment
        self.processed_rows = 0
        self.state: dict[tuple, _GroupState] = {}
        self.lock = concurrency.Lock()
        # serializes whole flushes: ADMIN flush_flow must not return
        # while a concurrent tick-flush still holds this flow's dirty
        # snapshot mid-emit (the sink would materialize only later)
        self.flush_lock = concurrency.Lock()
        self.plan = None          # lazily planned against the source schema
        self.device_state = None  # DeviceFlowState when the plan allows
        self.last_tick_ms = 0
        # restart recovery pending: state must re-derive from the source
        # before deltas may apply (deltas while set are ALSO in the
        # source, so the eventual backfill covers them)
        self.needs_backfill = False
        # a delta was skipped while a backfill scan was running: its row
        # may postdate the scan snapshot, so the backfill must re-run.
        # backfill_gate makes the skip-vs-clear handoff atomic without
        # blocking inserts behind the (long) scan itself.
        self.missed_during_backfill = False
        self.backfill_gate = concurrency.Lock()

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "db": self.db,
            "source_table": self.source_table,
            "sink_table": self.sink_table,
            "expire_after_s": self.expire_after_s,
            "comment": self.comment,
            "raw_sql": self.raw_sql,
        }


def _source_of(stmt: A.CreateFlow) -> str:
    q = stmt.query
    if not q.from_table:
        raise PlanError("flow query must read FROM a source table")
    return q.from_table.split(".")[-1]


class FlowManager:
    """Hosts all flows in-process (standalone's flownode role)."""

    def __init__(self, instance, *, tick_interval_s: float | None = None):
        import uuid

        self.instance = instance
        self.tick_interval_s = (
            1.0 if tick_interval_s is None else tick_interval_s
        )
        # process incarnation: frontends compare this to detect a
        # restart (state was re-derived from source; stale mirror
        # backlogs must be dropped, not replayed)
        self.epoch = uuid.uuid4().hex
        self._flows: dict[str, Flow] = {}
        self._by_source: dict[str, list[Flow]] = {}
        self._lock = concurrency.RLock()
        self._stop = concurrency.Event()
        self._load()
        # contract: the ticker is a manager-lifetime daemon; flow
        # window flushes it drives are their own root traces (the
        # request-attributed path is the inline flush on insert)
        self._ticker = concurrency.Thread(
            target=self._tick_loop,  # gtlint: disable=GT027
            daemon=True, name="flow-ticker",
        )
        self._ticker.start()

    # ------------------------------------------------------------------
    # DDL
    # ------------------------------------------------------------------
    def create_flow(self, stmt: A.CreateFlow, ctx) -> Flow:
        with self._lock:
            if stmt.name in self._flows:
                if stmt.if_not_exists:
                    return self._flows[stmt.name]
                raise FlowAlreadyExistsError(
                    f"flow already exists: {stmt.name}"
                )
            source = _source_of(stmt)
            db = getattr(ctx, "database", "public")
            # validate source exists + plan is an aggregate
            table = self.instance.catalog.table(db, source)
            flow = Flow(stmt.name, stmt, source, db)
            flow.raw_sql = _render_flow_sql(stmt)
            self._plan_flow(flow, table)
            self._flows[stmt.name] = flow
            self._by_source.setdefault(source, []).append(flow)
            self._persist()
            return flow

    def drop_flow(self, name: str, *, if_exists: bool = False):
        with self._lock:
            flow = self._flows.pop(name, None)
            if flow is None:
                if if_exists:
                    return
                raise FlowNotFoundError(f"flow not found: {name}")
            self._by_source.get(flow.source_table, []).remove(flow)
            self._persist()

    def flush_flow(self, name: str) -> bool:
        """Flush ONE flow's accumulated state into its sink (the
        reference's flush_flow admin function,
        /root/reference/src/common/function/src/flush_flow.rs)."""
        with self._lock:
            flow = self._flows.get(name)
        if flow is None:
            from greptimedb_tpu.errors import FlowNotFoundError

            raise FlowNotFoundError(f"flow not found: {name}")
        self._flush_flow(flow)
        return True

    def flow_names(self) -> list[str]:
        with self._lock:
            return sorted(self._flows)

    def maybe_flow(self, name: str) -> "Flow | None":
        with self._lock:
            return self._flows.get(name)

    def flow_sources(self) -> list[tuple[str, str]]:
        """(db, source_table) pairs that feed some flow — what a
        frontend needs to decide which inserts to mirror."""
        with self._lock:
            return sorted({
                (f.db, f.source_table) for f in self._flows.values()
            })

    def flow_infos(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "name": f.name,
                    "source_table": f.source_table,
                    "sink_table": f.sink_table,
                    "processed_rows": f.processed_rows,
                }
                for f in self._flows.values()
            ]

    def stop(self):
        self._stop.set()
        self._ticker.join(timeout=5)
        self.flush_all()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------
    def _persist(self):
        doc = [f.to_json() for f in self._flows.values()]
        self.instance.engine.store.write(
            FLOWS_PATH, json.dumps(doc).encode()
        )

    def _load(self):
        store = self.instance.engine.store
        if not store.exists(FLOWS_PATH):
            return
        for doc in json.loads(store.read(FLOWS_PATH)):
            try:
                stmts = parse_sql(doc["raw_sql"])
                stmt = stmts[0]
                flow = Flow(doc["name"], stmt, doc["source_table"],
                            doc.get("db", "public"))
                flow.raw_sql = doc["raw_sql"]
                table = self.instance.catalog.maybe_table(
                    flow.db, flow.source_table
                )
                # crash recovery: accumulated state died with the
                # process — re-derive it from the DURABLE source rows
                # (mirror backlogs covering these rows are dropped by
                # the frontend on epoch change). Source unreachable or
                # not yet visible => retry from the tick loop; deltas
                # are skipped until the backfill lands.
                flow.needs_backfill = True
                if table is not None:
                    self._plan_flow(flow, table)
                    try:
                        self._backfill(flow, table)
                        flow.needs_backfill = False
                    except Exception as e:  # noqa: BLE001
                        # needs_backfill stays set; the tick loop
                        # retries once the source is reachable
                        _log.info("backfill of flow %s deferred: %s",
                                  flow.name, e)
                self._flows[flow.name] = flow
                self._by_source.setdefault(
                    flow.source_table, []
                ).append(flow)
            except Exception:
                import traceback

                traceback.print_exc()

    def _backfill(self, flow: Flow, table):
        data = table.scan()
        rows = data.rows
        if rows is None or len(rows) == 0:
            return
        reg = data.registry
        cols: dict = {table.ts_name: rows.ts}
        for t in table.tag_names:
            cols[t] = reg.tag_values(t)[rows.sid]
        valid: dict = {}
        for f, arr in rows.fields.items():
            cols[f] = arr
            if rows.field_valid and f in rows.field_valid:
                valid[f] = rows.field_valid[f]
        self._apply_delta(flow, table, cols, valid)

    # ------------------------------------------------------------------
    # planning
    # ------------------------------------------------------------------
    def _plan_flow(self, flow: Flow, table):
        plan = plan_select(
            flow.stmt.query,
            ts_name=table.ts_name,
            tag_names=table.tag_names,
            all_columns=table.schema.column_names,
        )
        if plan.kind != "aggregate":
            raise UnsupportedError(
                "flows support aggregate queries (GROUP BY) only"
            )
        for a in plan.aggs:
            if a.op not in _ACC_OPS:
                raise UnsupportedError(
                    f"aggregate {a.op} is not accumulable in a flow"
                )
        # which key expr is the time window (date_bin/date_trunc on ts)?
        flow.time_key_idx = None
        for i, k in enumerate(plan.keys):
            if _is_time_bucket(k.expr, table.ts_name):
                flow.time_key_idx = i
                break
        flow.source_ts_name = table.ts_name
        # accumulators with a dense-array form keep their state on device
        # (flow/device_state.py); set/string state stays on the host path
        from greptimedb_tpu.flow import device_state as DS

        def _string_arg(a) -> bool:
            if a.arg is None or not isinstance(a.arg, A.Column):
                return False
            cs = table.schema.maybe_column(a.arg.name)
            return cs is not None and cs.data_type.is_string()

        flow.device_state = (
            DS.DeviceFlowState(plan, time_key_idx=flow.time_key_idx)
            if DS.plan_supports_device(plan)
            and not any(_string_arg(a) for a in plan.aggs)
            else None
        )
        # published LAST: _apply_delta's unlocked fast path keys off it
        flow.plan = plan

    # ------------------------------------------------------------------
    # ingest (mirrored inserts)
    # ------------------------------------------------------------------
    def on_insert(self, db: str, table_name: str, table, data: dict,
                  valid: dict):
        flows = self._by_source.get(table_name)
        if not flows:
            return
        for flow in flows:
            if flow.db != db:
                continue
            with flow.backfill_gate:
                if flow.needs_backfill:
                    # state not re-derived yet: this delta's rows are
                    # durable in the source, so the pending backfill
                    # covers them — applying now would double-count.
                    # Mark the skip (under the gate) so a backfill
                    # racing this delta re-runs: the row may postdate
                    # its scan snapshot.
                    flow.missed_during_backfill = True
                    continue
            try:
                self._apply_delta(flow, table, data, valid or {})
            except Exception:
                import traceback

                traceback.print_exc()

    def _apply_delta(self, flow: Flow, table, data: dict, valid: dict):
        from greptimedb_tpu.telemetry import tracing

        # joins the triggering insert's trace (directly in standalone,
        # via the mirrored traceparent on a flownode); tick-driven
        # backfills carry no trace and skip the span entirely
        with tracing.child_span("flow.eval", flow=flow.name):
            self._apply_delta_traced(flow, table, data, valid)

    def _apply_delta_traced(self, flow: Flow, table, data: dict,
                            valid: dict):
        if flow.plan is None:
            with flow.lock:
                # concurrent first inserts must not each build a plan +
                # device state (the loser's rows would be orphaned)
                if flow.plan is None:
                    self._plan_flow(flow, table)
        plan = flow.plan
        n = len(next(iter(data.values())))
        if n == 0:
            return
        cols = {}
        for k, v in data.items():
            vv = valid.get(k)
            cols[k] = Col(np.asarray(v),
                          None if vv is None or vv.all() else vv)
        src = DictSource(cols, n)

        mask = np.ones(n, bool)
        if plan.scan.residual is not None:
            cond = eval_expr(plan.scan.residual, src)
            mask &= cond.values.astype(bool) & cond.valid_mask
        # tag matchers from the WHERE clause apply to raw columns here
        for mname, op, value in plan.scan.matchers:
            c = cols.get(mname)
            if c is None:
                mask[:] = False
                break
            vals = c.values.astype(str)
            if op == "eq":
                mask &= vals == value
            elif op == "ne":
                mask &= vals != value
            elif op == "in":
                mask &= np.isin(vals, list(value))
            elif op == "nin":
                mask &= ~np.isin(vals, list(value))
            elif op in ("re", "nre"):
                hit = np.asarray(
                    [bool(value.fullmatch(s)) for s in vals]
                )
                mask &= hit if op == "re" else ~hit
        ts_col = cols.get(flow.source_ts_name)
        if ts_col is None:
            return
        ts = ts_col.values.astype(np.int64)
        if plan.scan.ts_min is not None:
            mask &= ts >= plan.scan.ts_min
        if plan.scan.ts_max is not None:
            mask &= ts <= plan.scan.ts_max
        if flow.expire_after_s is not None:
            horizon = int(time.time() * 1000) - flow.expire_after_s * 1000
            mask &= ts >= horizon
        if not mask.any():
            return

        key_vals = []
        for k in plan.keys:
            kv = eval_expr(k.expr, src)
            key_vals.append(kv.values)
        agg_args = []
        for a in plan.aggs:
            if a.arg is None:
                agg_args.append((None, None))
            else:
                c = eval_expr(a.arg, src)
                agg_args.append((c.values, c.validity))

        idxs = np.nonzero(mask)[0]
        ds = flow.device_state
        if ds is not None and len(idxs) and int(ts[idxs].min()) < 0:
            # device ts encoding assumes epoch >= 0
            self._demote_flow(flow)
            ds = None
        if ds is not None:
            key_cols = [np.asarray(kv, object)[idxs] for kv in key_vals]
            try:
                arg_sub = [
                    (None if vals is None
                     else np.asarray(vals[idxs], np.float64),
                     None if validity is None else validity[idxs])
                    for vals, validity in agg_args
                ]
            except (ValueError, TypeError):
                # non-numeric aggregate input: this flow is host-only
                self._demote_flow(flow)
            else:
                applied = False
                with flow.lock:
                    # a concurrent batch may have demoted the flow since
                    # ds was read; only apply if it is still live
                    if flow.device_state is ds:
                        gids = ds.intern_keys(key_cols, len(idxs))
                        ds.apply(gids, ts[idxs], arg_sub)
                        flow.processed_rows += len(idxs)
                        applied = True
                if applied:
                    return
        with flow.lock:
            flow.processed_rows += len(idxs)
            state = flow.state
            for i in idxs:
                key = tuple(
                    kv[i].item() if isinstance(kv[i], np.generic) else kv[i]
                    for kv in key_vals
                )
                gs = state.get(key)
                if gs is None:
                    gs = _GroupState(len(plan.aggs))
                    state[key] = gs
                gs.dirty = True
                for j, a in enumerate(plan.aggs):
                    vals, validity = agg_args[j]
                    v = None
                    if vals is not None:
                        if validity is not None and not validity[i]:
                            continue
                        v = float(vals[i]) if not isinstance(
                            vals[i], str
                        ) else vals[i]
                    gs.accs[j] = _accumulate(
                        a.op, gs.accs[j], v, int(ts[i])
                    )

    # ------------------------------------------------------------------
    # tick / writeback
    # ------------------------------------------------------------------
    def _tick_loop(self):
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.flush_all()
            except Exception:
                import traceback

                traceback.print_exc()

    def flush_all(self):
        with self._lock:
            flows = list(self._flows.values())
        for flow in flows:
            if flow.needs_backfill:
                # restart recovery: keep retrying the source re-derive
                # until the datanodes are reachable. State resets before
                # every attempt (a failed attempt may have half-applied
                # the scan), and the pass re-runs if a mirror delta was
                # skipped mid-scan — its row may postdate the snapshot.
                # NOT under flow.lock: _backfill -> _apply_delta takes
                # it internally (non-reentrant). Concurrent deltas are
                # excluded by the needs_backfill gate, and this tick
                # thread is the only backfill runner.
                try:
                    table = self.instance.catalog.maybe_table(
                        flow.db, flow.source_table
                    )
                    if table is None:
                        continue
                    if flow.plan is None:
                        with flow.lock:
                            if flow.plan is None:
                                self._plan_flow(flow, table)
                    clean = False
                    for _attempt in range(3):
                        flow.state = {}
                        flow.device_state = None
                        flow.missed_during_backfill = False
                        self._backfill(flow, table)
                        with flow.backfill_gate:
                            if not flow.missed_during_backfill:
                                # atomically open the delta gate: any
                                # delta that marked a miss did so under
                                # this gate and is visible here
                                flow.needs_backfill = False
                                clean = True
                        if clean:
                            break
                    # 3 missed passes (continuous ingest): keep the
                    # flag set — the freshly scanned state flushes
                    # below and the next tick rescans until a pass
                    # completes without a concurrent delta
                except Exception:
                    continue
            try:
                self._flush_flow(flow)
            except Exception:
                import traceback

                traceback.print_exc()

    def _demote_flow(self, flow: Flow):
        """Move a flow's device state back to host accumulators (input
        the device encoding can't represent: the flow keeps running on
        the host path with nothing lost)."""
        with flow.lock:
            ds = flow.device_state
            flow.device_state = None
            if ds is None or flow.plan is None:
                return
            rows, dirty = ds.export_host_accs()
            for gid, key in enumerate(ds.key_rows()):
                gs = flow.state.get(key)
                if gs is None:
                    gs = _GroupState(len(flow.plan.aggs))
                    flow.state[key] = gs
                gs.accs = rows[gid]
                gs.dirty = bool(dirty[gid]) or gs.dirty

    def _expire_horizon(self, flow: Flow):
        return int(time.time() * 1000) - flow.expire_after_s * 1000

    def _emit_groups(self, flow: Flow, key_rows, per_agg):
        """Finalized groups -> post-projection -> sink write. key_rows is
        a list of key tuples; per_agg a list of (values, present) arrays
        aligned with plan.aggs."""
        plan = flow.plan
        g = len(key_rows)
        out_cols: dict[str, Col] = {}
        for i, k in enumerate(plan.keys):
            vals = [key[i] for key in key_rows]
            arr = np.asarray(vals, object) if isinstance(
                vals[0], str
            ) else np.asarray(vals)
            out_cols[k.key] = Col(arr)
        for j, a in enumerate(plan.aggs):
            vals, present = per_agg[j]
            out_cols[a.key] = Col(
                vals, None if present.all() else present
            )
        gsrc = DictSource(out_cols, g)
        names = [nm for _, nm in plan.post_items]
        results = [eval_expr(e, gsrc) for e, _ in plan.post_items]
        self._write_sink(flow, names, results, out_cols)

    def _flush_flow(self, flow: Flow):
        if flow.plan is None:
            return
        # flush_lock exists to cover the whole flush INCLUDING the sink
        # write: ADMIN flush_flow must not return while a tick-flush
        # still holds this flow's dirty snapshot mid-emit. Only other
        # flushers of the SAME flow ever wait here; inserts take
        # flow.lock, which is released before the sink write
        # GTS103: the FIRST flush of a device flow jit-compiles its
        # kernel under this lock (single-flight); steady-state flushes
        # are milliseconds
        with flow.flush_lock:  # gtlint: disable=GTS102,GTS103
            self._flush_flow_locked(flow)

    def _flush_flow_locked(self, flow: Flow):
        ds = flow.device_state
        if ds is not None and self._flush_flow_device(flow, ds):
            return
        plan = flow.plan
        with flow.lock:
            dirty = [
                (key, gs) for key, gs in flow.state.items() if gs.dirty
            ]
            for _, gs in dirty:
                gs.dirty = False
            if flow.expire_after_s is not None and flow.time_key_idx is not None:
                horizon = self._expire_horizon(flow)
                expired = [
                    k for k in flow.state
                    if isinstance(k[flow.time_key_idx], (int, float))
                    and k[flow.time_key_idx] < horizon
                ]
                for k in expired:
                    del flow.state[k]
        if not dirty:
            return
        g = len(dirty)
        per_agg = []
        for j, a in enumerate(plan.aggs):
            vals = np.zeros(g)
            present = np.zeros(g, bool)
            for gi, (_, gs) in enumerate(dirty):
                out = _finalize(a.op, gs.accs[j])
                if out is not None:
                    vals[gi] = out
                    present[gi] = True
            per_agg.append((vals, present))
        try:
            self._emit_groups(flow, [key for key, _ in dirty], per_agg)
        except Exception:
            # keep the updates flushable: re-mark the groups dirty
            with flow.lock:
                for key, gs in dirty:
                    if key in flow.state:
                        gs.dirty = True
            raise

    def _flush_flow_device(self, flow: Flow, ds) -> bool:
        """Device-state tick: one finalize program over every group with
        a device-side dirty gather, then writeback of the dirty slice.
        Expiry compacts only after a successful write so the failure
        path's gids stay valid. Returns False (caller runs the host
        flush) if a concurrent batch demoted the flow."""
        with flow.lock:
            if flow.device_state is not ds:
                return False
            snap = ds.snapshot_dirty()
            dirty_gids = snap[2] if snap else np.zeros(0, np.int64)
            keys = [ds.key_rows()[i] for i in dirty_gids]
        if len(dirty_gids):
            # the state tuple in snap is immutable; the program + device
            # readback run here without stalling concurrent ingest
            _, per_agg = ds.finalize_snapshot(snap)
            try:
                self._emit_groups(
                    flow, keys,
                    [per_agg[j] for j in range(len(flow.plan.aggs))],
                )
            except Exception:
                with flow.lock:
                    if flow.device_state is ds:
                        ds.dirty[dirty_gids] = True
                    else:
                        # demoted mid-emit: re-dirty the host groups
                        for k in keys:
                            gs = flow.state.get(k)
                            if gs is not None:
                                gs.dirty = True
                raise
        if flow.expire_after_s is not None and \
                flow.time_key_idx is not None:
            with flow.lock:
                if flow.device_state is ds:
                    ds.expire_older_than(self._expire_horizon(flow))
        return True

    def _write_sink(self, flow: Flow, names, results, out_cols):
        plan = flow.plan
        sink = self.instance.catalog.maybe_table(flow.db, flow.sink_table)
        if sink is None:
            sink = self._create_sink(flow, names, results)
        ts_name = sink.ts_name
        n = len(results[0]) if results else 0
        tags = {}
        fields = {}
        fvalid = {}
        ts = None
        now_ms = int(time.time() * 1000)
        for nm, col in zip(names, results):
            cs = sink.schema.maybe_column(nm)
            if cs is None:
                continue
            if cs.is_time_index:
                ts = col.values.astype(np.int64)
            elif cs.is_tag:
                tags[nm] = np.asarray(
                    ["" if v is None else str(v) for v in col.values], object
                )
            else:
                fields[nm] = col.values
                if col.validity is not None:
                    fvalid[nm] = col.validity
        if ts is None:
            # placeholder time index (constant 0): writeback must UPSERT
            # per group key via last-write-wins dedup, never append — the
            # reference's __ts_placeholder semantics
            ts = np.zeros(n, np.int64)
        if "update_at" in sink.schema:
            fields["update_at"] = np.full(n, now_ms, np.int64)
        sink.write(tags, ts, fields, field_valid=fvalid or None)

    def _create_sink(self, flow: Flow, names, results):
        """Auto-create the sink table: time-bucket key -> TIME INDEX,
        string keys -> TAGs, aggregates -> FIELDs (the reference
        auto-creates sink tables on CREATE FLOW, flow/src/adapter.rs)."""
        plan = flow.plan
        cols = []
        have_ts = False
        key_outs = set()
        for i, k in enumerate(plan.keys):
            for (e, nm) in plan.post_items:
                if isinstance(e, A.Column) and e.name == k.key:
                    key_outs.add(nm)
                    if i == flow.time_key_idx and not have_ts:
                        cols.append(ColumnSchema(
                            nm, ConcreteDataType.timestamp_millisecond(),
                            SemanticType.TIMESTAMP, nullable=False,
                        ))
                        have_ts = True
                    else:
                        cols.append(ColumnSchema(
                            nm, ConcreteDataType.string(),
                            SemanticType.TAG,
                        ))
                    break
        for (e, nm), col in zip(plan.post_items, results):
            if nm in key_outs:
                continue
            dt = (ConcreteDataType.string()
                  if col.values.dtype == object
                  else ConcreteDataType.float64())
            cols.append(ColumnSchema(nm, dt, SemanticType.FIELD))
        if not have_ts:
            # non-windowed flow: constant-0 placeholder TIME INDEX makes
            # writeback an upsert; update_at (a FIELD) carries freshness
            cols.append(ColumnSchema(
                "update_at", ConcreteDataType.timestamp_millisecond(),
                SemanticType.FIELD,
            ))
            cols.append(ColumnSchema(
                "__ts_placeholder",
                ConcreteDataType.timestamp_millisecond(),
                SemanticType.TIMESTAMP, nullable=False,
            ))
        return self.instance.catalog.create_table(
            flow.db, flow.sink_table, Schema(cols), if_not_exists=True,
        )


# ----------------------------------------------------------------------
# accumulators (ReducePlan::Accumulable analogs)
# ----------------------------------------------------------------------

def _accumulate(op: str, acc, v, ts: int):
    if op == "count":
        return (acc or 0) + 1
    if op == "count_distinct":
        s = acc if acc is not None else set()
        s.add(v)
        return s
    if v is None:
        return acc
    if op == "sum":
        return (acc or 0.0) + v
    if op == "mean":
        s, n = acc if acc is not None else (0.0, 0)
        return (s + v, n + 1)
    if op == "min":
        return v if acc is None else min(acc, v)
    if op == "max":
        return v if acc is None else max(acc, v)
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        s, s2, n = acc if acc is not None else (0.0, 0.0, 0)
        return (s + v, s2 + v * v, n + 1)
    if op == "last_value":
        if acc is None or ts >= acc[1]:
            return (v, ts)
        return acc
    if op == "first_value":
        if acc is None or ts < acc[1]:
            return (v, ts)
        return acc
    raise UnsupportedError(op)


def _finalize(op: str, acc):
    if acc is None:
        return 0 if op in ("count", "count_distinct") else None
    if op == "count":
        return acc
    if op == "count_distinct":
        return len(acc)
    if op == "sum":
        return acc
    if op == "mean":
        s, n = acc
        return s / max(n, 1)
    if op in ("min", "max"):
        return acc
    if op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
        s, s2, n = acc
        ddof = 1 if op.endswith("_samp") else 0
        if n <= ddof:
            return None
        mean = s / n
        var = max(s2 / n - mean * mean, 0.0) * (n / (n - ddof))
        return var ** 0.5 if op.startswith("stddev") else var
    if op in ("first_value", "last_value"):
        return acc[0]
    raise UnsupportedError(op)


def _is_time_bucket(e: A.Expr, ts_name: str) -> bool:
    if isinstance(e, A.FuncCall) and e.name in ("date_bin", "date_trunc"):
        from greptimedb_tpu.query.expr import collect_columns

        return ts_name in collect_columns(e)
    if isinstance(e, A.Column) and e.name == ts_name:
        return True
    return False


def _render_flow_sql(stmt: A.CreateFlow) -> str:
    """Re-render CREATE FLOW for persistence/forwarding (the original
    text is not kept by the parser). IF NOT EXISTS renders only when
    the statement had it — a forwarded duplicate-name CREATE must still
    raise on the flownode."""
    ine = "IF NOT EXISTS " if stmt.if_not_exists else ""
    parts = [f"CREATE FLOW {ine}{stmt.name} SINK TO "
             f"{stmt.sink_table}"]
    if stmt.expire_after_s is not None:
        parts.append(f"EXPIRE AFTER '{stmt.expire_after_s}s'")
    if stmt.comment:
        parts.append(f"COMMENT '{stmt.comment}'")
    parts.append("AS " + _render_select(stmt.query))
    return " ".join(parts)


def _render_select(q: A.Select) -> str:
    from greptimedb_tpu.query.expr import format_expr

    items = ", ".join(
        format_expr(it.expr) + (f" AS {it.alias}" if it.alias else "")
        for it in q.items
    )
    out = f"SELECT {items}"
    if q.from_table:
        out += f" FROM {q.from_table}"
    if q.where is not None:
        out += f" WHERE {format_expr(q.where)}"
    if q.group_by:
        out += " GROUP BY " + ", ".join(format_expr(g) for g in q.group_by)
    if q.having is not None:
        out += f" HAVING {format_expr(q.having)}"
    return out
