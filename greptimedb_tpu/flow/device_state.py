"""Device-resident accumulable flow state.

Capability counterpart of the reference's Hydroflow accumulable reduce
(/root/reference/src/flow/src/compute/render/reduce.rs:43-60 reduce_
accum_subgraph: per-key accumulator state updated by diff batches), laid
out TPU-first: per-group accumulators live as dense device arrays
indexed by group id, a delta batch applies as ONE jit program (segment
reductions over the batch folded into the state arrays — no scatter:
untouched segments reduce to the op identity and fold as no-ops), and a
tick finalizes EVERY group in one program, gathering the dirty slice on
device before the readback.

Supported accumulators: count, sum, mean, min, max, var_pop/var_samp/
stddev_pop/stddev_samp (s, s2, n partials), first_value/last_value over
numeric fields. Flows using count_distinct or string-valued aggregates
stay on the host path (flow/manager.py) — set-valued and string state
have no dense-array form.

Numerics are f32-safe (no jax_enable_x64 requirement, matching TPU's
native dtype): counts and presence live in exact int32 slots, running
float sums (sum/mean/var partials) carry a Neumaier compensation slot so
magnitude-driven f32 absorption is corrected at every fold, and
first/last winners order by timestamps split into two int32 halves
(hi = ts >> 20, lo = ts & 0xfffff — exact for any non-negative epoch-ms
value; negative timestamps demote the flow to the host path). Equal-
timestamp ties resolve by arrival order within a batch (segment_min /
segment_max over the row iota) and by host accumulator semantics across
batches (first_value keeps the earlier batch on a tie, last_value takes
the later one).

Group ids are interned host-side (vocabulary dicts per key column, the
same dictionary-coding the series registry uses); state arrays grow by
power-of-two capacity with a device copy, and expired groups compact by
gathering live rows into fresh arrays. The EXPIRE AFTER check is a
vectorized compare over a parallel time-key array with an O(1) min
short-circuit, not a per-key Python scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ops the device path accumulates; everything else -> host fallback
DEVICE_OPS = frozenset({
    "count", "sum", "mean", "min", "max",
    "var_pop", "var_samp", "stddev_pop", "stddev_samp",
    "first_value", "last_value",
})

_IMAX = np.int32(2**31 - 1)

# state slots per op: name -> ((kind, identity), ...); kind "f" is the
# platform float dtype, "i" is exact int32. "c" slots are Neumaier
# compensation terms paired with the float sum before them.
_SLOTS = {
    "count": (("i", 0),),
    "sum": (("f", 0.0), ("f", 0.0), ("i", 0)),          # s, comp, n
    "mean": (("f", 0.0), ("f", 0.0), ("i", 0)),         # s, comp, n
    "min": (("f", np.inf), ("i", 0)),
    "max": (("f", -np.inf), ("i", 0)),
    # k (per-group shift), s, comp, s2, comp2, n — variance accumulates
    # sum(v-k) and sum((v-k)^2) around a shift k fixed at the group's
    # first batch, so E[x^2]-E[x]^2 cancellation happens on SMALL numbers
    # and stays accurate in f32 even when |v| >> stddev
    "var_pop": (("f", 0.0), ("f", 0.0), ("f", 0.0), ("f", 0.0),
                ("f", 0.0), ("i", 0)),
    "var_samp": (("f", 0.0), ("f", 0.0), ("f", 0.0), ("f", 0.0),
                 ("f", 0.0), ("i", 0)),
    "stddev_pop": (("f", 0.0), ("f", 0.0), ("f", 0.0), ("f", 0.0),
                   ("f", 0.0), ("i", 0)),
    "stddev_samp": (("f", 0.0), ("f", 0.0), ("f", 0.0), ("f", 0.0),
                    ("f", 0.0), ("i", 0)),
    # v, ts_hi, ts_lo; sentinel hi=IMAX (first) / -1 (last) means "empty"
    "first_value": (("f", 0.0), ("i", _IMAX), ("i", _IMAX)),
    "last_value": (("f", 0.0), ("i", -1), ("i", -1)),
}


def plan_supports_device(plan) -> bool:
    return all(a.op in DEVICE_OPS for a in plan.aggs)


def _float_dtype():
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


def _ts_split(ts: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Epoch-ms -> exact (hi, lo) int32 pair; requires ts >= 0."""
    ts64 = ts.astype(np.int64)
    return ((ts64 >> 20).astype(np.int32),
            (ts64 & 0xFFFFF).astype(np.int32))


def _kahan_fold(s, c, d):
    """Neumaier-compensated s += d: absorbs additions the raw float sum
    would round away, whichever of s and d is larger."""
    t = s + d
    c = c + jnp.where(jnp.abs(s) >= jnp.abs(d), (s - t) + d, (d - t) + s)
    return t, c


# NOTE: state is deliberately NOT donated — finalize snapshots the state
# tuple and runs outside the flow lock, so the buffers a concurrent
# apply() replaces must stay alive until that snapshot is consumed.
@functools.partial(jax.jit, static_argnames=("ops", "g"))
def _apply_program(state, gid, ts_hi, ts_lo, vals, has, *, ops: tuple,
                   g: int):
    """Fold one delta batch into the state arrays.

    state: tuple of (G,) arrays, one per slot of each agg.
    gid:   (N,) int32; ts_hi/ts_lo: (N,) int32 split timestamps.
    vals/has: per-agg (N,) value + validity arrays (stacked tuples).
    """
    out = list(state)
    si = 0

    def _nsum(ok):
        return jax.ops.segment_sum(ok.astype(jnp.int32), gid,
                                   num_segments=g)

    def _vsum(v, ok):
        return jax.ops.segment_sum(jnp.where(ok, v, 0), gid,
                                   num_segments=g)

    for j, op in enumerate(ops):
        v = vals[j]
        ok = has[j]
        if op == "count":
            out[si] = out[si] + _nsum(ok)
            si += 1
        elif op in ("sum", "mean"):
            out[si], out[si + 1] = _kahan_fold(
                out[si], out[si + 1], _vsum(v, ok)
            )
            out[si + 2] = out[si + 2] + _nsum(ok)
            si += 3
        elif op == "min":
            d = jax.ops.segment_min(
                jnp.where(ok, v, jnp.inf), gid, num_segments=g
            )
            out[si] = jnp.minimum(out[si], d)
            out[si + 1] = out[si + 1] + _nsum(ok)
            si += 2
        elif op == "max":
            d = jax.ops.segment_max(
                jnp.where(ok, v, -jnp.inf), gid, num_segments=g
            )
            out[si] = jnp.maximum(out[si], d)
            out[si + 1] = out[si + 1] + _nsum(ok)
            si += 2
        elif op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
            bn = _nsum(ok)
            n_old = out[si + 5]
            # pin the shift to (about) the group's first batch mean; any
            # constant near the data works, it only has to kill the
            # magnitude of the squared terms
            bmean = _vsum(v, ok) / jnp.maximum(bn, 1)
            k = jnp.where((n_old == 0) & (bn > 0), bmean, out[si])
            vk = v - k[gid]
            out[si] = k
            out[si + 1], out[si + 2] = _kahan_fold(
                out[si + 1], out[si + 2], _vsum(vk, ok)
            )
            out[si + 3], out[si + 4] = _kahan_fold(
                out[si + 3], out[si + 4], _vsum(vk * vk, ok)
            )
            out[si + 5] = n_old + bn
            si += 6
        elif op in ("first_value", "last_value"):
            last = op == "last_value"
            hi_fill = jnp.int32(-1) if last else _IMAX
            seg_ext = jax.ops.segment_max if last else jax.ops.segment_min
            # batch winner by (ts_hi, ts_lo, arrival) lexicographically,
            # one exact int32 segment reduction per component
            bh = seg_ext(jnp.where(ok, ts_hi, hi_fill), gid,
                         num_segments=g)
            c1 = ok & (ts_hi == bh[gid])
            bl = seg_ext(jnp.where(c1, ts_lo, hi_fill), gid,
                         num_segments=g)
            c2 = c1 & (ts_lo == bl[gid])
            iota = jnp.arange(ts_hi.shape[0], dtype=jnp.int32)
            widx = seg_ext(jnp.where(c2, iota, hi_fill), gid,
                           num_segments=g)
            hit = c2 & (iota == widx[gid])
            bv = jax.ops.segment_sum(
                jnp.where(hit, v, 0), gid, num_segments=g
            )
            has_cand = bh != hi_fill
            shi, slo = out[si + 1], out[si + 2]
            # cross-batch compare by timestamp with host semantics: a
            # later batch replaces at equal ts for last_value, keeps the
            # earlier arrival for first_value
            if last:
                take = has_cand & (
                    (bh > shi) | ((bh == shi) & (bl >= slo))
                )
            else:
                take = has_cand & (
                    (bh < shi) | ((bh == shi) & (bl < slo))
                )
            out[si] = jnp.where(take, bv, out[si])
            out[si + 1] = jnp.where(take, bh, shi)
            out[si + 2] = jnp.where(take, bl, slo)
            si += 3
        else:  # pragma: no cover - guarded by plan_supports_device
            raise ValueError(op)
    return tuple(out)


@functools.partial(jax.jit, static_argnames=("ops", "g"))
def _finalize_program(state, *, ops: tuple, g: int):
    """All-group finalize in one program: per-agg (values, presence)."""
    outs = []
    pres = []
    si = 0
    fdt = _float_dtype()
    for op in ops:
        if op == "count":
            n = state[si]
            outs.append(n)  # int32: exact, converted host-side
            pres.append(jnp.ones_like(n, bool))
            si += 1
        elif op in ("sum", "mean"):
            s = state[si] + state[si + 1]
            n = state[si + 2]
            ok = n > 0
            val = s / jnp.maximum(n, 1).astype(fdt) if op == "mean" else s
            outs.append(jnp.where(ok, val, 0))
            pres.append(ok)
            si += 3
        elif op in ("min", "max"):
            m, n = state[si], state[si + 1]
            ok = n > 0
            outs.append(jnp.where(ok, m, 0))
            pres.append(ok)
            si += 2
        elif op in ("var_pop", "var_samp", "stddev_pop", "stddev_samp"):
            s = state[si + 1] + state[si + 2]      # sum(v - k)
            s2 = state[si + 3] + state[si + 4]     # sum((v - k)^2)
            n = state[si + 5].astype(fdt)
            ddof = 1.0 if op.endswith("_samp") else 0.0
            ok = n > ddof
            n1 = jnp.maximum(n, 1)
            mean = s / n1                          # shift-invariant
            var = jnp.maximum(s2 / n1 - mean * mean, 0.0)
            var = var * (n1 / jnp.maximum(n1 - ddof, 1))
            out = jnp.sqrt(var) if op.startswith("stddev") else var
            outs.append(jnp.where(ok, out, 0))
            pres.append(ok)
            si += 6
        elif op in ("first_value", "last_value"):
            v, hi = state[si], state[si + 1]
            ok = hi != (jnp.int32(-1) if op == "last_value" else _IMAX)
            outs.append(jnp.where(ok, v, 0))
            pres.append(ok)
            si += 3
        else:  # pragma: no cover
            raise ValueError(op)
    return tuple(outs), tuple(pres)


_NEVER_EXPIRES = float("inf")


class DeviceFlowState:
    """Dense per-gid accumulators on device + host-side key interning."""

    def __init__(self, plan, time_key_idx: int | None = None):
        self.ops = tuple(a.op for a in plan.aggs)
        self.n_keys = len(plan.keys)
        self.time_key_idx = time_key_idx
        self._key_index: dict[tuple, int] = {}
        self._key_rows: list[tuple] = []
        self._col_dicts = None            # per-key-column Dictionary
        self._tk_vals: list[float] = []   # time-key per gid (inf: never)
        self._tk_min = _NEVER_EXPIRES
        self.capacity = 0
        self.state: tuple = ()
        self.dirty = np.zeros(0, bool)
        self.processed = 0

    # ---- group interning ----------------------------------------------
    def _append_key(self, kt: tuple) -> int:
        gid = len(self._key_rows)
        self._key_index[kt] = gid
        self._key_rows.append(kt)
        tk = self.time_key_idx
        v = _NEVER_EXPIRES
        if tk is not None and isinstance(kt[tk], (int, float)):
            v = float(kt[tk])
        self._tk_vals.append(v)
        if v < self._tk_min:
            self._tk_min = v
        return gid

    def intern_keys(self, key_cols: list[np.ndarray], n: int) -> np.ndarray:
        """Map n rows of key columns to dense gids (keyless: all gid 0).

        Per-column codes come from the incremental Dictionary interner
        (datatypes/batch.py: arrow hash-encode fast path, stable codes
        across batches), then unique composite code rows map to gids."""
        if not key_cols:
            if not self._key_rows:
                self._append_key(())
            return np.zeros(n, np.int32)
        if self._col_dicts is None:
            from greptimedb_tpu.datatypes.batch import Dictionary

            self._col_dicts = [Dictionary() for _ in key_cols]
        codes = [
            d.intern_array(_key_strings(c)).astype(np.int64)
            for d, c in zip(self._col_dicts, key_cols)
        ]
        key = codes[0]
        for d, c2 in zip(self._col_dicts[1:], codes[1:]):
            key = key * len(d) + c2
        _, first_rows, inv = np.unique(
            key, return_index=True, return_inverse=True
        )
        gids = np.empty(len(first_rows), np.int32)
        for i, row in enumerate(first_rows):
            kt = tuple(_scalar(col[row]) for col in key_cols)
            gid = self._key_index.get(kt)
            if gid is None:
                gid = self._append_key(kt)
            gids[i] = gid
        return gids[np.ravel(inv)]

    @property
    def num_groups(self) -> int:
        return len(self._key_rows)

    def key_rows(self) -> list[tuple]:
        return self._key_rows

    # ---- capacity ------------------------------------------------------
    def _slot_specs(self):
        out = []
        for op in self.ops:
            out.extend(_SLOTS[op])
        return out

    def _identity_array(self, kind, ident, cap: int):
        dt = _float_dtype() if kind == "f" else jnp.int32
        return jnp.full((cap,), ident, dt)

    def _ensure_capacity(self, g: int):
        if g <= self.capacity and self.state:
            return
        cap = max(self.capacity or 1024, 1024)
        while cap < g:
            cap *= 2
        new = []
        for i, (kind, ident) in enumerate(self._slot_specs()):
            arr = self._identity_array(kind, ident, cap)
            if self.state:
                arr = arr.at[: self.capacity].set(self.state[i])
            new.append(arr)
        self.state = tuple(new)
        nd = np.zeros(cap, bool)
        nd[: len(self.dirty)] = self.dirty
        self.dirty = nd
        self.capacity = cap

    # ---- delta application --------------------------------------------
    def apply(self, gids: np.ndarray, ts: np.ndarray,
              agg_args: list[tuple[np.ndarray | None, np.ndarray | None]]):
        """One device program folds this batch into the state."""
        n = len(gids)
        if n == 0:
            return
        if len(ts) and int(ts.min()) < 0:
            # the int32 ts split assumes epoch >= 0; manager falls back
            raise ValueError("negative timestamps: host path required")
        self._ensure_capacity(self.num_groups)
        hi, lo = _ts_split(ts)
        vals = []
        has = []
        for arr, validity in agg_args:
            if arr is None:
                vals.append(np.zeros(n, np.float32))
                has.append(np.ones(n, bool))
            else:
                vals.append(np.asarray(arr, np.float64))
                has.append(
                    np.ones(n, bool) if validity is None
                    else np.asarray(validity, bool)
                )
        # flow evals carry the same compile/execute/transfer
        # attribution (and device-program registry rows) as the query
        # path. The apply deliberately does NOT block_until_ready —
        # the delta fold overlaps host work, and the next apply's data
        # dependency orders it anyway — so the timing is flagged
        # dispatch_only and the profiler suppresses achieved-rate
        # claims for this program.
        from greptimedb_tpu.telemetry import device_trace

        d_gid = jnp.asarray(gids.astype(np.int32))
        d_hi = jnp.asarray(hi)
        d_lo = jnp.asarray(lo)
        d_vals = tuple(jnp.asarray(v) for v in vals)
        d_has = tuple(jnp.asarray(h) for h in has)
        upload = int(
            d_gid.nbytes + d_hi.nbytes + d_lo.nbytes
            + sum(int(v.nbytes) for v in d_vals)
            + sum(int(h.nbytes) for h in d_has)
        )
        with device_trace.device_call(
                "flow_apply",
                key=("flow_apply", self.ops, self.capacity),
                rows=n) as dcall:
            dcall.transfer(upload, "upload")
            self.state = dcall.run(
                _apply_program,
                self.state, d_gid, d_hi, d_lo, d_vals, d_has,
                ops=self.ops, g=self.capacity,
            )
            dcall.executed(dispatch_only=True)
        self.dirty[np.unique(gids)] = True
        self.processed += n

    # ---- finalize ------------------------------------------------------
    def snapshot_dirty(self):
        """Under the flow lock: snapshot (immutable state tuple, dirty
        gids) and clear the dirty bits. Returns None when clean."""
        g = self.num_groups
        if g == 0 or not self.dirty[:g].any():
            return None
        dirty = np.nonzero(self.dirty[:g])[0]
        self.dirty[:g] = False
        return (self.state, self.capacity, dirty)

    def finalize_snapshot(self, snap):
        """Outside the lock: one finalize program for every group; the
        dirty slice is gathered on device so only it crosses to the
        host. Returns (dirty_gids, {agg_idx: (values, present)})."""
        from greptimedb_tpu.telemetry import device_trace

        state, cap, dirty = snap
        with device_trace.device_call(
                "flow_finalize",
                key=("flow_finalize", self.ops, cap),
                groups=int(len(dirty))) as dcall:
            outs, pres = dcall.run(
                _finalize_program, state, ops=self.ops, g=cap
            )
            outs[0].block_until_ready()
            dcall.executed()
            didx = jnp.asarray(dirty.astype(np.int32))
            per_agg = {}
            nbytes = 0
            for j in range(len(self.ops)):
                v_d = jnp.take(outs[j], didx)
                p_d = jnp.take(pres[j], didx)
                # count the DEVICE arrays' bytes: the host copies widen
                # to float64, which would double the reported tunnel
                # traffic in the platform-float32 device mode
                nbytes += int(v_d.nbytes) + int(p_d.nbytes)
                per_agg[j] = (np.asarray(v_d, np.float64),
                              np.asarray(p_d, bool))
            dcall.transfer(nbytes)
        return dirty, per_agg

    # ---- demotion ------------------------------------------------------
    def export_host_accs(self):
        """Read back every group as host-accumulator tuples (the
        manager._accumulate format), so a flow can demote to the host
        path without losing accumulated state."""
        g = self.num_groups
        if g == 0 or not self.state:
            return [], np.zeros(0, bool)
        hs = [np.asarray(s) for s in self.state]
        rows = []
        for gid in range(g):
            accs = []
            si = 0
            for op in self.ops:
                if op == "count":
                    accs.append(int(hs[si][gid]))
                    si += 1
                elif op == "sum":
                    n = int(hs[si + 2][gid])
                    s = float(hs[si][gid]) + float(hs[si + 1][gid])
                    accs.append(s if n else None)
                    si += 3
                elif op == "mean":
                    n = int(hs[si + 2][gid])
                    s = float(hs[si][gid]) + float(hs[si + 1][gid])
                    accs.append((s, n) if n else None)
                    si += 3
                elif op in ("min", "max"):
                    n = int(hs[si + 1][gid])
                    accs.append(float(hs[si][gid]) if n else None)
                    si += 2
                elif op in ("var_pop", "var_samp", "stddev_pop",
                            "stddev_samp"):
                    n = int(hs[si + 5][gid])
                    k = float(hs[si][gid])
                    sk = float(hs[si + 1][gid]) + float(hs[si + 2][gid])
                    s2k = float(hs[si + 3][gid]) + float(hs[si + 4][gid])
                    # unshift to the host (raw s, s2) acc form in f64;
                    # precision is bounded by the f32 slots, fine for the
                    # rare demotion path
                    s = sk + n * k
                    s2 = s2k + 2 * k * sk + n * k * k
                    accs.append((s, s2, n) if n else None)
                    si += 6
                else:  # first_value / last_value
                    hi = int(hs[si + 1][gid])
                    lo = int(hs[si + 2][gid])
                    empty = hi == (-1 if op == "last_value" else int(_IMAX))
                    accs.append(
                        None if empty
                        else (float(hs[si][gid]), (hi << 20) | lo)
                    )
                    si += 3
            rows.append(accs)
        return rows, self.dirty[:g].copy()

    # ---- expiry --------------------------------------------------------
    def expire_older_than(self, horizon: float) -> bool:
        """Vectorized EXPIRE AFTER: drop groups whose time key is older
        than horizon. O(1) when nothing can expire. Groups still dirty
        (updated since the last emit) survive one more tick so their
        final state reaches the sink first."""
        if self._tk_min >= horizon or not self.num_groups:
            return False
        g = self.num_groups
        tk = np.asarray(self._tk_vals, np.float64)
        self.expire((tk >= horizon) | self.dirty[:g])
        return True

    def expire(self, keep_mask: np.ndarray):
        """Compact to the surviving groups (keep_mask over gids)."""
        g = self.num_groups
        keep = np.nonzero(keep_mask[:g])[0]
        if len(keep) == g:
            return
        rows = [self._key_rows[i] for i in keep]
        old_dirty = (self.dirty[keep] if len(self.dirty)
                     else np.zeros(0, bool))
        self._key_rows = rows
        self._key_index = {k: i for i, k in enumerate(rows)}
        self._tk_vals = [self._tk_vals[i] for i in keep]
        self._tk_min = min(self._tk_vals, default=_NEVER_EXPIRES)
        if not self.state:
            return
        idx = jnp.asarray(keep.astype(np.int32))
        gathered = tuple(jnp.take(s, idx) for s in self.state)
        cap = 1024
        while cap < len(rows):
            cap *= 2
        self.state = tuple(
            self._identity_array(kind, ident, cap)
            .at[: len(rows)].set(gv)
            for (kind, ident), gv in zip(self._slot_specs(), gathered)
        )
        nd = np.zeros(cap, bool)
        nd[: len(rows)] = old_dirty
        self.dirty = nd
        self.capacity = cap


def _scalar(v):
    return v.item() if isinstance(v, np.generic) else v


def _key_strings(c) -> np.ndarray:
    """Injective string form of a key column for interning. Numeric
    arrays stringify directly (homogeneous, so str() is injective);
    object arrays tag non-string values so NULL and the literal string
    "None" (etc.) stay distinct groups, matching the host path."""
    arr = np.asarray(c, object) if not isinstance(c, np.ndarray) else c
    if arr.dtype != object:
        return arr.astype(str)
    return np.asarray(
        [v if type(v) is str else f"\x00{type(v).__name__}:{v}"
         for v in arr],
        object,
    )
