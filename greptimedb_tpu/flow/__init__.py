from greptimedb_tpu.flow.manager import FlowManager

__all__ = ["FlowManager"]
