"""Bounded memo for compiled mesh programs.

Deliberately free of jax imports so the query modules can construct
their caches at import time (importing anything under parallel/ pulls in jax ~0.5s via the package __init__; the
program BUILDERS stay lazy behind ProgramCache.get).
"""

from __future__ import annotations

from greptimedb_tpu import concurrency


class ProgramCache:
    """FIFO-bounded get-or-build memo for compiled mesh programs. A
    process only ever holds a handful of live meshes, so eviction just
    drops the oldest compile; `build` receives the key verbatim. The
    lock covers the build so concurrent first queries share ONE program
    object (builders only wrap jax.jit — no I/O, no device work)."""

    def __init__(self, build, cap: int = 4):
        self._build = build
        self._cap = cap
        self._lock = concurrency.Lock()
        self._entries: dict = {}

    def get(self, key):
        with self._lock:
            prog = self._entries.get(key)
            if prog is None:
                prog = self._entries[key] = self._build(key)
                while len(self._entries) > self._cap:
                    self._entries.pop(next(iter(self._entries)))
            return prog
