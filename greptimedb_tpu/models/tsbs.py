"""TSBS benchmark query programs (the north-star workload, BASELINE.md).

These are the fused device pipelines the physical planner lowers recognized
query shapes onto. The reference executes the same queries through
DataFusion hash-aggregates on the datanode
(/root/reference/src/query/src/datafusion.rs); here each query is one XLA
program over (series, time) grids.

TSBS devops/cpu-only queries (docs/benchmarks/tsbs in the reference):
- double-groupby-N: mean of N cpu fields GROUP BY (hostname, hour) over 12h
- cpu-max-all-N: max of all 10 fields per hour for N hosts
- single-groupby-1-1-1: 1 field, 1 host, 5-minute buckets over 1h
- groupby-orderby-limit: max per 1-minute bucket, last 5 buckets
- high-cpu-N: rows where usage_user > 90 for N hosts
- lastpoint: latest row per host
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from greptimedb_tpu.ops import segment as S
from greptimedb_tpu.parallel.mesh import AXIS_SHARD, AXIS_TIME


@functools.partial(jax.jit, static_argnames=("cells_per_bucket",))
def groupby_time_mean(vals: jax.Array, has: jax.Array, cells_per_bucket: int):
    """mean per (series, time-bucket): (S, T) -> (S, T // cpb).

    The double-groupby kernel: with hostname already the series axis and
    hour = cells_per_bucket grid cells, GROUP BY (hostname, hour) is a
    reshape + masked mean — no hashing at all."""
    s, t = vals.shape
    nb = t // cells_per_bucket
    v = jnp.where(has, vals, 0).reshape(s, nb, cells_per_bucket)
    m = has.reshape(s, nb, cells_per_bucket)
    cnt = jnp.sum(m, axis=2)
    out = jnp.sum(v, axis=2) / jnp.maximum(cnt, 1).astype(vals.dtype)
    return out, cnt > 0


@functools.partial(jax.jit, static_argnames=("cells_per_bucket",))
def groupby_time_max(vals: jax.Array, has: jax.Array, cells_per_bucket: int):
    s, t = vals.shape
    nb = t // cells_per_bucket
    v = jnp.where(has, vals, -jnp.inf).reshape(s, nb, cells_per_bucket)
    m = has.reshape(s, nb, cells_per_bucket)
    present = jnp.any(m, axis=2)
    out = jnp.max(v, axis=2)
    return jnp.where(present, out, 0), present


@functools.partial(jax.jit, static_argnames=("cells_per_bucket",))
def double_groupby(fields: jax.Array, has: jax.Array, cells_per_bucket: int):
    """TSBS double-groupby-N: fields (F, S, T) -> (F, S, H) hourly means."""
    f, s, t = fields.shape
    nb = t // cells_per_bucket
    v = jnp.where(has[None], fields, 0).reshape(f, s, nb, cells_per_bucket)
    m = has.reshape(1, s, nb, cells_per_bucket)
    cnt = jnp.sum(m, axis=3)
    out = jnp.sum(v, axis=3) / jnp.maximum(cnt, 1).astype(fields.dtype)
    return out, (cnt > 0)[0]


@functools.partial(jax.jit, static_argnames=("threshold",))
def high_cpu_mask(gate_field: jax.Array, has: jax.Array, threshold: float):
    """high-cpu-N predicate: cells where the gate field exceeds threshold."""
    return has & (gate_field > jnp.asarray(threshold, gate_field.dtype))


@jax.jit
def lastpoint(vals: jax.Array, has: jax.Array, tsg: jax.Array):
    """Latest sample per series: (S,) values + ts + presence."""
    t = vals.shape[1]
    i = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), has.shape)
    li = jnp.max(jnp.where(has, i, -1), axis=1)
    present = li >= 0
    safe = jnp.maximum(li, 0)
    v = jnp.take_along_axis(vals, safe[:, None], axis=1)[:, 0]
    ts = jnp.take_along_axis(tsg, safe[:, None], axis=1)[:, 0]
    return v, ts, present


def build_distributed_query_step(
    mesh: Mesh, t_global: int, cells_per_bucket: int, k: int
):
    """The full multi-device query step used by __graft_entry__'s
    dryrun_multichip: grids sharded (series x time) over the mesh.

    Per device: partial (sum, count) per *global* time bucket via a one-hot
    matmul (rides the MXU) -> psum over the time axis (buckets crossing
    block boundaries recombine exactly) -> double-groupby means; then a
    global top-k over per-series totals: local top_k, all_gather over the
    series axis, re-select. All collectives ride ICI."""
    n_time = mesh.shape[AXIS_TIME]
    assert t_global % n_time == 0
    t_local = t_global // n_time
    nb = max(t_global // cells_per_bucket, 1)

    def local(fields, has):
        # fields: (F, S_local, T_local); has: (S_local, T_local)
        q = jax.lax.axis_index(AXIS_TIME)
        gidx = q * t_local + jnp.arange(t_local, dtype=jnp.int32)
        bucket = jnp.minimum(gidx // cells_per_bucket, nb - 1)
        onehot = jax.nn.one_hot(bucket, nb, dtype=fields.dtype)  # (T_l, NB)
        v = jnp.where(has[None], fields, 0)
        ps = jnp.einsum("fst,tb->fsb", v, onehot)
        pc = jnp.einsum("st,tb->sb", has.astype(fields.dtype), onehot)
        gs = jax.lax.psum(ps, AXIS_TIME)
        gc = jax.lax.psum(pc, AXIS_TIME)
        means = gs / jnp.maximum(gc, 1)[None]          # (F, S_l, NB)
        # per-series total across fields+buckets for the top-k stage
        series_score = jnp.sum(means, axis=(0, 2))
        n_local = series_score.shape[0]
        kk = min(k, n_local)
        loc_v, loc_i = jax.lax.top_k(series_score, kk)
        shard = jax.lax.axis_index(AXIS_SHARD)
        glob_i = loc_i + shard * n_local
        all_v = jax.lax.all_gather(loc_v, AXIS_SHARD).reshape(-1)
        all_i = jax.lax.all_gather(glob_i, AXIS_SHARD).reshape(-1)
        top_v, sel = jax.lax.top_k(all_v, kk)
        return means, top_v, all_i[sel]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, AXIS_SHARD, AXIS_TIME), P(AXIS_SHARD, AXIS_TIME)),
        out_specs=(P(None, AXIS_SHARD, None), P(), P()),
        check_rep=False,
    )
