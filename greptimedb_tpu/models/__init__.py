"""Flagship device programs ("models") — complete, jit-compiled query
pipelines used by benchmarks, __graft_entry__, and the physical planner as
fused fast paths for recognized query shapes."""
