"""Per-connection query context.

Counterpart of the reference's session layer
(/root/reference/src/session/src/context.rs QueryContext): current
catalog/schema, timezone, and channel; threaded through every statement.
"""

from __future__ import annotations

from dataclasses import dataclass, field


#: process-wide default timezone new sessions start in; the
#: `default_timezone` TOML knob sets it at role startup (cli.py), and
#: `SET time_zone` overrides it per session
_DEFAULT_TIMEZONE = "UTC"


def set_default_timezone(tz: str) -> None:
    """Apply the `default_timezone` config knob: new QueryContexts and
    the SHOW VARIABLES defaults report `tz` until a session overrides
    it."""
    global _DEFAULT_TIMEZONE
    tz = tz or "UTC"
    _DEFAULT_TIMEZONE = tz
    DEFAULT_VARIABLES["time_zone"] = tz
    DEFAULT_VARIABLES["system_time_zone"] = tz


def default_timezone() -> str:
    return _DEFAULT_TIMEZONE


@dataclass
class QueryContext:
    database: str = "public"
    timezone: str = field(
        default_factory=lambda: _DEFAULT_TIMEZONE)
    channel: str = "http"
    username: str = ""
    extensions: dict = field(default_factory=dict)
    # session variables set via SET; read back by SHOW VARIABLES and the
    # MySQL @@var probes (reference: src/session/src/context.rs
    # configuration_parameter + set handling in operator/statement/set.rs)
    variables: dict = field(default_factory=dict)


#: server-level defaults reported by SHOW VARIABLES when the session has
#: not overridden them (MySQL-compatible names clients probe on connect)
DEFAULT_VARIABLES = {
    "version": "8.4.2-greptimedb-tpu",
    "version_comment": "GreptimeDB-TPU",
    "sql_mode": "ANSI",
    "time_zone": "UTC",
    "system_time_zone": "UTC",
    "max_allowed_packet": "16777216",
    "max_execution_time": "0",
    "autocommit": "ON",
    "character_set_client": "utf8mb4",
    "character_set_results": "utf8mb4",
    "character_set_connection": "utf8mb4",
    "collation_connection": "utf8mb4_bin",
    "transaction_isolation": "REPEATABLE-READ",
    "wait_timeout": "28800",
    "interactive_timeout": "28800",
    "net_write_timeout": "60",
    "lower_case_table_names": "0",
    "datestyle": "ISO, MDY",
    "client_encoding": "UTF8",
    "read_timeout": "0",
}
