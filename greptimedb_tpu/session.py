"""Per-connection query context.

Counterpart of the reference's session layer
(/root/reference/src/session/src/context.rs QueryContext): current
catalog/schema, timezone, and channel; threaded through every statement.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class QueryContext:
    database: str = "public"
    timezone: str = "UTC"
    channel: str = "http"
    username: str = ""
    extensions: dict = field(default_factory=dict)
