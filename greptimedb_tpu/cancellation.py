"""Cooperative query cancellation.

Counterpart of the reference's process manager + tokio task abort on KILL
(/root/reference/src/catalog/src/process_manager.rs): a kill cannot abort
an XLA program mid-flight, so long-running statements poll `checkpoint()`
at stage boundaries (per-region scans, between batch statements) and
raise there.
"""

from __future__ import annotations

import contextvars

from greptimedb_tpu.sched import deadline as _deadline

_check: contextvars.ContextVar = contextvars.ContextVar(
    "gtpu_cancel_check", default=None
)


def set_check(fn):
    """Install a zero-arg callable that raises if the statement was
    killed. Returns a token for `reset`."""
    return _check.set(fn)


def reset(token):
    _check.reset(token)


def checkpoint():
    """Raise (via the installed callable) if the current statement has
    been killed, or (typed QueryDeadlineExceededError) if its deadline
    lapsed. No-op outside statement execution."""
    fn = _check.get()
    if fn is not None:
        fn()
    _deadline.check()
