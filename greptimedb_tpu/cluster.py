"""In-process distributed cluster: N datanodes + metasrv + frontend.

Capability counterpart of the reference's distributed deployment driven the
way tests-integration does it (/root/reference/tests-integration/src/
cluster.rs:69-306 builds a real multi-node cluster in one process: mock
metasrv, N real datanodes over a shared store, a frontend routing through
real clients). Here:

- every datanode owns a private WAL directory and a private engine, but all
  share one object store (the S3 analog) — so flushed data survives node
  loss and region migration moves ownership, not bytes;
- the frontend assembles `Table` objects whose regions live on different
  datanodes (region routes from the metasrv kv), so the whole query engine
  (SQL, PromQL, flows) runs unchanged against the cluster;
- heartbeats feed phi-accrual detectors; `Cluster.supervise()` fails over
  regions of dead nodes via the RegionMigration procedure.
"""

from __future__ import annotations

import os

import time

from greptimedb_tpu.catalog.manager import (
    TableInfo,
    region_options_from_table,
)
from greptimedb_tpu.catalog.table import Table
from greptimedb_tpu.datatypes.schema import Schema
from greptimedb_tpu.errors import (
    IllegalStateError,
    RegionNotFoundError,
    TableAlreadyExistsError,
    TableNotFoundError,
)
from greptimedb_tpu.meta.kv import FsKv, KvBackend, MemoryKv
from greptimedb_tpu.meta.metasrv import Metasrv, RegionMigrationProcedure
from greptimedb_tpu.storage.engine import EngineConfig, TsdbEngine
from greptimedb_tpu.storage.object_store import FsObjectStore
from greptimedb_tpu.storage.region import Region, RegionMetadata

from greptimedb_tpu import concurrency

TABLE_PREFIX = "__table/"


class Datanode:
    """One region host (reference: src/datanode RegionServer)."""

    def __init__(self, node_id: int, shared_store, data_root: str,
                 *, shared_wal_root: str | None = None):
        self.node_id = node_id
        self.store = shared_store
        self.engine = TsdbEngine(
            EngineConfig(data_root=data_root, enable_background=False,
                         wal_root=shared_wal_root),
            store=shared_store,
        )
        self.alive = True

    # region lifecycle -------------------------------------------------
    def open_region(self, meta: RegionMetadata, *, writable: bool = True
                    ) -> Region:
        region = self.engine.open_region(meta)
        region.writable = writable
        return region

    def close_region(self, region_id: int):
        self.engine.close_region(region_id)

    def region(self, region_id: int) -> Region:
        return self.engine.region(region_id)

    def has_region(self, region_id: int) -> bool:
        try:
            self.engine.region(region_id)
            return True
        except RegionNotFoundError:
            return False

    def region_stats(self) -> dict:
        out = {}
        for r in self.engine.regions():
            out[r.meta.region_id] = {
                "rows": r.memtable.rows
                + sum(m.rows for m in r.manifest.state.ssts),
                "memtable_bytes": r.memtable.bytes,
                "sst_count": len(r.manifest.state.ssts),
            }
        return out

    def crash(self):
        """Simulate a crash: stop heartbeating, refuse service."""
        self.alive = False

    def shutdown(self):
        self.engine.close()


class Cluster:
    """Frontend + metasrv + datanodes in one process."""

    def __init__(self, root: str, *, n_datanodes: int = 3,
                 selector: str = "round_robin", kv: KvBackend | None = None,
                 phi_threshold: float = 8.0, shared_wal: bool = False):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.kv = kv or FsKv(os.path.join(root, "meta", "kv.json"))
        self.shared_store = FsObjectStore(os.path.join(root, "object_store"))
        # shared_wal == the remote-WAL deployment shape: failover replays
        # the lost node's WAL, so unflushed writes survive
        self.shared_wal_root = (
            os.path.join(root, "shared_wal") if shared_wal else None
        )
        self.metasrv = Metasrv(self.kv, selector=selector,
                               phi_threshold=phi_threshold)
        self.metasrv.cluster = self
        self.datanodes: dict[int, Datanode] = {}
        self._tables: dict[tuple[str, str], Table] = {}
        self._next_table_id = 2048
        self._lock = concurrency.RLock()
        for i in range(n_datanodes):
            self.add_datanode(i)
        self._restore_tables()
        self.metasrv.procedures.register_loader(
            RegionMigrationProcedure.type_name, RegionMigrationProcedure
        )
        self.metasrv.procedures.recover(self.metasrv)

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------
    def add_datanode(self, node_id: int) -> Datanode:
        dn = Datanode(
            node_id, self.shared_store,
            os.path.join(self.root, f"dn{node_id}"),
            shared_wal_root=self.shared_wal_root,
        )
        self.datanodes[node_id] = dn
        # register only — the first real heartbeat seeds the phi detector
        # (a synthetic wall-clock sample here would poison test clocks)
        self.metasrv.register_node(node_id)
        return dn

    def heartbeat(self, node_id: int, now_ms: float | None = None):
        dn = self.datanodes[node_id]
        if not dn.alive:
            return []
        return self.metasrv.heartbeat(node_id, dn.region_stats(), now_ms)

    def heartbeat_all(self, now_ms: float | None = None):
        for nid in list(self.datanodes):
            self.heartbeat(nid, now_ms)

    def supervise(self, now_ms: float | None = None) -> list[str]:
        """One supervisor tick; returns migration procedure ids."""
        return self.metasrv.tick(now_ms)

    # ------------------------------------------------------------------
    # region ops used by migration procedures
    # ------------------------------------------------------------------
    def _region_meta(self, region_id: int) -> RegionMetadata:
        for (db, name), table in self._tables.items():
            if region_id in table.info.region_ids():
                opts = region_options_from_table(table.info.options)
                return RegionMetadata(
                    region_id=region_id,
                    table=table.info.name,
                    tag_names=[c.name for c in
                               table.info.schema.tag_columns],
                    field_names=[c.name for c in
                                 table.info.schema.field_columns],
                    ts_name=table.info.schema.time_index.name,
                    options=opts,
                )
        raise RegionNotFoundError(f"region {region_id} belongs to no table")

    def open_region_on(self, node_id: int, region_id: int, *,
                       writable: bool) -> None:
        dn = self.datanodes[node_id]
        if not dn.alive:
            raise IllegalStateError(f"datanode {node_id} is down")
        dn.open_region(self._region_meta(region_id), writable=writable)

    def downgrade_region_on(self, node_id: int, region_id: int, *,
                            failover: bool = False) -> None:
        dn = self.datanodes.get(node_id)
        if dn is None or not dn.alive or not dn.has_region(region_id):
            return  # dead leader: failover path
        region = dn.region(region_id)
        region.writable = False
        region.flush()

    def upgrade_region_on(self, node_id: int, region_id: int) -> None:
        dn = self.datanodes[node_id]
        if not dn.has_region(region_id):
            # crash-resume: the candidate open was in-memory only and a
            # restart lost it; re-opening from the shared store (+ WAL
            # replay) is exactly the open_candidate step re-done
            dn.open_region(self._region_meta(region_id), writable=True)
            return
        region = dn.region(region_id)
        # re-open to pick up SSTs flushed by the downgrade step
        meta = region.meta
        dn.close_region(region_id)
        dn.open_region(meta, writable=True)

    def close_region_on(self, node_id: int, region_id: int) -> None:
        dn = self.datanodes.get(node_id)
        if dn is None or not dn.alive:
            return
        if dn.has_region(region_id):
            dn.close_region(region_id)

    # ------------------------------------------------------------------
    # DDL + table access (frontend role)
    # ------------------------------------------------------------------
    def create_table(self, db: str, name: str, schema: Schema, *,
                     num_regions: int = 3, options: dict | None = None
                     ) -> Table:
        with self._lock:
            if (db, name) in self._tables:
                raise TableAlreadyExistsError(name)
            info = TableInfo(
                table_id=self._next_table_id, name=name, database=db,
                schema=schema, options=options or {},
                num_regions=num_regions,
                created_ms=int(time.time() * 1000),
            )
            self._next_table_id += 1
            region_ids = info.region_ids()
            routes = self.metasrv.allocate_regions(region_ids)
            opts = region_options_from_table(info.options)
            for rid in region_ids:
                meta = RegionMetadata(
                    region_id=rid, table=name,
                    tag_names=[c.name for c in schema.tag_columns],
                    field_names=[c.name for c in schema.field_columns],
                    ts_name=schema.time_index.name,
                    options=opts,
                )
                self.datanodes[routes[rid]].open_region(meta)
            self.kv.put_json(TABLE_PREFIX + f"{db}.{name}", info.to_json())
            table = self._assemble(info)
            self._tables[(db, name)] = table
            return table

    def drop_table(self, db: str, name: str):
        with self._lock:
            table = self._tables.pop((db, name), None)
            if table is None:
                raise TableNotFoundError(name)
            for rid in table.info.region_ids():
                nid = self.metasrv.route_of(rid)
                if nid is not None:
                    self.close_region_on(nid, rid)
            self.metasrv.remove_routes(table.info.region_ids())
            self.kv.delete(TABLE_PREFIX + f"{db}.{name}")

    def table(self, db: str, name: str) -> Table:
        with self._lock:
            table = self._tables.get((db, name))
            if table is None:
                raise TableNotFoundError(f"{db}.{name}")
            # routes may have moved (migration/failover): re-assemble
            return self._assemble(table.info)

    def _assemble(self, info: TableInfo) -> Table:
        regions = []
        for rid in info.region_ids():
            nid = self.metasrv.route_of(rid)
            if nid is None:
                raise RegionNotFoundError(f"region {rid} has no route")
            dn = self.datanodes.get(nid)
            if dn is None or not dn.alive:
                raise IllegalStateError(
                    f"region {rid} routed to dead datanode {nid}"
                )
            if not dn.has_region(rid):
                dn.open_region(self._region_meta_from_info(info, rid))
            regions.append(dn.region(rid))
        table = Table(info, regions)
        self._tables[(info.database, info.name)] = table
        return table

    def _region_meta_from_info(self, info: TableInfo, rid: int
                               ) -> RegionMetadata:
        return RegionMetadata(
            region_id=rid, table=info.name,
            tag_names=[c.name for c in info.schema.tag_columns],
            field_names=[c.name for c in info.schema.field_columns],
            ts_name=info.schema.time_index.name,
            options=region_options_from_table(info.options),
        )

    def _restore_tables(self):
        for key, raw in self.kv.range(TABLE_PREFIX):
            import json

            info = TableInfo.from_json(json.loads(raw))
            # advance the id BEFORE assembly: a failed assemble must not
            # let create_table reuse this table's id (region id collision)
            self._next_table_id = max(
                self._next_table_id, info.table_id + 1
            )
            try:
                self._tables[(info.database, info.name)] = (
                    self._assemble(info)
                )
            except Exception:
                import traceback

                traceback.print_exc()

    # ------------------------------------------------------------------
    def region_distribution(self) -> dict[int, list[int]]:
        """node_id -> region ids (information_schema.region_peers analog)."""
        out: dict[int, list[int]] = {nid: [] for nid in self.datanodes}
        for rid, nid in self.metasrv._all_routes().items():
            out.setdefault(nid, []).append(rid)
        return out

    def shutdown(self):
        for dn in self.datanodes.values():
            dn.shutdown()
