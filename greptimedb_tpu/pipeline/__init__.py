from greptimedb_tpu.pipeline.manager import PipelineManager
from greptimedb_tpu.pipeline.etl import Pipeline

__all__ = ["PipelineManager", "Pipeline"]
