"""Pipeline storage + log ingest.

Counterpart of /root/reference/src/pipeline/src/manager/: pipelines are
versioned documents persisted via the object store, looked up by name at
ingest time; ingested rows auto-create/widen the target log table (string
columns default to FIELDs; `index: tag` makes TAGs; `index: timestamp`
names the TIME INDEX).
"""

from __future__ import annotations

import json

import time

import numpy as np

from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.datatypes.types import ConcreteDataType
from greptimedb_tpu.errors import InvalidArgumentError
from greptimedb_tpu.pipeline.etl import IdentityPipeline, Pipeline

from greptimedb_tpu import concurrency

PIPELINES_PATH = "meta/pipelines.json"

_get_lock = concurrency.Lock()

class PipelineManager:
    @classmethod
    def get(cls, instance) -> "PipelineManager":
        """One manager per instance, attached to it (no global registry —
        the manager dies with the instance)."""
        mgr = getattr(instance, "_pipeline_manager", None)
        if mgr is None:
            with _get_lock:
                mgr = getattr(instance, "_pipeline_manager", None)
                if mgr is None:
                    mgr = cls(instance)
                    instance._pipeline_manager = mgr
        return mgr

    def __init__(self, instance):
        self.instance = instance
        self._pipelines: dict[str, Pipeline] = {}
        self._lock = concurrency.RLock()
        self._load()

    # ------------------------------------------------------------------
    def _load(self):
        store = self.instance.engine.store
        if not store.exists(PIPELINES_PATH):
            return
        for name, src in json.loads(store.read(PIPELINES_PATH)).items():
            try:
                self._pipelines[name] = Pipeline(src)
            except Exception:
                import traceback

                traceback.print_exc()

    def _persist(self):
        doc = {name: p.source for name, p in self._pipelines.items()}
        self.instance.engine.store.write(
            PIPELINES_PATH, json.dumps(doc).encode()
        )

    # ------------------------------------------------------------------
    def upsert_pipeline(self, name: str, source: str) -> Pipeline:
        p = Pipeline(source)  # validate
        with self._lock:
            self._pipelines[name] = p
            self._persist()
        return p

    def get_pipeline(self, name: str) -> Pipeline | None:
        if name == "greptime_identity":
            return IdentityPipeline()
        with self._lock:
            return self._pipelines.get(name)

    def delete_pipeline(self, name: str):
        with self._lock:
            self._pipelines.pop(name, None)
            self._persist()

    def pipeline_names(self) -> list[str]:
        with self._lock:
            return sorted(self._pipelines)

    # ------------------------------------------------------------------
    def ingest(self, db: str, table_name: str, pipeline_name: str,
               events: list[dict]) -> int:
        pipeline = self.get_pipeline(pipeline_name)
        if pipeline is None:
            raise InvalidArgumentError(
                f"pipeline not found: {pipeline_name}"
            )
        rows = pipeline.run(events)
        if not rows:
            return 0
        specs = pipeline.column_specs()
        return self._write_rows(db, table_name, rows, specs)

    def _write_rows(self, db: str, table_name: str, rows: list[dict],
                    specs: list[tuple[str, str, str | None]]) -> int:
        # infer columns: explicit specs first, else from the data
        if specs:
            ts_name = next(
                (n for n, t, idx in specs if idx == "timestamp"), None
            )
            tag_names = [n for n, t, idx in specs if idx == "tag"]
            col_types = {n: t for n, t, idx in specs}
        else:
            ts_name = "greptime_timestamp"
            tag_names = []
            col_types = {}
            for row in rows:
                for k, v in row.items():
                    if k in col_types or k == ts_name:
                        continue
                    if isinstance(v, bool):
                        col_types[k] = "bool"
                    elif isinstance(v, int):
                        col_types[k] = "int64"
                    elif isinstance(v, float):
                        col_types[k] = "float64"
                    else:
                        col_types[k] = "string"
            col_types[ts_name] = "timestamp_ms"
        if ts_name is None:
            raise InvalidArgumentError(
                "pipeline transform has no `index: timestamp` column"
            )

        from greptimedb_tpu.servers.influx import ensure_table

        field_types = {
            n: ConcreteDataType.from_name(t)
            for n, t in col_types.items()
            if n != ts_name and n not in tag_names
        }
        table = self.instance.catalog.maybe_table(db, table_name)
        if table is None:
            cols = [
                ColumnSchema(n, ConcreteDataType.string(),
                             SemanticType.TAG, nullable=False)
                for n in tag_names
            ]
            for n, t in field_types.items():
                cols.append(ColumnSchema(n, t, SemanticType.FIELD))
            cols.append(ColumnSchema(
                ts_name, ConcreteDataType.timestamp_millisecond(),
                SemanticType.TIMESTAMP, nullable=False,
            ))
            if not self.instance.catalog.has_database(db):
                self.instance.catalog.create_database(
                    db, if_not_exists=True
                )
            table = self.instance.catalog.create_table(
                db, table_name, Schema(cols), if_not_exists=True,
            )
        else:
            table = ensure_table(
                self.instance, db, table_name, tag_names, field_types,
            )

        n = len(rows)
        now_ms = int(time.time() * 1000)
        ts = np.asarray(
            [now_ms if row.get(ts_name) is None else row[ts_name]
             for row in rows],
            np.int64,
        )
        tags = {
            t: np.asarray(
                ["" if row.get(t) is None else str(row.get(t))
                 for row in rows], object,
            )
            for t in tag_names
        }
        fields = {}
        valid = {}
        for name, dt in field_types.items():
            vals = [row.get(name) for row in rows]
            validity = np.asarray([v is not None for v in vals], bool)
            if dt.is_string():
                arr = np.asarray(
                    ["" if v is None else str(v) for v in vals], object
                )
            else:
                arr = np.zeros(n, dt.to_numpy())
                for i, v in enumerate(vals):
                    if v is not None:
                        arr[i] = v
            fields[name] = arr
            if not validity.all():
                valid[name] = validity
        table.write(tags, ts, fields, field_valid=valid or None)
        data = {ts_name: ts, **tags, **fields}
        self.instance._notify_flows(db, table_name, table, data, valid)
        return n
