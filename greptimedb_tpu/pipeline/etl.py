"""Log ETL pipelines (YAML-defined).

Capability counterpart of /root/reference/src/pipeline/src/etl.rs (+
etl/processor/*.rs, etl/transform/): a YAML document declares an ordered
processor chain (dissect/regex/date/gsub/csv/...) over ingested JSON log
events, then a transform section types the resulting fields into table
columns (tag/field/time index).

Example:

    processors:
      - dissect:
          fields: [message]
          patterns: ['%{ip} - %{user} [%{ts}] "%{method} %{path}"']
      - date:
          fields: [ts]
          formats: ['%d/%b/%Y:%H:%M:%S']
    transform:
      - fields: [ip, method, path]
        type: string
        index: tag
      - fields: [user]
        type: string
      - fields: [ts]
        type: time
        index: timestamp
"""

from __future__ import annotations

import datetime as _dt
import json
import re
import time
import urllib.parse

import yaml

from greptimedb_tpu.errors import InvalidArgumentError


class PipelineError(InvalidArgumentError):
    pass


# ----------------------------------------------------------------------
# processors
# ----------------------------------------------------------------------

class Processor:
    def process(self, event: dict) -> dict | None:
        raise NotImplementedError


def _fields_of(cfg) -> list[str]:
    f = cfg.get("fields") or ([cfg["field"]] if "field" in cfg else [])
    if isinstance(f, str):
        f = [f]
    return f


class DissectProcessor(Processor):
    """'%{key} %{key2}' pattern splitting (dissect.rs analog — simplified:
    literal separators between %{...} captures)."""

    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.patterns = [
            self._compile(p) for p in cfg.get("patterns", [])
        ]
        self.ignore_missing = cfg.get("ignore_missing", False)

    @staticmethod
    def _compile(pattern: str) -> tuple[re.Pattern, list[str]]:
        keys = []
        rx = []
        pos = 0
        for m in re.finditer(r"%\{([^}]*)\}", pattern):
            rx.append(re.escape(pattern[pos:m.start()]))
            key = m.group(1)
            if key.startswith("?"):   # named skip
                rx.append(r".*?")
            elif key == "":
                rx.append(r".*?")
            else:
                keys.append(key)
                rx.append(f"(?P<{re.escape(key)}>.*?)")
            pos = m.end()
        rx.append(re.escape(pattern[pos:]))
        return re.compile("^" + "".join(rx) + "$"), keys

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if v is None:
                if self.ignore_missing:
                    continue
                raise PipelineError(f"dissect: missing field {f!r}")
            for rx, keys in self.patterns:
                m = rx.match(str(v))
                if m:
                    event.update(m.groupdict())
                    break
        return event


class RegexProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.patterns = [re.compile(p) for p in cfg.get("patterns", [])]
        self.ignore_missing = cfg.get("ignore_missing", False)

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if v is None:
                continue
            for rx in self.patterns:
                m = rx.search(str(v))
                if m:
                    for k, val in m.groupdict().items():
                        if val is not None:
                            event[f"{f}_{k}"] = val
                    break
        return event


class DateProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.formats = cfg.get("formats", [])
        self.timezone = cfg.get("timezone", "UTC")
        self.ignore_missing = cfg.get("ignore_missing", False)

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if v is None:
                if self.ignore_missing:
                    continue
                raise PipelineError(f"date: missing field {f!r}")
            event[f] = self._parse(str(v))
        return event

    def _tzinfo(self):
        if self.timezone in ("UTC", "utc", "", None):
            return _dt.timezone.utc
        try:
            from zoneinfo import ZoneInfo

            return ZoneInfo(self.timezone)
        except Exception:
            return _dt.timezone.utc

    def _parse(self, s: str) -> int:
        for fmt in self.formats:
            try:
                dt = _dt.datetime.strptime(s, fmt)
            except ValueError:
                continue
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=self._tzinfo())
            return int(dt.timestamp() * 1000)
        from greptimedb_tpu.query.expr import parse_ts_literal

        return parse_ts_literal(s)


class EpochProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.resolution = cfg.get("resolution", "ms")
        self.ignore_missing = cfg.get("ignore_missing", False)

    def process(self, event: dict) -> dict:
        scale = {"s": 1000.0, "sec": 1000.0, "second": 1000.0,
                 "ms": 1.0, "milli": 1.0, "millisecond": 1.0,
                 "us": 1e-3, "micro": 1e-3, "microsecond": 1e-3,
                 "ns": 1e-6, "nano": 1e-6, "nanosecond": 1e-6}[
            self.resolution
        ]
        for f in self.fields:
            v = event.get(f)
            if v is None:
                continue
            event[f] = int(float(v) * scale)
        return event


class GsubProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.pattern = re.compile(cfg["pattern"])
        self.replacement = cfg.get("replacement", "")

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if v is not None:
                event[f] = self.pattern.sub(self.replacement, str(v))
        return event


class LetterProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.method = cfg.get("method", "lower")

    def process(self, event: dict) -> dict:
        fn = {"lower": str.lower, "upper": str.upper,
              "capital": str.capitalize}[self.method]
        for f in self.fields:
            v = event.get(f)
            if v is not None:
                event[f] = fn(str(v))
        return event


class CsvProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.separator = cfg.get("separator", ",")
        self.target_fields = cfg.get("target_fields", [])
        if isinstance(self.target_fields, str):
            self.target_fields = [
                t.strip() for t in self.target_fields.split(",")
            ]

    def process(self, event: dict) -> dict:
        import csv as _csv
        import io

        for f in self.fields:
            v = event.get(f)
            if v is None:
                continue
            row = next(
                _csv.reader(io.StringIO(str(v)),
                            delimiter=self.separator),
                [],
            )
            for name, val in zip(self.target_fields, row):
                event[name] = val
        return event


class JoinProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.separator = cfg.get("separator", ",")

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if isinstance(v, list):
                event[f] = self.separator.join(str(x) for x in v)
        return event


class UrlEncodingProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.method = cfg.get("method", "decode")

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if v is None:
                continue
            if self.method == "decode":
                event[f] = urllib.parse.unquote(str(v))
            else:
                event[f] = urllib.parse.quote(str(v))
        return event


class JsonPathProcessor(Processor):
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.json_path = cfg.get("json_path", "$")

    def process(self, event: dict) -> dict:
        for f in self.fields:
            v = event.get(f)
            if v is None:
                continue
            try:
                doc = json.loads(v) if isinstance(v, str) else v
            except json.JSONDecodeError:
                continue
            path = [p for p in self.json_path.lstrip("$.").split(".") if p]
            for p in path:
                if isinstance(doc, dict):
                    doc = doc.get(p)
            event[f] = doc
        return event


_PROCESSORS = {
    "dissect": DissectProcessor,
    "regex": RegexProcessor,
    "date": DateProcessor,
    "epoch": EpochProcessor,
    "gsub": GsubProcessor,
    "letter": LetterProcessor,
    "csv": CsvProcessor,
    "join": JoinProcessor,
    "urlencoding": UrlEncodingProcessor,
    "json_path": JsonPathProcessor,
}


# ----------------------------------------------------------------------
# transforms (typing into columns)
# ----------------------------------------------------------------------

_TYPES = {
    "string": "string", "int8": "int8", "int16": "int16", "int32": "int32",
    "int64": "int64", "uint8": "uint8", "uint16": "uint16",
    "uint32": "uint32", "uint64": "uint64", "float32": "float32",
    "float64": "float64", "boolean": "bool", "bool": "bool",
    "time": "timestamp_ms", "timestamp": "timestamp_ms",
    "epoch": "timestamp_ms",
}


class TransformRule:
    def __init__(self, cfg: dict):
        self.fields = _fields_of(cfg)
        self.type = _TYPES.get(str(cfg.get("type", "string")).lower(),
                               "string")
        self.index = cfg.get("index")          # tag | timestamp | fulltext
        self.on_failure = cfg.get("on_failure", "ignore")

    def convert(self, v):
        if v is None:
            return None
        try:
            if self.type == "string":
                return str(v)
            if self.type == "bool":
                return bool(v)
            if self.type.startswith("timestamp"):
                return int(v)
            if self.type.startswith(("int", "uint")):
                return int(float(v))
            return float(v)
        except (TypeError, ValueError):
            if self.on_failure == "ignore":
                return None
            raise PipelineError(
                f"cannot convert {v!r} to {self.type}"
            ) from None


class Pipeline:
    def __init__(self, source: str):
        self.source = source
        doc = yaml.safe_load(source) or {}
        self.processors: list[Processor] = []
        for item in doc.get("processors", []) or []:
            (name, cfg), = item.items()
            cls = _PROCESSORS.get(name)
            if cls is None:
                raise PipelineError(f"unknown processor: {name}")
            self.processors.append(cls(cfg or {}))
        self.transforms = [
            TransformRule(t) for t in doc.get("transform", []) or []
        ]

    def run(self, events: list[dict]) -> list[dict]:
        """Apply processors; returns transformed typed rows."""
        out = []
        for raw in events:
            event = dict(raw)
            for p in self.processors:
                event = p.process(event)
                if event is None:
                    break
            if event is None:
                continue
            if self.transforms:
                row = {}
                for t in self.transforms:
                    for f in t.fields:
                        row[f] = t.convert(event.get(f))
                out.append(row)
            else:
                out.append(event)
        return out

    def column_specs(self) -> list[tuple[str, str, str | None]]:
        """(name, type, index) per output column; empty if identity."""
        specs = []
        for t in self.transforms:
            for f in t.fields:
                specs.append((f, t.type, t.index))
        return specs


class IdentityPipeline(Pipeline):
    """greptime_identity: JSON fields map 1:1 to columns, types inferred,
    a greptime_timestamp column is added (event.rs identity semantics)."""

    def __init__(self):
        self.source = "greptime_identity"
        self.processors = []
        self.transforms = []

    def run(self, events: list[dict]) -> list[dict]:
        now = int(time.time() * 1000)
        out = []
        for i, raw in enumerate(events):
            row = {}
            for k, v in raw.items():
                if isinstance(v, (dict, list)):
                    row[k] = json.dumps(v)
                else:
                    row[k] = v
            # distinct per-event timestamps: identical (series, ts) rows
            # would collapse under last-write-wins dedup
            row.setdefault("greptime_timestamp", now + i)
            out.append(row)
        return out
