"""Layered configuration (defaults < TOML file < env < CLI flags).

Capability counterpart of the reference's options system
(/root/reference/src/cmd/src/options.rs GreptimeOptions::load_layered_
options: serde defaults, `--config-file` TOML, `GREPTIMEDB_<ROLE>__`
double-underscore env keys, CLI overrides — last wins).

Every role process (standalone/frontend/datanode/metasrv/flownode)
resolves its options through `load_options`; values are kept as a
nested dict with dotted-path access so new sections need no schema
changes here.
"""

from __future__ import annotations

import os

ENV_PREFIX = "GREPTIMEDB_TPU"

# role-shared defaults; role sections are only consulted by their role
DEFAULTS: dict = {
    "data_home": "./greptimedb_tpu_data",
    "default_timezone": "UTC",
    "http": {
        "addr": "127.0.0.1:4000", "enable": True,
        "tls": {"cert_path": "", "key_path": ""},
    },
    # self-import node metrics into the TSDB every write_interval_s
    # (reference: src/servers/src/export_metrics.rs)
    "export_metrics": {
        "enable": False, "db": "greptime_metrics",
        "write_interval_s": 30.0,
    },
    # anonymous usage reporting (ref src/common/greptimedb-telemetry);
    # nothing is sent unless enable=true AND an endpoint is configured
    "telemetry": {"enable": False, "endpoint": "", "interval_s": 1800.0},
    # arrow flight; advertise_addr overrides the address peers dial
    # (bind-addr with the resolved port otherwise — port-0 binds and
    # wildcard hosts need it)
    "grpc": {"addr": "127.0.0.1:4001", "enable": True,
             "advertise_addr": ""},
    "mysql": {"addr": "127.0.0.1:4002", "enable": True},
    "postgres": {"addr": "127.0.0.1:4003", "enable": True},
    "opentsdb": {"enable": True},
    "influxdb": {"enable": True},
    "wal": {"sync": False, "backend": "fs", "topics": 4},
    "storage": {
        "type": "fs",            # fs | memory | s3
        # s3: bucket/endpoint/access_key_id/secret_access_key/region/root
        "cache_capacity_bytes": 0,
        # optional dedicated cold-tier store ([storage.cold], same
        # keys as [storage]): compaction rewrites windows past
        # [compaction] cold_horizon_ms onto it. Absent, cold files ride
        # the primary store BENEATH any local read cache.
        "cold": {},
    },
    "flow": {"enable": True, "tick_interval_s": 1.0},
    # pipelined wire-ingest dataplane (greptimedb_tpu/ingest/): the
    # frontend write path batches, coalesces, and streams region writes
    # to every datanode concurrently over long-lived Flight streams
    "ingest": {
        "pipeline": True,            # false = serial blocking DoPut
        "batch_max_rows": 262144,    # per coalesced wire batch group
        "coalesce_min_rows": 4096,   # group-commit target batch size
        "max_delay_ms": 4.0,         # max adaptive coalesce hold
        "queue_max_rows": 1048576,   # per-datanode backpressure bound
        "block_timeout_s": 2.0,      # blocked past this => 429 shed
        "max_inflight_groups": 2,    # double-buffered send/apply
        "ack_timeout_s": 60.0,       # unacked past this => overloaded
        "idle_stream_s": 60.0,       # close parked streams after this
    },
    # distributed query dataplane (dist/): datanode merged-scan cache,
    # intra-datanode region-scan parallelism, frontend fan-out pool
    "dist_query": {
        "scan_cache_bytes": 268435456,   # datanode LRU byte budget
        "region_scan_parallelism": 4,    # bounded pool per datanode
        "fanout_pool_size": 8,           # shared frontend fan-out pool
    },
    "engine": {
        "enable_background": True,
        "background_interval_s": 5.0,
    },
    # recovery & startup dataplane (storage/recovery.py): bounded
    # region-parallel open, pipelined SST restore with a readahead
    # window, manifest checkpoint cadence, and the post-replay flush
    # that truncates the WAL so the next restart replays nothing
    "recovery": {
        "open_parallelism": 0,          # 0 = min(8, regions in batch)
        "sst_prefetch_depth": 4,        # ranged gets in flight / region
        "checkpoint_interval_edits": 64,
        "flush_after_replay": True,
        "restore_ssts": False,          # eager fetch+verify+warm at open
    },
    # compaction + tiered-storage dataplane (storage/compaction.py):
    # leveled TWCS merges on a bounded per-engine pool with
    # device-accelerated merge, tombstone GC when a merge covers every
    # overlapping live file, and hot/cold tiering past the horizon.
    # The L0 trigger + window stay per-table (WITH(...) options).
    "compaction": {
        "workers": 1,                    # bounded merge pool size
        "l1_trigger_files": 4,           # L1 -> L2 file-count trigger
        "l1_trigger_bytes": 268435456,   # L1 -> L2 byte trigger (0=off)
        "l2_trigger_files": 4,           # L2 self-merge trigger
        "cold_horizon_ms": 0,            # rewrite older windows cold; 0=off
        "device_merge_min_rows": 262144, # device merge threshold; <=0 host
        "verify_device_merge": False,    # assert device == host per merge
        "prefetch_depth": 4,             # pipelined compaction-read window
        "cleanup_orphans": True,         # drop unreferenced SSTs at open
    },
    # query admission control + scheduling (sched/): per-tenant token
    # buckets and concurrency limits over a bounded priority queue,
    # queue-time SLOs, end-to-end deadlines, graceful degradation.
    # 0 = unlimited for every limit knob; the permissive defaults keep
    # the controller on the hot path without ever queueing or shedding
    "scheduler": {
        "enable": True,
        "max_concurrency": 0,        # global execution slots
        "queue_depth": 256,          # bounded wait queue (0 = unbounded)
        "queue_timeout_s": 10.0,     # queue-time SLO => 503 shed (0 = none)
        "default_deadline_s": 0.0,   # absolute per-query deadline
        "tenant_qps": 0.0,           # per-tenant token bucket rate
        "tenant_burst": 0.0,         # 0 => max(1, 2*qps)
        "tenant_concurrency": 0,     # per-tenant execution slots
        "allow_partial_results": False,  # degrade instead of fail
        # per-tenant overrides: [scheduler.tenants.<name>]
        # qps/burst/concurrency/priority (lower priority runs first)
        "tenants": {},
    },
    # adaptive control plane (autotune/): feedback controllers over the
    # observability surfaces move the runtime-mutable knobs through the
    # validated registry (ADMIN set_config rides the same path).
    # Off by default — enabling it hands the listed knobs to the
    # controllers; durability/correctness knobs are never registered.
    "autotune": {
        "enable": False,
        "tick_interval_s": 5.0,      # control-loop cadence
        "history": 256,              # decision audit-log ring size
        # shared guardrails (controllers.py Guardrails)
        "step": 0.25,                # max relative knob move per decision
        "band": 0.15,                # hysteresis dead-band
        "cooldown_ticks": 2,         # hold ticks after a decision
        # per-controller enables
        "admission": True,           # [scheduler] max_concurrency
        "planner": True,             # [mesh] shard_min_series/rows
        "hbm": True,                 # session/result/scan byte budgets
        "compaction": True,          # [compaction] workers/trigger
    },
    # multi-chip sharded query execution (parallel/mesh.py): one
    # process-wide mesh over the visible devices; large grids shard the
    # series axis across it and the shard_map reduction programs
    # recombine with explicit collectives. The replicate-vs-shard
    # thresholds feed query/planner.decide_mesh_execution.
    "mesh": {
        "enabled": False,
        "axis_size": 0,                 # shard-axis devices; 0 = all
        "time_parallel": 1,             # devices on the time axis
        "force_host_device_count": 0,   # CPU simulation (virtual devices)
        "shard_min_series": 4096,       # grids below this replicate
        "shard_min_rows": 262144,       # row reductions below this replicate
        # Pallas kernel paths (parallel/kernels): auto|on|off — auto
        # enables them on the native TPU backend only; on forces them
        # everywhere (interpret mode off-TPU); off keeps the XLA paths.
        "pallas_kernels": "auto",
        "pallas_min_series": 4096,      # kernel grid floor (stay XLA below)
        "pallas_min_rows": 262144,      # fused merge-gather row floor
        "pallas_max_k": 128,            # topk merge kernel O(k^2) cap
    },
    # secondary tag-index dataplane (index/): per-region inverted
    # tag-value -> sid postings over the dictionary-coded label plane,
    # version-validated, with a memoized per-matcher-set sid cache and
    # (device_plane) the label plane HBM-resident so matcher masks are
    # computed on device. enable=false falls every matcher back to the
    # full label-plane compare (the bit-identical oracle).
    "index": {
        "enable": True,
        "device_plane": True,
        "result_cache_entries": 256,   # per-index memoized matcher sets
        "rebuild_threshold": 4096,     # delta series before CSR rebuild
    },
    "frontend": {
        # flight addresses of the datanodes this frontend fans out to
        "datanode_addrs": [],
        # flight address of the flownode continuous-aggregation flows
        # run on ("" = run flows in-process on the frontend)
        "flownode_addr": "",
    },
    "metasrv": {
        "addr": "127.0.0.1:4010", "selector": "round_robin",
        # phi-accrual failure detection (meta/failure_detector.py):
        # threshold + acceptable heartbeat pause drive how fast a
        # silent node flips UNHEALTHY -> DOWN on the cluster surfaces
        "phi_threshold": 8.0,
        "acceptable_pause_ms": 10000.0,
    },
    "datanode": {"node_id": 0, "metasrv_addr": ""},
    # fleet observability plane (dist/fleet.py + telemetry/
    # node_stats.py): every role attaches a compact node-stats payload
    # to its metasrv heartbeat; the frontend serves cluster-wide
    # information_schema.cluster_* tables by fanning the bounded
    # node_telemetry Flight action to every peer, /v1/cluster/metrics
    # federates every node's metric families behind a TTL cache, and
    # /health?deep=1 + /v1/cluster/health run real readiness probes
    "fleet": {
        "enable": True,
        "stats_interval_s": 2.0,     # min spacing of heartbeat payloads
        "heartbeat_interval_s": 2.0,  # heartbeat loop cadence
        "history": 32,               # metasrv per-node sample ring size
        "fanout_timeout_s": 5.0,     # per-peer bound for cluster_* fan-out
        "cache_ttl_s": 5.0,          # federated-scrape cache TTL
    },
    # gtsan cooperative concurrency sanitizer (tools/san): off by
    # default — the concurrency facade hands out raw stdlib objects
    # and adds no per-operation cost. enable=true (or GTPU_SAN=1)
    # switches to instrumented locks/threads/pools
    "sanitizer": {
        "enable": False,
        "hold_time_ms": 1000.0,   # GTS103 lock hold-time threshold
        "fail_on_cycle": True,    # findings fail the run (vs report)
    },
    # end-to-end distributed tracing (telemetry/tracing.py): every
    # query/ingest batch produces one stitched trace across processes
    # (frontend sched/plan/fan-out + datanode scan + device
    # compile/execute/transfer spans under a shared trace_id), served
    # by /v1/traces + information_schema.traces. Sampling is
    # TAIL-BASED: slow (>= slow_ms), errored and shed statements are
    # ALWAYS kept; the rest keep with probability sample_ratio
    "tracing": {
        "enable": True,
        "sample_ratio": 1.0,    # head probability for unremarkable traces
        "capacity": 256,        # trace ring size (0 = unbounded; bench
                                # refuses to run like that)
        "slow_ms": 5000.0,      # always-keep threshold for slow traces
    },
    # query execution device preference (None = row-count heuristic);
    # true forces the grid/device fast paths — what the dist-process
    # tracing test uses to exercise device attribution on CPU jax
    "query": {"prefer_device": None},
    # persistent query sessions (query/sessions.py): folded device
    # RESULT buffers stay HBM-resident across polls, so a repeated
    # dashboard query skips the program dispatch round trip and delta
    # polls slice device-side. LRU byte budget over HBM.
    "sessions": {
        "enable": True,
        "hbm_bytes": 1073741824,
    },
    # frontend result-set cache (query/result_cache.py): completed
    # result payloads keyed on (statement fingerprint, physical
    # versions), served without touching datanode or device while
    # versions match. Off by default: turning it on makes REPEATED
    # identical statements answer from the frontend (dashboards want
    # this; debugging repeated-execution behavior does not).
    # validate_interval_ms > 0 bounds how often a dist frontend
    # re-validates versions against the datanodes (staleness bound);
    # 0 validates every poll (free locally, one cheap metadata action
    # per datanode for dist tables).
    "result_cache": {
        "enable": False,
        "bytes": 268435456,
        "validate_interval_ms": 0.0,
    },
    # unified memory observability (telemetry/memory.py): every
    # byte-budgeted pool (device grid/session caches, host scan/result/
    # page caches, trace ring, ingest queues) registers with one
    # process-wide accountant. device_budget_bytes > 0 adds a GLOBAL
    # HBM watermark below the sum of individual pool budgets, enforced
    # by demand-driven proportional eviction across the device pools;
    # census_on_scrape reconciles owner-tagged buffers against
    # jax.live_arrays() on every /metrics render so
    # gtpu_mem_unaccounted_device_bytes is an always-on leak detector
    "memory": {
        "enable": True,
        "device_budget_bytes": 0,   # 0 = per-pool budgets only
        "census_on_scrape": True,
    },
    # statement statistics (telemetry/stmt_stats.py): every executed
    # statement folds into a registry row keyed by its normalized
    # fingerprint (literals/IN-lists folded) — calls, errors, latency
    # percentiles, exec path, compile/cache hits, transfer bytes, shed
    # counts, last trace id. Surfaced as information_schema.
    # statement_statistics, /v1/stats/statements and gtpu_stmt_*
    # metrics. max_fingerprints bounds the registry (LRU rows collapse
    # into "_other"); metric_fingerprints bounds the /metrics label
    # cardinality (first-come, later fingerprints export as "_other").
    # Reset at runtime with ADMIN reset_statement_statistics().
    "stmt_stats": {
        "enable": True,
        "max_fingerprints": 512,
        "metric_fingerprints": 64,
    },
    # device program profiler (telemetry/device_programs.py): every
    # jit/shard_map program dispatched through a device_call registers
    # one row — calls, compile_ms, execute p50/p99, transfer bytes,
    # XLA cost_analysis flops / bytes accessed, memory_analysis
    # temp/output bytes, and a roofline verdict (bound=compute|memory,
    # %-of-peak) against the hardware peaks. Surfaced as
    # information_schema.device_programs, /debug/prof/device and
    # gtpu_device_program_* metrics; reset with ADMIN
    # reset_device_profiler(). peak_tflops / peak_hbm_gbps at 0 mean
    # auto: TPU backends default to v5e single-chip numbers (197
    # TFLOP/s bf16, 819 GB/s HBM); CPU runs report achieved-only.
    # analysis=false skips the lazy XLA cost/memory analysis (rows
    # keep per-call stats only). trace_dir is where
    # /debug/prof/device/trace?seconds= writes its TensorBoard/
    # perfetto-loadable captures ("" = the system temp dir).
    # metric_programs bounds the /metrics label cardinality (first-
    # come, like stmt_stats' metric_fingerprints — exported series can
    # never be evicted, so programs past the cap export under
    # program="_other").
    "profiling": {
        "enable": True,
        "max_programs": 256,
        "metric_programs": 128,
        "peak_tflops": 0.0,
        "peak_hbm_gbps": 0.0,
        "analysis": True,
        "trace_dir": "",
    },
    "logging": {
        "level": "info",
        # statements slower than threshold land in the slow-query log +
        # information_schema.slow_queries (ref [logging.slow_query])
        "slow_query": {
            "enable": True, "threshold_s": 5.0, "sample_ratio": 1.0,
        },
    },
}


class Options:
    """Nested options with dotted-path access: opts.get('http.addr')."""

    def __init__(self, values: dict):
        self.values = values

    def get(self, path: str, default=None):
        cur = self.values
        for part in path.split("."):
            if not isinstance(cur, dict) or part not in cur:
                return default
            cur = cur[part]
        return cur

    def section(self, name: str) -> dict:
        v = self.get(name, {})
        return v if isinstance(v, dict) else {}

    def set(self, path: str, value):
        cur = self.values
        parts = path.split(".")
        for part in parts[:-1]:
            cur = cur.setdefault(part, {})
        cur[parts[-1]] = value


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _parse_scalar(raw: str):
    """Env values parse like TOML scalars; unparseable stays a string."""
    low = raw.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        return [
            _parse_scalar(x.strip().strip("'\""))
            for x in inner.split(",")
        ]
    return raw


def _env_overrides(env, prefixes: list[str]) -> dict:
    out: dict = {}
    for key, raw in env.items():
        for pfx in prefixes:
            if not key.startswith(pfx + "__"):
                continue
            path = key[len(pfx) + 2:].lower().split("__")
            cur = out
            for part in path[:-1]:
                cur = cur.setdefault(part, {})
            cur[path[-1]] = _parse_scalar(raw)
            break
    return out


def load_options(
    role: str = "standalone",
    config_file: str | None = None,
    env: dict | None = None,
    cli_overrides: dict | None = None,
) -> Options:
    """Resolve options for a role: defaults < TOML < env < CLI.

    env keys: GREPTIMEDB_TPU__SECTION__KEY (or the role-scoped
    GREPTIMEDB_TPU_<ROLE>__SECTION__KEY, which wins over the generic
    prefix). cli_overrides maps dotted paths to values; None values are
    skipped so unset flags never mask lower layers.
    """
    import copy

    # deep copy: Options.set writes into nested dicts, which must never
    # reach back into the shared module-level DEFAULTS
    values = copy.deepcopy(DEFAULTS)
    if config_file:
        try:
            import tomllib  # 3.11+
        except ModuleNotFoundError:  # 3.10: same API, external name
            import tomli as tomllib

        with open(config_file, "rb") as f:
            values = _deep_merge(values, tomllib.load(f))
    env = dict(os.environ if env is None else env)
    for prefixes in (
        [ENV_PREFIX],
        [f"{ENV_PREFIX}_{role.upper()}"],
    ):
        ov = _env_overrides(env, prefixes)
        if ov:
            values = _deep_merge(values, ov)
    opts = Options(values)
    for path, value in (cli_overrides or {}).items():
        if value is not None:
            opts.set(path, value)
    return opts
