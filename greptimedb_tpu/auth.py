"""Pluggable user authentication.

Capability counterpart of /root/reference/src/auth/ (UserProvider trait,
user_provider.rs:36, with static and watch-file providers): the HTTP server
consults a provider for Basic-auth credentials when one is configured.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os

from greptimedb_tpu.errors import GreptimeError

from greptimedb_tpu import concurrency

class AccessDeniedError(GreptimeError):
    pass


class UserProvider:
    def authenticate(self, username: str, password: str) -> bool:
        raise NotImplementedError

    def plain_password(self, username: str) -> str | None:
        """Plaintext password for challenge-response handshakes
        (mysql_native_password). Providers that only store hashes return
        None; such users can authenticate only over password-carrying
        protocols (HTTP Basic)."""
        return None


class StaticUserProvider(UserProvider):
    """`user=pwd` pairs, the static_user_provider analog. Values may be
    plain or `sha256:<hex>`."""

    def __init__(self, users: dict[str, str]):
        self._users = dict(users)

    @staticmethod
    def from_option(opt: str) -> "StaticUserProvider":
        """'user1=pwd1,user2=pwd2'"""
        users = {}
        for pair in opt.split(","):
            if not pair.strip():
                continue
            k, _, v = pair.partition("=")
            users[k.strip()] = v.strip()
        return StaticUserProvider(users)

    def authenticate(self, username: str, password: str) -> bool:
        want = self._users.get(username)
        if want is None:
            return False
        if want.startswith("sha256:"):
            return hmac.compare_digest(
                hashlib.sha256(password.encode()).hexdigest().encode(),
                want[len("sha256:"):].encode(),
            )
        return hmac.compare_digest(password.encode(), want.encode())

    def plain_password(self, username: str) -> str | None:
        """Plaintext password when stored plain — required by challenge
        handshakes (mysql_native_password); sha256-stored users can only
        authenticate over protocols that send the password (HTTP Basic)."""
        want = self._users.get(username)
        if want is None or want.startswith("sha256:"):
            return None
        return want


class WatchFileUserProvider(UserProvider):
    """Reloads `user=pwd` lines from a file when its mtime changes
    (watch_file_user_provider analog)."""

    def __init__(self, path: str):
        self.path = path
        self._mtime = 0.0
        self._inner = StaticUserProvider({})
        self._lock = concurrency.Lock()
        self._maybe_reload()

    def _maybe_reload(self):
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            return
        with self._lock:
            if mtime == self._mtime:
                return
            users = {}
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        k, _, v = line.partition("=")
                        users[k.strip()] = v.strip()
            self._inner = StaticUserProvider(users)
            self._mtime = mtime

    def authenticate(self, username: str, password: str) -> bool:
        self._maybe_reload()
        return self._inner.authenticate(username, password)

    def plain_password(self, username: str) -> str | None:
        self._maybe_reload()
        return self._inner.plain_password(username)


def check_basic_auth(header: str | None, provider: UserProvider | None
                     ) -> str | None:
    """Returns the authenticated username (or None when no provider is
    configured); raises AccessDeniedError on bad credentials."""
    if provider is None:
        return None
    if not header or not header.startswith("Basic "):
        raise AccessDeniedError("missing Authorization header")
    try:
        raw = base64.b64decode(header[len("Basic "):]).decode()
        user, _, pwd = raw.partition(":")
    except Exception:
        raise AccessDeniedError("malformed Authorization header") from None
    if not provider.authenticate(user, pwd):
        raise AccessDeniedError(f"invalid credentials for {user!r}")
    return user
