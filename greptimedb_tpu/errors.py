"""Error taxonomy.

Mirrors the reference's `ErrorExt`/`StatusCode` scheme
(/root/reference/src/common/error/src/status_code.rs) with a flat Python
exception hierarchy carrying a wire status code.
"""

from __future__ import annotations

import enum


class StatusCode(enum.IntEnum):
    SUCCESS = 0
    UNKNOWN = 1000
    UNSUPPORTED = 1001
    UNEXPECTED = 1002
    INTERNAL = 1003
    INVALID_ARGUMENTS = 1004
    CANCELLED = 1005
    ILLEGAL_STATE = 1006

    INVALID_SYNTAX = 2000
    PLAN_QUERY = 3000
    ENGINE_EXECUTE_QUERY = 3001

    TABLE_ALREADY_EXISTS = 4000
    TABLE_NOT_FOUND = 4001
    TABLE_COLUMN_NOT_FOUND = 4002
    TABLE_COLUMN_EXISTS = 4003
    DATABASE_NOT_FOUND = 4004
    REGION_NOT_FOUND = 4005
    REGION_ALREADY_EXISTS = 4006
    REGION_READONLY = 4007
    DATABASE_ALREADY_EXISTS = 4010

    STORAGE_UNAVAILABLE = 5000
    REQUEST_OUTDATED = 5001

    RUNTIME_RESOURCES_EXHAUSTED = 6000
    RATE_LIMITED = 6001
    QUERY_OVERLOADED = 6002
    QUERY_QUEUE_TIMEOUT = 6003
    DEADLINE_EXCEEDED = 6004

    USER_NOT_FOUND = 7000
    UNSUPPORTED_PASSWORD_TYPE = 7001
    USER_PASSWORD_MISMATCH = 7002
    AUTH_HEADER_NOT_FOUND = 7003
    INVALID_AUTH_HEADER = 7004
    ACCESS_DENIED = 7005
    PERMISSION_DENIED = 7006

    FLOW_ALREADY_EXISTS = 8000
    FLOW_NOT_FOUND = 8001


class GreptimeError(Exception):
    """Base error; every subsystem raises a subclass."""

    status_code: StatusCode = StatusCode.INTERNAL

    def __init__(self, msg: str = "", *, code: StatusCode | None = None):
        super().__init__(msg)
        if code is not None:
            self.status_code = code

    @property
    def message(self) -> str:
        return str(self)


class InvalidSyntaxError(GreptimeError):
    status_code = StatusCode.INVALID_SYNTAX


class PlanError(GreptimeError):
    status_code = StatusCode.PLAN_QUERY


class ExecutionError(GreptimeError):
    status_code = StatusCode.ENGINE_EXECUTE_QUERY


class UnsupportedError(GreptimeError):
    status_code = StatusCode.UNSUPPORTED


class InvalidArgumentError(GreptimeError):
    status_code = StatusCode.INVALID_ARGUMENTS


class TableNotFoundError(GreptimeError):
    status_code = StatusCode.TABLE_NOT_FOUND


class TableAlreadyExistsError(GreptimeError):
    status_code = StatusCode.TABLE_ALREADY_EXISTS


class ColumnNotFoundError(GreptimeError):
    status_code = StatusCode.TABLE_COLUMN_NOT_FOUND


class DatabaseNotFoundError(GreptimeError):
    status_code = StatusCode.DATABASE_NOT_FOUND


class DatabaseAlreadyExistsError(GreptimeError):
    status_code = StatusCode.DATABASE_ALREADY_EXISTS


class RegionNotFoundError(GreptimeError):
    status_code = StatusCode.REGION_NOT_FOUND


class RegionReadonlyError(GreptimeError):
    status_code = StatusCode.REGION_READONLY


class StorageError(GreptimeError):
    status_code = StatusCode.STORAGE_UNAVAILABLE


class SstRestoreError(StorageError):
    """An SST object failed verification during recovery restore: the
    ranged get returned fewer bytes than the manifest entry records
    (torn/partial object), the object is missing, or the Parquet
    payload is corrupt. Carries the offending file path so operators
    see WHICH object to repair instead of a decode traceback."""


class CompactionError(StorageError):
    """A compaction job failed: a picked input could not be fetched/
    verified, the device merge diverged from the host path under
    verification, or the output commit lost its race irrecoverably.
    Carries the region id and failing stage so ADMIN callers (and the
    wire, via [gtdb:<code>]) see what to retry."""


class DatanodeUnavailableError(GreptimeError):
    """A datanode process is unreachable (connection refused/timeout) —
    retryable after a route refresh (failover may have moved its
    regions)."""

    status_code = StatusCode.STORAGE_UNAVAILABLE


class FlowNotFoundError(GreptimeError):
    status_code = StatusCode.FLOW_NOT_FOUND


class FlowAlreadyExistsError(GreptimeError):
    status_code = StatusCode.FLOW_ALREADY_EXISTS


class IllegalStateError(GreptimeError):
    status_code = StatusCode.ILLEGAL_STATE


class OverloadedError(GreptimeError):
    """Base of the typed overload surface: the node is shedding load
    instead of queueing without bound. Every protocol maps these to a
    back-off signal (HTTP 429/503, `[gtdb:<code>]` over Flight/MySQL/
    Postgres) — never a hang."""

    status_code = StatusCode.RATE_LIMITED


class IngestOverloadedError(OverloadedError):
    """The ingest dataplane's bounded queues stayed full past the
    block timeout: a datanode is slow or stalled and the accepting
    edge sheds instead of growing memory without bound. Clients
    should back off and retry (HTTP surfaces map this to 429)."""

    status_code = StatusCode.RATE_LIMITED


class QueryOverloadedError(OverloadedError):
    """The frontend admission controller shed this query at the door:
    the tenant is over its qps quota, or the bounded wait queue is
    full. Retryable after client back-off (HTTP 429)."""

    status_code = StatusCode.QUERY_OVERLOADED


class QueryQueueTimeoutError(OverloadedError):
    """The query was admitted to the wait queue but no execution slot
    freed within the queue-time SLO: the instance is saturated. Shed
    instead of growing the queue's sojourn time without bound
    (HTTP 503)."""

    status_code = StatusCode.QUERY_QUEUE_TIMEOUT


class QueryDeadlineExceededError(GreptimeError):
    """The query's absolute deadline expired — at a cooperative
    checkpoint, or because a datanode failed to answer its bounded
    per-call deadline (slow or blackholed). The deadline BOUNDS the
    query; it never hangs (HTTP 503)."""

    status_code = StatusCode.DEADLINE_EXCEEDED


class ArithmeticOverflowError(ExecutionError):
    """An exact integer aggregate (e.g. SUM over BIGINT/UINT64)
    exceeds the int64 result range; raised instead of silently
    wrapping two's-complement."""


# wire mapping: one REPRESENTATIVE class per status code so a typed
# error re-raises as the same class on the far side of an RPC
# boundary (codes shared by several classes map to the most specific
# retry-relevant one)
_CODE_CLASSES: dict[StatusCode, type] = {
    StatusCode.UNSUPPORTED: UnsupportedError,
    StatusCode.INVALID_ARGUMENTS: InvalidArgumentError,
    StatusCode.INVALID_SYNTAX: InvalidSyntaxError,
    StatusCode.PLAN_QUERY: PlanError,
    StatusCode.ENGINE_EXECUTE_QUERY: ExecutionError,
    StatusCode.TABLE_NOT_FOUND: TableNotFoundError,
    StatusCode.TABLE_ALREADY_EXISTS: TableAlreadyExistsError,
    StatusCode.TABLE_COLUMN_NOT_FOUND: ColumnNotFoundError,
    StatusCode.DATABASE_NOT_FOUND: DatabaseNotFoundError,
    StatusCode.DATABASE_ALREADY_EXISTS: DatabaseAlreadyExistsError,
    StatusCode.REGION_NOT_FOUND: RegionNotFoundError,
    StatusCode.REGION_READONLY: RegionReadonlyError,
    StatusCode.STORAGE_UNAVAILABLE: StorageError,
    StatusCode.RATE_LIMITED: IngestOverloadedError,
    StatusCode.QUERY_OVERLOADED: QueryOverloadedError,
    StatusCode.QUERY_QUEUE_TIMEOUT: QueryQueueTimeoutError,
    StatusCode.DEADLINE_EXCEEDED: QueryDeadlineExceededError,
    StatusCode.FLOW_NOT_FOUND: FlowNotFoundError,
    StatusCode.FLOW_ALREADY_EXISTS: FlowAlreadyExistsError,
    StatusCode.ILLEGAL_STATE: IllegalStateError,
}


def wire_message(e: Exception) -> str:
    """Error text with the `[gtdb:<code>]` marker prepended for typed
    errors — the SAME marker the Flight boundary stamps
    (servers/flight.py wrap_flight_error), reused on the MySQL and
    Postgres wires so every protocol client can classify overload/
    deadline/shed errors by code instead of prose."""
    msg = str(e) or type(e).__name__
    if isinstance(e, GreptimeError):
        return f"[gtdb:{int(e.status_code)}] {msg}"
    return msg


def error_from_code(code: int, msg: str) -> GreptimeError:
    """Rebuild the typed error a remote process serialized as its
    status code (see servers/flight.py wrap_flight_error /
    dist/client.py _raise)."""
    try:
        cls = _CODE_CLASSES.get(StatusCode(int(code)))
    except ValueError:
        cls = None
    if cls is None:
        return GreptimeError(msg)
    return cls(msg)
