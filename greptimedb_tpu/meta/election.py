"""Lease-based leader election over the CAS kv.

Capability counterpart of the reference's etcd election
(/root/reference/src/meta-srv/src/election/etcd.rs:161-206
campaign/lease keep-alive: the leader holds a leased key and renews it;
followers watch and take over when the lease lapses), built on the same
compare-and-put primitive our KvBackend already guarantees.

The leader key holds {leader, expires_at}: a candidate CAS-claims the
key when absent or expired, the incumbent CAS-renews against the exact
bytes it last wrote (so a steal it didn't see makes renewal fail
cleanly), and stepping down deletes the key for an immediate handover.
"""

from __future__ import annotations

import json
import logging
import threading

import time

from greptimedb_tpu.meta.kv import KvBackend

from greptimedb_tpu import concurrency

_log = logging.getLogger("greptimedb_tpu.meta.election")

LEADER_KEY = "__meta/election/leader"


class Election:
    """One candidate's campaign loop. start() spawns the ticker;
    is_leader reflects the latest observation."""

    def __init__(self, kv: KvBackend, candidate_id: str, *,
                 key: str = LEADER_KEY, lease_s: float = 5.0,
                 tick_s: float | None = None,
                 on_change=None):
        self.kv = kv
        self.me = candidate_id
        self.key = key
        self.lease_s = lease_s
        self.tick_s = tick_s if tick_s is not None else lease_s / 3.0
        self.on_change = on_change
        self._is_leader = False
        self._last_written: bytes | None = None
        self._stop = concurrency.Event()
        self._thread: threading.Thread | None = None
        self._lock = concurrency.Lock()

    # ---- observation --------------------------------------------------
    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def leader(self) -> tuple[str | None, float]:
        doc = self._read()
        if doc is None:
            return None, 0.0
        return doc.get("leader"), float(doc.get("expires_at", 0.0))

    def _read(self) -> dict | None:
        raw = self.kv.get(self.key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    # ---- campaign -----------------------------------------------------
    def step(self, now: float | None = None) -> bool:
        """One election round; returns leadership after the round."""
        now = time.time() if now is None else now
        # GTS103: the round intentionally holds the in-process lock
        # across the kv CAS, which waits on the CROSS-PROCESS flock —
        # bounded by a peer's lease tick, not by this process. Splitting
        # it would let two in-process campaigners interleave reads and
        # CAS attempts of one round.
        with self._lock:  # gtlint: disable=GTS103
            raw = self.kv.get(self.key)
            doc = None
            if raw is not None:
                try:
                    doc = json.loads(raw)
                except ValueError:
                    doc = None
            new = json.dumps({
                # wall clock by design: expires_at lives in the SHARED
                # kv and is compared against every candidate's own
                # clock — monotonic clocks are process-local and
                # meaningless across them
                "leader": self.me,
                "expires_at": now + self.lease_s,  # gtlint: disable=GT011
            }).encode()
            won = False
            if raw is None:
                won = self.kv.compare_and_put(self.key, None, new,
                                              durable=False)
            elif doc is None:
                # corrupt leader key: CAS against its raw bytes so SOME
                # candidate can always repair it
                won = self.kv.compare_and_put(self.key, raw, new,
                                              durable=False)
            elif doc.get("leader") == self.me:
                # renew against the exact bytes we hold; a steal we
                # haven't observed fails the CAS and demotes us
                expect = (self._last_written
                          if self._last_written is not None else raw)
                won = self.kv.compare_and_put(self.key, expect, new,
                                              durable=False)
            elif float(doc.get("expires_at", 0.0)) < now:
                won = self.kv.compare_and_put(self.key, raw, new,
                                              durable=False)
            if won:
                self._last_written = new
            was = self._is_leader
            self._is_leader = won
        if won != was and self.on_change is not None:
            try:
                self.on_change(won)
            except Exception as e:  # noqa: BLE001
                # a throwing observer must not demote/kill the loop
                _log.warning("leadership observer failed: %s", e)
        return won

    def resign(self):
        """Step down: delete the key iff we still own it."""
        with self._lock:
            if not self._is_leader:
                return
            raw = self.kv.get(self.key)
            if raw is not None and raw == self._last_written:
                # best-effort: CAS to an already-expired lease so the
                # next candidate's step() takes over immediately
                self.kv.compare_and_put(self.key, raw, json.dumps({
                    "leader": self.me, "expires_at": 0.0,
                }).encode(), durable=False)
            was = self._is_leader
            self._is_leader = False
        if was and self.on_change is not None:
            try:
                self.on_change(False)
            except Exception as e:  # noqa: BLE001
                _log.warning("leadership observer failed on resign: %s",
                             e)

    # ---- lifecycle ----------------------------------------------------
    def start(self) -> "Election":
        self._thread = concurrency.Thread(
            target=self._loop, daemon=True,
            name=f"election-{self.me}",
        )
        self._thread.start()
        return self

    def _loop(self):
        self.step()
        while not self._stop.wait(self.tick_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001
                # kv momentarily unreachable: lease expiry handles
                # demotion; keep ticking so we can re-campaign
                _log.debug("election step failed: %s", e)

    def stop(self, *, resign: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if resign:
            self.resign()
