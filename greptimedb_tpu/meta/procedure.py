"""Persistent procedure (saga) framework.

Capability counterpart of /root/reference/src/common/procedure/src/
procedure.rs:33-110 + local runner: multi-step operations (DDL, region
migration) run as state machines whose state is dumped to the kv store
after every persisting step, so a crashed node resumes or rolls back on
restart. Status mirrors the reference's Executing{persist}/Suspended/Done.
"""

from __future__ import annotations

import json
import threading

import time
import traceback
import uuid
from dataclasses import dataclass

from greptimedb_tpu.errors import IllegalStateError
from greptimedb_tpu.meta.kv import KvBackend

from greptimedb_tpu import concurrency

PROC_PREFIX = "__procedure/"


@dataclass
class Status:
    kind: str                  # executing | suspended | done | poisoned
    persist: bool = False
    output: object = None

    @staticmethod
    def executing(*, persist: bool = True) -> "Status":
        return Status("executing", persist=persist)

    @staticmethod
    def done(output=None) -> "Status":
        return Status("done", output=output)

    @staticmethod
    def suspended() -> "Status":
        return Status("suspended", persist=True)


class Procedure:
    """Subclass with: type_name (class attr), execute(ctx) -> Status,
    dump() -> dict, and classmethod restore(data: dict). Optional
    rollback(ctx)."""

    type_name: str = ""

    def execute(self, ctx) -> Status:
        raise NotImplementedError

    def dump(self) -> dict:
        raise NotImplementedError

    def rollback(self, ctx) -> None:
        pass

    @classmethod
    def restore(cls, data: dict) -> "Procedure":
        raise NotImplementedError


@dataclass
class ProcedureMeta:
    proc_id: str
    type_name: str
    state: str                 # running | done | failed | rolled_back
    error: str | None = None
    output: object = None


class ProcedureManager:
    """Runs procedures on worker threads with retry/backoff, persisting
    state between steps (LocalManager analog,
    /root/reference/src/common/procedure/src/local/)."""

    def __init__(self, kv: KvBackend, *, max_retries: int = 3,
                 retry_delay_s: float = 0.05):
        self.kv = kv
        self.max_retries = max_retries
        self.retry_delay_s = retry_delay_s
        self._loaders: dict[str, type[Procedure]] = {}
        self._metas: dict[str, ProcedureMeta] = {}
        self._events: dict[str, threading.Event] = {}
        self._lock = concurrency.Lock()

    def register_loader(self, type_name: str, cls: type[Procedure]):
        self._loaders[type_name] = cls

    # ------------------------------------------------------------------
    def submit(self, procedure: Procedure, ctx=None) -> str:
        proc_id = uuid.uuid4().hex
        meta = ProcedureMeta(proc_id, procedure.type_name, "running")
        ev = concurrency.Event()
        with self._lock:
            self._metas[proc_id] = meta
            self._events[proc_id] = ev
        self._persist_state(proc_id, procedure, "running")
        t = concurrency.Thread(
            target=self._run, args=(proc_id, procedure, ctx, ev),
            daemon=True, name=f"procedure-{procedure.type_name}",
        )
        t.start()
        return proc_id

    def wait(self, proc_id: str, timeout: float = 30.0) -> ProcedureMeta:
        ev = self._events.get(proc_id)
        if ev is None or not ev.wait(timeout):
            raise IllegalStateError(f"procedure {proc_id} did not finish")
        return self._metas[proc_id]

    def submit_and_wait(self, procedure: Procedure, ctx=None,
                        timeout: float = 30.0) -> ProcedureMeta:
        return self.wait(self.submit(procedure, ctx), timeout)

    # ------------------------------------------------------------------
    def _run(self, proc_id: str, procedure: Procedure, ctx,
             ev: threading.Event):
        meta = self._metas[proc_id]
        retries = 0
        try:
            while True:
                try:
                    status = procedure.execute(ctx)
                except Exception as e:
                    retries += 1
                    if retries > self.max_retries:
                        meta.state = "failed"
                        meta.error = f"{e}\n{traceback.format_exc()}"
                        try:
                            procedure.rollback(ctx)
                            meta.state = "rolled_back"
                        except Exception:
                            traceback.print_exc()
                        self._finish(proc_id)
                        return
                    time.sleep(self.retry_delay_s * (2 ** (retries - 1)))
                    continue
                retries = 0
                if status.kind == "done":
                    meta.state = "done"
                    meta.output = status.output
                    self._finish(proc_id)
                    return
                if status.persist:
                    self._persist_state(proc_id, procedure, "running")
                if status.kind == "suspended":
                    time.sleep(self.retry_delay_s)
        finally:
            ev.set()

    def _persist_state(self, proc_id: str, procedure: Procedure,
                       state: str):
        self.kv.put_json(PROC_PREFIX + proc_id, {
            "type_name": procedure.type_name,
            "state": state,
            "data": procedure.dump(),
        })

    def _finish(self, proc_id: str):
        self.kv.delete(PROC_PREFIX + proc_id)

    # ------------------------------------------------------------------
    def recover(self, ctx=None) -> list[str]:
        """Resume procedures left 'running' by a crash (the crash-resume
        path of the reference's procedure store)."""
        resumed = []
        for key, raw in self.kv.range(PROC_PREFIX):
            doc = json.loads(raw)
            cls = self._loaders.get(doc["type_name"])
            if cls is None:
                continue
            proc = cls.restore(doc["data"])
            proc_id = key[len(PROC_PREFIX):]
            meta = ProcedureMeta(proc_id, proc.type_name, "running")
            ev = concurrency.Event()
            with self._lock:
                self._metas[proc_id] = meta
                self._events[proc_id] = ev
            concurrency.Thread(
                target=self._run, args=(proc_id, proc, ctx, ev),
                daemon=True,
            ).start()
            resumed.append(proc_id)
        return resumed

    def list_procedures(self) -> list[ProcedureMeta]:
        with self._lock:
            return list(self._metas.values())
