"""Phi-accrual failure detection.

Semantics of /root/reference/src/meta-srv/src/failure_detector.rs:8-26 (a
port of Akka's PhiAccrualFailureDetector): heartbeat inter-arrival times
feed a normal model; phi(now) = -log10(P(no heartbeat for this long));
crossing the threshold marks the peer suspect.
"""

from __future__ import annotations

import math
from collections import deque


class PhiAccrualFailureDetector:
    def __init__(
        self,
        *,
        threshold: float = 8.0,
        min_std_deviation_ms: float = 100.0,
        acceptable_heartbeat_pause_ms: float = 10_000.0,
        first_heartbeat_estimate_ms: float = 1_000.0,
        max_sample_size: int = 1_000,
    ):
        self.threshold = threshold
        self.min_std_deviation_ms = min_std_deviation_ms
        self.acceptable_pause_ms = acceptable_heartbeat_pause_ms
        self.first_estimate_ms = first_heartbeat_estimate_ms
        self._intervals: deque[float] = deque(maxlen=max_sample_size)
        self._sum = 0.0
        self._sum2 = 0.0
        self.last_heartbeat_ms: float | None = None

    def heartbeat(self, now_ms: float) -> None:
        last = self.last_heartbeat_ms
        self.last_heartbeat_ms = now_ms
        if last is None:
            # seed the model like the reference: mean = first estimate,
            # stddev = estimate / 4
            est = self.first_estimate_ms
            self._push(est - est / 4)
            self._push(est + est / 4)
            return
        self._push(now_ms - last)

    def _push(self, interval: float) -> None:
        if len(self._intervals) == self._intervals.maxlen:
            old = self._intervals[0]
            self._sum -= old
            self._sum2 -= old * old
        self._intervals.append(interval)
        self._sum += interval
        self._sum2 += interval * interval

    @property
    def mean(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    @property
    def std_deviation(self) -> float:
        n = len(self._intervals)
        if n == 0:
            return self.min_std_deviation_ms
        var = max(self._sum2 / n - self.mean ** 2, 0.0)
        return max(math.sqrt(var), self.min_std_deviation_ms)

    def phi(self, now_ms: float) -> float:
        if self.last_heartbeat_ms is None:
            return 0.0
        elapsed = now_ms - self.last_heartbeat_ms
        mean = self.mean + self.acceptable_pause_ms
        std = self.std_deviation
        y = (elapsed - mean) / std
        # saturate: the cubic in the exponent overflows exp() past |y|~21,
        # and the probabilities are already pinned at 0/1 well before that
        y = max(min(y, 18.0), -18.0)
        # logistic approximation of the normal CDF (as in Akka/reference)
        e = math.exp(-y * (1.5976 + 0.070566 * y * y))
        if elapsed > mean:
            p = e / (1.0 + e)
        else:
            p = 1.0 - 1.0 / (1.0 + e)
        if p < 1e-300:
            p = 1e-300
        return -math.log10(p)

    def is_available(self, now_ms: float) -> bool:
        return self.phi(now_ms) < self.threshold
