"""Key-value metadata backends.

Capability counterpart of /root/reference/src/common/meta/src/kv_backend/
(etcd, memory, raft-engine backends behind one KvBackend trait with txn
support): get/put/range/delete plus compare-and-put, which is what the
metadata layer, procedure store, and election need. An external etcd can
slot in behind the same interface later.
"""

from __future__ import annotations

import json
import os

from greptimedb_tpu import concurrency

class KvBackend:
    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def compare_and_put(self, key: str, expect: bytes | None,
                        value: bytes, *, durable: bool = True) -> bool:
        """Atomic: put iff current value == expect (None == absent).
        `durable=False` marks EPHEMERAL state (election leases): the
        write must be atomic and visible, but need not survive power
        loss — it expires on its own. Backends may skip fsync."""
        raise NotImplementedError

    def put_many(self, items: list[tuple[str, bytes]]) -> None:
        """Batch put: ONE commit (one flock + persist for durable
        backends) instead of one per key — DDL fanning N region routes
        must not pay N fsyncs."""
        for k, v in items:
            self.put(k, v)

    def delete_many(self, keys: list[str]) -> int:
        """Batch delete under one commit; returns how many existed."""
        return sum(1 for k in keys if self.delete(k))

    # convenience
    def get_json(self, key: str):
        v = self.get(key)
        return None if v is None else json.loads(v)

    def put_json(self, key: str, obj) -> None:
        self.put(key, json.dumps(obj).encode())


class MemoryKv(KvBackend):
    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = concurrency.RLock()

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def put(self, key, value):
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key):
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix):
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items()
                if k.startswith(prefix)
            )

    def compare_and_put(self, key, expect, value, *, durable=True):
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = bytes(value)
            return True

    def put_many(self, items):
        with self._lock:
            for k, v in items:
                self._data[k] = bytes(v)

    def delete_many(self, keys):
        with self._lock:
            return sum(
                1 for k in keys if self._data.pop(k, None) is not None
            )


class FsKv(KvBackend):
    """Durable kv over one JSON file with atomic rename commits — the
    standalone-mode analog of the reference's raft-engine kv backend
    (src/log-store/src/raft_engine/backend.rs).

    Safe for MULTIPLE instances (threads or processes) over one file:
    every operation revalidates the in-memory cache against the file's
    (mtime_ns, size) stamp, and mutations hold an OS-level flock on a
    sidecar lock file — so compare_and_put is a true cross-process CAS
    and leader election over a shared data_home can't split-brain.

    Keys written with `durable=False` (election leases) live in a
    SIDECAR file (`<path>.eph`, atomic rename, never fsync'd): the
    durable file is only ever replaced by an fsync'd copy, so a power
    loss can lose at most the leases — which expire on their own —
    never the routes/metadata the fsync exists to protect."""

    def __init__(self, path: str):
        self.path = path
        self._mem = MemoryKv()     # durable keys (fsync'd commits)
        self._emem = MemoryKv()    # ephemeral keys (<path>.eph)
        self._lock = concurrency.RLock()
        self._stamp: tuple | None = None
        self._estamp: tuple | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._reload_if_changed()

    # ---- cross-instance coherence -------------------------------------
    @property
    def _eph_path(self) -> str:
        return self.path + ".eph"

    def _file_stamp(self, path: str | None = None):
        try:
            st = os.stat(path or self.path)
            return (st.st_mtime_ns, st.st_size)
        except FileNotFoundError:
            return None

    @staticmethod
    def _load_file(path: str, stamp) -> MemoryKv | None:
        mem = MemoryKv()
        if stamp is not None:
            try:
                with open(path) as f:
                    for k, v in json.load(f).items():
                        mem.put(k, bytes.fromhex(v))
            except (ValueError, OSError):
                return None   # mid-replace read; next op retries
        return mem

    def _reload_if_changed(self):
        stamp = self._file_stamp()
        if stamp != self._stamp:
            mem = self._load_file(self.path, stamp)
            if mem is not None:
                self._mem = mem
                self._stamp = stamp
        estamp = self._file_stamp(self._eph_path)
        if estamp != self._estamp:
            emem = self._load_file(self._eph_path, estamp)
            if emem is not None:
                self._emem = emem
                self._estamp = estamp

    def _flock(self):
        import fcntl
        from contextlib import contextmanager

        @contextmanager
        def hold():
            with open(self.path + ".lock", "w") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lf, fcntl.LOCK_UN)

        return hold()

    def _persist(self):
        doc = {k: v.hex() for k, v in self._mem.range("")}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._stamp = self._file_stamp()

    def _persist_eph(self):
        # NO fsync, by design: ephemeral writes (election lease
        # renewals, every lease_s/3 forever) were stalling every
        # concurrent kv mutation behind a loaded disk's fsync — the
        # observed load-dependent golden wire-topology DROP timeout.
        # The atomic rename keeps the write all-or-nothing and visible
        # to peers; losing a lease to power loss is harmless (it
        # expires anyway), and the durable file is untouched here.
        doc = {k: v.hex() for k, v in self._emem.range("")}
        tmp = self._eph_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
        os.replace(tmp, self._eph_path)
        self._estamp = self._file_stamp(self._eph_path)

    def get(self, key):
        with self._lock:
            self._reload_if_changed()
            v = self._emem.get(key)
            return v if v is not None else self._mem.get(key)

    # GTS103 (put/delete/compare_and_put): the in-process lock
    # deliberately covers the CROSS-PROCESS flock + fsync'd persist —
    # its hold time is bounded by the peer process's critical section
    # (seconds under load), and releasing it earlier would let sibling
    # threads interleave _reload/_mem mutation/persist around the flock.
    def put(self, key, value):
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            self._mem.put(key, value)
            self._persist()
            if self._emem.delete(key):
                # a durable write supersedes any ephemeral shadow
                self._persist_eph()

    def delete(self, key):
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            out = self._mem.delete(key)
            if out:
                self._persist()
            eout = self._emem.delete(key)
            if eout:
                self._persist_eph()
            return out or eout

    def range(self, prefix):
        with self._lock:
            self._reload_if_changed()
            merged = dict(self._mem.range(prefix))
            merged.update(self._emem.range(prefix))
            return tuple(sorted(merged.items()))

    def compare_and_put(self, key, expect, value, *, durable=True):
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            cur = self._emem.get(key)
            in_eph = cur is not None
            if not in_eph:
                cur = self._mem.get(key)
            if cur != (bytes(expect) if expect is not None else None):
                return False
            if durable:
                self._mem.put(key, value)
                self._persist()
                if in_eph:
                    self._emem.delete(key)
                    self._persist_eph()
            else:
                # the ephemeral copy shadows any durable one on reads;
                # in practice a key is one or the other for life
                # (election leases are always durable=False)
                self._emem.put(key, value)
                self._persist_eph()
            return True

    def put_many(self, items):
        if not items:
            return
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            emut = False
            for k, v in items:
                self._mem.put(k, v)
                # like put(): a durable write supersedes any
                # ephemeral shadow, or get() would serve stale bytes
                emut |= self._emem.delete(k)
            self._persist()
            if emut:
                self._persist_eph()

    def delete_many(self, keys):
        if not keys:
            return 0
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            n = 0
            dmut = emut = False
            for k in keys:
                d = self._mem.delete(k)
                e = self._emem.delete(k)
                dmut |= d
                emut |= e
                if d or e:
                    n += 1
            if dmut:
                self._persist()
            if emut:
                self._persist_eph()
            return n
