"""Key-value metadata backends.

Capability counterpart of /root/reference/src/common/meta/src/kv_backend/
(etcd, memory, raft-engine backends behind one KvBackend trait with txn
support): get/put/range/delete plus compare-and-put, which is what the
metadata layer, procedure store, and election need. An external etcd can
slot in behind the same interface later.
"""

from __future__ import annotations

import json
import os
import threading


class KvBackend:
    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def compare_and_put(self, key: str, expect: bytes | None,
                        value: bytes) -> bool:
        """Atomic: put iff current value == expect (None == absent)."""
        raise NotImplementedError

    # convenience
    def get_json(self, key: str):
        v = self.get(key)
        return None if v is None else json.loads(v)

    def put_json(self, key: str, obj) -> None:
        self.put(key, json.dumps(obj).encode())


class MemoryKv(KvBackend):
    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = threading.RLock()

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def put(self, key, value):
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key):
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix):
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items()
                if k.startswith(prefix)
            )

    def compare_and_put(self, key, expect, value):
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = bytes(value)
            return True


class FsKv(KvBackend):
    """Durable kv over one JSON file with atomic rename commits — the
    standalone-mode analog of the reference's raft-engine kv backend
    (src/log-store/src/raft_engine/backend.rs)."""

    def __init__(self, path: str):
        self.path = path
        self._mem = MemoryKv()
        self._lock = threading.RLock()
        if os.path.exists(path):
            with open(path) as f:
                for k, v in json.load(f).items():
                    self._mem.put(k, bytes.fromhex(v))
        else:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _persist(self):
        doc = {k: v.hex() for k, v in self._mem.range("")}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def get(self, key):
        return self._mem.get(key)

    def put(self, key, value):
        with self._lock:
            self._mem.put(key, value)
            self._persist()

    def delete(self, key):
        with self._lock:
            out = self._mem.delete(key)
            if out:
                self._persist()
            return out

    def range(self, prefix):
        return self._mem.range(prefix)

    def compare_and_put(self, key, expect, value):
        with self._lock:
            ok = self._mem.compare_and_put(key, expect, value)
            if ok:
                self._persist()
            return ok
