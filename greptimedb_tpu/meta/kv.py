"""Key-value metadata backends.

Capability counterpart of /root/reference/src/common/meta/src/kv_backend/
(etcd, memory, raft-engine backends behind one KvBackend trait with txn
support): get/put/range/delete plus compare-and-put, which is what the
metadata layer, procedure store, and election need. An external etcd can
slot in behind the same interface later.
"""

from __future__ import annotations

import json
import os

from greptimedb_tpu import concurrency

class KvBackend:
    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def range(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def compare_and_put(self, key: str, expect: bytes | None,
                        value: bytes) -> bool:
        """Atomic: put iff current value == expect (None == absent)."""
        raise NotImplementedError

    # convenience
    def get_json(self, key: str):
        v = self.get(key)
        return None if v is None else json.loads(v)

    def put_json(self, key: str, obj) -> None:
        self.put(key, json.dumps(obj).encode())


class MemoryKv(KvBackend):
    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = concurrency.RLock()

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def put(self, key, value):
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key):
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix):
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items()
                if k.startswith(prefix)
            )

    def compare_and_put(self, key, expect, value):
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = bytes(value)
            return True


class FsKv(KvBackend):
    """Durable kv over one JSON file with atomic rename commits — the
    standalone-mode analog of the reference's raft-engine kv backend
    (src/log-store/src/raft_engine/backend.rs).

    Safe for MULTIPLE instances (threads or processes) over one file:
    every operation revalidates the in-memory cache against the file's
    (mtime_ns, size) stamp, and mutations hold an OS-level flock on a
    sidecar lock file — so compare_and_put is a true cross-process CAS
    and leader election over a shared data_home can't split-brain."""

    def __init__(self, path: str):
        self.path = path
        self._mem = MemoryKv()
        self._lock = concurrency.RLock()
        self._stamp: tuple | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._reload_if_changed()

    # ---- cross-instance coherence -------------------------------------
    def _file_stamp(self):
        try:
            st = os.stat(self.path)
            return (st.st_mtime_ns, st.st_size)
        except FileNotFoundError:
            return None

    def _reload_if_changed(self):
        stamp = self._file_stamp()
        if stamp == self._stamp:
            return
        mem = MemoryKv()
        if stamp is not None:
            try:
                with open(self.path) as f:
                    for k, v in json.load(f).items():
                        mem.put(k, bytes.fromhex(v))
            except (ValueError, OSError):
                return   # mid-replace read; next op retries
        self._mem = mem
        self._stamp = stamp

    def _flock(self):
        import fcntl
        from contextlib import contextmanager

        @contextmanager
        def hold():
            with open(self.path + ".lock", "w") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lf, fcntl.LOCK_UN)

        return hold()

    def _persist(self):
        doc = {k: v.hex() for k, v in self._mem.range("")}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._stamp = self._file_stamp()

    def get(self, key):
        with self._lock:
            self._reload_if_changed()
            return self._mem.get(key)

    # GTS103 (put/delete/compare_and_put): the in-process lock
    # deliberately covers the CROSS-PROCESS flock + fsync'd persist —
    # its hold time is bounded by the peer process's critical section
    # (seconds under load), and releasing it earlier would let sibling
    # threads interleave _reload/_mem mutation/persist around the flock.
    def put(self, key, value):
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            self._mem.put(key, value)
            self._persist()

    def delete(self, key):
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            out = self._mem.delete(key)
            if out:
                self._persist()
            return out

    def range(self, prefix):
        with self._lock:
            self._reload_if_changed()
            return self._mem.range(prefix)

    def compare_and_put(self, key, expect, value):
        with self._lock, self._flock():  # gtlint: disable=GTS103
            self._reload_if_changed()
            ok = self._mem.compare_and_put(key, expect, value)
            if ok:
                self._persist()
            return ok
