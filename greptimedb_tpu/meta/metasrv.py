"""Metasrv: cluster brain — node registry, region routes, leases,
heartbeat mailbox, failure detection and failover.

Capability counterpart of /root/reference/src/meta-srv/src/: the heartbeat
handler pipeline (handler/*.rs), region-lease grants
(region_lease_handler.rs), placement selectors (selector/), the
RegionSupervisor consulting per-(node,region) phi-accrual detectors and
triggering region migration (region/supervisor.rs:123-392), and the region
migration procedure state machine (procedure/region_migration/*:
open_candidate -> downgrade_leader -> upgrade_candidate ->
update_metadata).
"""

from __future__ import annotations

import json
import logging

import time
from collections import deque
from dataclasses import dataclass, field

from greptimedb_tpu.errors import IllegalStateError
from greptimedb_tpu.meta.failure_detector import PhiAccrualFailureDetector
from greptimedb_tpu.meta.kv import KvBackend
from greptimedb_tpu.meta.procedure import Procedure, ProcedureManager, Status

from greptimedb_tpu import concurrency

_log = logging.getLogger("greptimedb_tpu.meta.metasrv")

ROUTE_PREFIX = "__route/"
PEER_PREFIX = "__peer/"
LEASE_SECS = 10.0


@dataclass
class NodeInfo:
    node_id: int
    last_heartbeat_ms: float = 0.0
    region_stats: dict = field(default_factory=dict)  # region_id -> stats
    alive: bool = True
    # fleet observability: role/addr declared at registration (or by
    # the first enriched heartbeat), the latest heartbeat-carried
    # node-stats payload, and a bounded ring of recent samples
    role: str = "datanode"
    addr: str = ""
    stats: dict = field(default_factory=dict)
    stats_history: deque = field(default_factory=deque)

    @property
    def load(self) -> int:
        return sum(
            s.get("rows", 0) for s in self.region_stats.values()
        )


class Selector:
    """Region placement policy (selector/{round_robin,load_based}.rs)."""

    def __init__(self, kind: str = "round_robin"):
        self.kind = kind
        self._rr = 0

    def select(self, nodes: list[NodeInfo], n: int) -> list[int]:
        # only DATANODES host regions: frontends/flownodes heartbeat
        # into the same registry for fleet observability but must never
        # be placement targets
        alive = [nd for nd in nodes
                 if nd.alive and nd.role == "datanode"]
        if not alive:
            raise IllegalStateError("no alive datanodes")
        out = []
        if self.kind == "load_based":
            ranked = sorted(alive, key=lambda nd: nd.load)
            for i in range(n):
                out.append(ranked[i % len(ranked)].node_id)
            return out
        for _ in range(n):
            out.append(alive[self._rr % len(alive)].node_id)
            self._rr += 1
        return out


class Metasrv:
    def __init__(self, kv: KvBackend, *, selector: str = "round_robin",
                 phi_threshold: float = 8.0,
                 acceptable_pause_ms: float = 10_000.0,
                 stats_history: int = 32):
        self.kv = kv
        self.selector = Selector(selector)
        self.nodes: dict[int, NodeInfo] = {}
        self.detectors: dict[int, PhiAccrualFailureDetector] = {}
        self.procedures = ProcedureManager(kv)
        self.maintenance_mode = False
        self.phi_threshold = phi_threshold
        self.acceptable_pause_ms = acceptable_pause_ms
        # bounded per-node ring of heartbeat-carried node-stats samples
        self.stats_history = max(1, int(stats_history))
        self._mailbox: dict[int, list[dict]] = {}
        self._lock = concurrency.RLock()
        self._failover_cb = None  # set by the cluster: (region, from, to)
        self._load_routes()

    # ------------------------------------------------------------------
    # node lifecycle + heartbeats
    # ------------------------------------------------------------------
    def register_node(self, node_id: int, addr: str | None = None,
                      role: str = "datanode"):
        with self._lock:
            node = NodeInfo(node_id, role=role, addr=addr or "")
            node.stats_history = deque(maxlen=self.stats_history)
            self.nodes[node_id] = node
            self.detectors[node_id] = PhiAccrualFailureDetector(
                threshold=self.phi_threshold,
                acceptable_heartbeat_pause_ms=self.acceptable_pause_ms,
            )
            self._mailbox.setdefault(node_id, [])
            if addr and role == "datanode":
                # persisted peer address book: frontends resolve region
                # routes to datanode Flight addresses through this
                # (datanodes only — it feeds region routing)
                self.kv.put_json(PEER_PREFIX + str(node_id), addr)

    def peers(self) -> dict[int, str]:
        return {
            int(k[len(PEER_PREFIX):]): json.loads(v)
            for k, v in self.kv.range(PEER_PREFIX)
        }

    def heartbeat(self, node_id: int, region_stats: dict,
                  now_ms: float | None = None,
                  node_stats: dict | None = None,
                  role: str | None = None,
                  addr: str | None = None) -> list[dict]:
        """Handler pipeline: keep lease, collect stats, feed detector,
        drain mailbox instructions (returned in the heartbeat response as
        in the reference's mailbox design). `node_stats` is the
        heartbeat-carried node telemetry payload
        (telemetry/node_stats.build_node_stats): the latest sample plus
        a bounded ring of recent ones are kept per node, and the
        payload's role/addr heal a registration the leader lost (an HA
        leader change re-learns the fleet from heartbeats alone)."""
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        # the sender's IDENTITY (role + addr) rides EVERY beat
        # (explicit params, else the enriched payload): a restarted
        # leader whose first contact with a node is a heartbeat — the
        # client's beats kept succeeding, so it never re-registers —
        # must still learn the right role (a frontend can never become
        # a placement target) and heal the address book (a datanode
        # with no peer-book addr is undialable). Absent both, the
        # legacy datanode default applies.
        beat_role = role or (node_stats or {}).get("role")
        beat_addr = addr or (node_stats or {}).get("addr")
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                self.register_node(node_id, beat_addr,
                                   role=beat_role or "datanode")
                node = self.nodes[node_id]
            else:
                if beat_role:
                    node.role = str(beat_role)
                if beat_addr and node.addr != beat_addr:
                    node.addr = str(beat_addr)
                    if node.role == "datanode":
                        # heal the persisted peer book too (one kv
                        # write on CHANGE only, never per beat)
                        self.kv.put_json(PEER_PREFIX + str(node_id),
                                         node.addr)
            node.last_heartbeat_ms = now_ms
            node.region_stats = region_stats
            node.alive = True
            if node_stats:
                node.stats = node_stats
                if node.stats_history.maxlen is None:
                    node.stats_history = deque(
                        maxlen=self.stats_history
                    )
                node.stats_history.append(
                    {"ts_ms": now_ms, **node_stats}
                )
            self.detectors[node_id].heartbeat(now_ms)
            instructions = self._mailbox.get(node_id, [])
            self._mailbox[node_id] = []
            if node.role != "datanode":
                # non-region roles get no lease grant (nothing routes
                # to them); the heartbeat is pure liveness + telemetry
                return instructions
            # region lease grant: every region this node leads
            leases = [
                rid for rid, nid in self._all_routes().items()
                if nid == node_id
            ]
            return instructions + [{
                "type": "grant_lease",
                "regions": leases,
                "lease_secs": LEASE_SECS,
            }]

    # ------------------------------------------------------------------
    # fleet state (information_schema.cluster_* / meta_http /cluster)
    # ------------------------------------------------------------------
    def node_status(self, node_id: int,
                    now_ms: float | None = None) -> str:
        """Phi-accrual verdict for one node: ALIVE below half the
        threshold, UNHEALTHY between, DOWN past it (or already marked
        dead by the supervisor tick). UNKNOWN = registered but never
        heartbeated."""
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        with self._lock:
            node = self.nodes.get(node_id)
            det = self.detectors.get(node_id)
        if node is None or det is None:
            return "UNKNOWN"
        if not node.alive:
            return "DOWN"
        if det.last_heartbeat_ms is None:
            return "UNKNOWN"
        phi = det.phi(now_ms)
        if phi >= self.phi_threshold:
            return "DOWN"
        if phi >= self.phi_threshold * 0.5:
            return "UNHEALTHY"
        return "ALIVE"

    def cluster_nodes(self, now_ms: float | None = None, *,
                      history: bool = False) -> list[dict]:
        """One document per registered node: identity, liveness verdict
        (live phi value included), the latest heartbeat-carried
        node-stats payload, and optionally the bounded sample ring."""
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        out = []
        # the whole snapshot builds under the lock (RLock — node_status
        # re-enters): a heartbeat appending to a node's stats ring
        # mid-copy would otherwise tear the deque iteration
        with self._lock:
            nodes = sorted(self.nodes.values(), key=lambda n: n.node_id)
            phis = {
                nid: det.phi(now_ms)
                if det.last_heartbeat_ms is not None else None
                for nid, det in self.detectors.items()
            }
            for node in nodes:
                doc = {
                    "node_id": node.node_id,
                    "role": node.role,
                    "addr": (node.addr
                             or (node.stats or {}).get("addr", "")),
                    "status": self.node_status(node.node_id, now_ms),
                    "phi": phis.get(node.node_id),
                    "last_heartbeat_ms": node.last_heartbeat_ms,
                    "region_count": len(node.region_stats),
                    "stats": dict(node.stats),
                }
                if history:
                    doc["history"] = list(node.stats_history)
                out.append(doc)
        return out

    def send_instruction(self, node_id: int, instruction: dict):
        with self._lock:
            self._mailbox.setdefault(node_id, []).append(instruction)

    # ------------------------------------------------------------------
    # routes
    # ------------------------------------------------------------------
    def allocate_regions(self, region_ids: list[int]) -> dict[int, int]:
        """Place new regions on nodes via the selector; persist routes
        as ONE kv commit (one flock + fsync, not one per region — a
        multi-region CREATE must not pay N durable writes)."""
        with self._lock:
            chosen = self.selector.select(
                list(self.nodes.values()), len(region_ids)
            )
            routes = dict(zip(region_ids, chosen))
            self.kv.put_many([
                (ROUTE_PREFIX + str(rid), json.dumps(nid).encode())
                for rid, nid in routes.items()
            ])
            return routes

    def route_of(self, region_id: int) -> int | None:
        v = self.kv.get_json(ROUTE_PREFIX + str(region_id))
        return v

    def update_route(self, region_id: int, node_id: int):
        self.kv.put_json(ROUTE_PREFIX + str(region_id), node_id)

    def remove_routes(self, region_ids: list[int]):
        # one kv commit for the whole table's routes: the DDL wait on
        # the metasrv is bounded by ONE durable write, not N (the
        # per-region loop was the load-dependent golden wire-topology
        # DROP timeout — each delete fsync'd the whole kv file)
        self.kv.delete_many(
            [ROUTE_PREFIX + str(rid) for rid in region_ids]
        )

    def _all_routes(self) -> dict[int, int]:
        return {
            int(k[len(ROUTE_PREFIX):]): json.loads(v)
            for k, v in self.kv.range(ROUTE_PREFIX)
        }

    def _load_routes(self):
        pass  # routes live in kv; nothing to warm

    # ------------------------------------------------------------------
    # supervision / failover
    # ------------------------------------------------------------------
    def tick(self, now_ms: float | None = None) -> list[str]:
        """RegionSupervisor tick: check detectors, fail over regions led
        by suspect nodes. Returns submitted procedure ids."""
        if self.maintenance_mode:
            return []
        now_ms = now_ms if now_ms is not None else time.time() * 1000
        suspects = []
        with self._lock:
            for nid, det in self.detectors.items():
                node = self.nodes[nid]
                if node.alive and not det.is_available(now_ms):
                    node.alive = False
                    suspects.append(nid)
        out = []
        for nid in suspects:
            out.extend(self.failover_node(nid))
        return out

    def failover_node(self, node_id: int) -> list[str]:
        routes = self._all_routes()
        owned = [rid for rid, nid in routes.items() if nid == node_id]
        proc_ids = []
        for rid in owned:
            try:
                target = self.selector.select(
                    [nd for nd in self.nodes.values()
                     if nd.node_id != node_id],
                    1,
                )[0]
            except IllegalStateError:
                continue
            proc = RegionMigrationProcedure(
                region_id=rid, from_node=node_id, to_node=target,
                reason="failover",
            )
            proc_ids.append(self.procedures.submit(proc, self._ctx()))
        return proc_ids

    def migrate_region(self, region_id: int, to_node: int,
                       timeout: float = 30.0):
        """Manual migration (admin function migrate_region analog)."""
        from_node = self.route_of(region_id)
        if from_node is None:
            raise IllegalStateError(f"region {region_id} has no route")
        proc = RegionMigrationProcedure(
            region_id=region_id, from_node=from_node, to_node=to_node,
            reason="manual",
        )
        meta = self.procedures.submit_and_wait(
            proc, self._ctx(), timeout=timeout
        )
        if meta.state != "done":
            raise IllegalStateError(
                f"migration failed: {meta.state} {meta.error}"
            )

    def _ctx(self):
        return self


class RegionMigrationProcedure(Procedure):
    """open_candidate -> downgrade_leader -> upgrade_candidate ->
    update_metadata (procedure/region_migration/*.rs state machine)."""

    type_name = "RegionMigration"

    STATES = ("open_candidate", "downgrade_leader", "upgrade_candidate",
              "update_metadata", "done")

    def __init__(self, *, region_id: int, from_node: int, to_node: int,
                 reason: str = "manual", state: str = "open_candidate"):
        self.region_id = region_id
        self.from_node = from_node
        self.to_node = to_node
        self.reason = reason
        self.state = state

    def dump(self) -> dict:
        return {
            "region_id": self.region_id, "from_node": self.from_node,
            "to_node": self.to_node, "reason": self.reason,
            "state": self.state,
        }

    @classmethod
    def restore(cls, data: dict) -> "RegionMigrationProcedure":
        return cls(**data)

    def execute(self, metasrv: Metasrv) -> Status:
        cluster = getattr(metasrv, "cluster", None)
        if cluster is None:
            raise IllegalStateError("metasrv has no cluster attached")
        if self.state == "open_candidate":
            cluster.open_region_on(self.to_node, self.region_id,
                                   writable=False)
            self.state = "downgrade_leader"
            return Status.executing()
        if self.state == "downgrade_leader":
            # graceful: flush the leader so the candidate sees all data;
            # on failover the old node is dead and this is a no-op
            cluster.downgrade_region_on(
                self.from_node, self.region_id,
                failover=self.reason == "failover",
            )
            self.state = "upgrade_candidate"
            return Status.executing()
        if self.state == "upgrade_candidate":
            cluster.upgrade_region_on(self.to_node, self.region_id)
            self.state = "update_metadata"
            return Status.executing()
        if self.state == "update_metadata":
            metasrv.update_route(self.region_id, self.to_node)
            cluster.close_region_on(self.from_node, self.region_id)
            self.state = "done"
            return Status.done({
                "region_id": self.region_id, "to_node": self.to_node,
            })
        raise IllegalStateError(f"bad state {self.state}")

    def rollback(self, metasrv: Metasrv) -> None:
        cluster = getattr(metasrv, "cluster", None)
        if cluster is None:
            return
        # abort: drop the half-opened candidate, keep the original route
        try:
            cluster.close_region_on(self.to_node, self.region_id)
        except Exception as e:  # noqa: BLE001
            # the candidate may never have opened; the kept route is
            # what guarantees correctness, not this cleanup
            _log.info("rollback close of region %s on node %s: %s",
                      self.region_id, self.to_node, e)
