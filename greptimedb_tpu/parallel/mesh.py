"""Device mesh construction for the query engine."""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_SHARD = "shard"   # series axis (region/data parallel analog)
AXIS_TIME = "time"     # time-block axis (sequence parallel analog)


def make_mesh(
    devices: list | None = None,
    *,
    time_parallel: int = 1,
) -> Mesh:
    """Build a (shard, time) mesh over the available devices.

    time_parallel devices are dedicated to time-block parallelism; the rest
    shard the series axis. time_parallel=1 degenerates to pure series
    sharding (the common case for aggregate-heavy workloads)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    assert n % time_parallel == 0, (n, time_parallel)
    grid = np.asarray(devices).reshape(n // time_parallel, time_parallel)
    return Mesh(grid, (AXIS_SHARD, AXIS_TIME))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (AXIS_SHARD, AXIS_TIME))
