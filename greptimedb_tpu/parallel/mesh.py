"""Device mesh lifecycle for the query engine.

One process-wide mesh, built from the visible devices at first use and
threaded cli -> instance -> QueryEngine (`[mesh]` TOML knobs). The mesh
is the engine-side analog of the reference's region partitioning: the
series axis of every large grid shards over AXIS_SHARD and the shard_map
programs in parallel/dist.py + query/reduce.py + query/device_range.py +
promql/fast.py recombine with explicit collectives.

The replicate-vs-shard decision per query lives in query/planner.py
(decide_mesh_execution); this module only owns construction and the
process-wide singleton.
"""

from __future__ import annotations

import logging
import os

from dataclasses import dataclass

import numpy as np
import jax
from jax.sharding import Mesh

from greptimedb_tpu import concurrency

AXIS_SHARD = "shard"   # series axis (region/data parallel analog)
AXIS_TIME = "time"     # time-block axis (sequence parallel analog)

# Fixed series/row fold-block count for cross-device reductions: every
# blocked partial fold (sharded OR single-device) splits the reduced
# axis into FOLD_BLOCKS aligned blocks and combines them in one fixed
# left-fold order, so results are bit-identical across mesh sizes
# 1/2/4/8 and the unsharded path (tests/fuzz/test_fuzz_mesh_parity.py).
FOLD_BLOCKS = 8

_log = logging.getLogger("greptimedb_tpu.parallel.mesh")


@dataclass(frozen=True)
class MeshOptions:
    """`[mesh]` TOML knobs (config.py DEFAULTS mirrors these)."""

    enabled: bool = False
    axis_size: int = 0              # shard-axis devices; 0 = all visible
    time_parallel: int = 1          # devices dedicated to the time axis
    # CPU simulation: force N virtual host devices BEFORE jax init
    # (XLA_FLAGS --xla_force_host_platform_device_count)
    force_host_device_count: int = 0
    # replicate-vs-shard planner thresholds (query/planner.py)
    shard_min_series: int = 4096    # grid paths: series below this replicate
    shard_min_rows: int = 262144    # row paths: rows below this replicate
    # Pallas kernel paths (parallel/kernels): auto = native TPU backend
    # only; on = everywhere via interpret mode (tests/fuzz/CPU bench);
    # off = never. Shape floors keep small programs on the XLA paths.
    pallas_kernels: str = "auto"
    pallas_min_series: int = 4096   # kernel grid paths below this stay XLA
    pallas_min_rows: int = 262144   # fused merge-gather row floor
    pallas_max_k: int = 128         # topk merge kernel is O(k^2) per hop


def mesh_options_from(section: dict) -> MeshOptions:
    d = MeshOptions()
    return MeshOptions(
        enabled=bool(section.get("enabled", d.enabled)),
        axis_size=int(section.get("axis_size", d.axis_size)),
        time_parallel=int(section.get("time_parallel", d.time_parallel)),
        force_host_device_count=int(
            section.get("force_host_device_count",
                        d.force_host_device_count)
        ),
        shard_min_series=int(
            section.get("shard_min_series", d.shard_min_series)
        ),
        shard_min_rows=int(section.get("shard_min_rows", d.shard_min_rows)),
        pallas_kernels=str(
            section.get("pallas_kernels", d.pallas_kernels)
        ),
        pallas_min_series=int(
            section.get("pallas_min_series", d.pallas_min_series)
        ),
        pallas_min_rows=int(
            section.get("pallas_min_rows", d.pallas_min_rows)
        ),
        pallas_max_k=int(section.get("pallas_max_k", d.pallas_max_k)),
    )


def make_mesh(
    devices: list | None = None,
    *,
    time_parallel: int = 1,
) -> Mesh:
    """Build a (shard, time) mesh over the available devices.

    time_parallel devices are dedicated to time-block parallelism; the rest
    shard the series axis. time_parallel=1 degenerates to pure series
    sharding (the common case for aggregate-heavy workloads)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    assert n % time_parallel == 0, (n, time_parallel)
    grid = np.asarray(devices).reshape(n // time_parallel, time_parallel)
    return Mesh(grid, (AXIS_SHARD, AXIS_TIME))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                (AXIS_SHARD, AXIS_TIME))


def shard_count(mesh) -> int:
    """Shard-axis size of a mesh (1 when mesh is None)."""
    return 1 if mesh is None else int(mesh.shape[AXIS_SHARD])


# ----------------------------------------------------------------------
# process-wide mesh
# ----------------------------------------------------------------------

_state_lock = concurrency.Lock()
_global_mesh: Mesh | None = None
_global_opts: MeshOptions | None = None
_configured = False


def _force_host_devices(n: int) -> bool:
    """Request n virtual CPU devices. Only effective before the jax
    backend initializes; returns False (with a warning) otherwise."""
    flag = f"--xla_force_host_platform_device_count={n}"
    existing = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" in existing:
        return True  # already pinned (conftest / operator)
    try:
        # probe the backend REGISTRY, not jax.extend.backend.backends()
        # — calling backends() initializes every backend, which would
        # make this check self-defeating (the flag must land first)
        from jax._src import xla_bridge as _xb

        initialized = bool(getattr(_xb, "_backends", None))
    except Exception:  # noqa: BLE001 - probe API drift: assume live
        initialized = True
    if initialized and len(jax.devices()) < n:
        _log.warning(
            "[mesh] force_host_device_count=%d requested after the jax "
            "backend initialized with %d device(s); set XLA_FLAGS=%r "
            "before process start", n, len(jax.devices()), flag,
        )
        return False
    os.environ["XLA_FLAGS"] = f"{existing} {flag}".strip()
    return True


def configure(opts: MeshOptions) -> Mesh | None:
    """Build (once) and return the process-wide query mesh, or None when
    disabled / only one device is usable. Safe to call from every role
    entrypoint — first configuration wins."""
    global _global_mesh, _global_opts, _configured
    with _state_lock:
        if _configured:
            return _global_mesh
        _configured = True
        _global_opts = opts
        if not opts.enabled:
            return None
        if opts.force_host_device_count > 1:
            _force_host_devices(opts.force_host_device_count)
        devices = jax.devices()
        n = opts.axis_size * max(opts.time_parallel, 1) if opts.axis_size \
            else len(devices)
        n = min(n, len(devices))
        tp = max(opts.time_parallel, 1)
        n -= n % tp
        if n // tp <= 1:
            # covers the degenerate geometries too (1 device with
            # time_parallel=2 would otherwise build a 0-shard mesh)
            _log.info("[mesh] enabled but only %d usable device(s); "
                      "running single-device", max(n, 1))
            return None
        _global_mesh = make_mesh(devices[:n], time_parallel=tp)
        from greptimedb_tpu.telemetry.metrics import global_registry

        global_registry.gauge(
            "gtpu_mesh_devices",
            "Devices in the process-wide query mesh (shard axis)",
        ).set(shard_count(_global_mesh))
        _log.info("[mesh] query mesh %s over %d device(s)",
                  dict(_global_mesh.shape), n)
        return _global_mesh


def global_mesh() -> Mesh | None:
    """The process-wide mesh, or None when not configured/enabled."""
    with _state_lock:
        return _global_mesh


def global_mesh_opts() -> MeshOptions | None:
    """The MeshOptions configure() ran with, or None before configure.
    Sites without an engine in reach (query/window_fns.py) use this so
    the operator's `[mesh]` thresholds apply everywhere."""
    with _state_lock:
        return _global_opts


def update_shard_thresholds(*, base: MeshOptions | None = None,
                            shard_min_series: int | None = None,
                            shard_min_rows: int | None = None
                            ) -> MeshOptions:
    """Runtime update of the planner replicate/shard thresholds
    (autotune/knobs.py is the sanctioned caller — GT021). MeshOptions
    is frozen, so the process-wide object is SWAPPED, never mutated:
    sites reading via global_mesh_opts() see the new thresholds on
    their next plan; callers holding their own reference
    (QueryEngine.mesh_opts) are re-pointed by the knob's apply hook."""
    import dataclasses

    global _global_opts
    with _state_lock:
        cur = base or _global_opts or MeshOptions()
        kw = {}
        if shard_min_series is not None:
            kw["shard_min_series"] = int(shard_min_series)
        if shard_min_rows is not None:
            kw["shard_min_rows"] = int(shard_min_rows)
        new = dataclasses.replace(cur, **kw)
        # keep the no-engine-in-reach sites (global_mesh_opts readers)
        # on the same thresholds as the engine-held reference
        _global_opts = new
        return new


def reset_for_tests() -> None:
    """Drop the process-wide mesh so tests can reconfigure."""
    global _global_mesh, _global_opts, _configured
    with _state_lock:
        _global_mesh = None
        _global_opts = None
        _configured = False
