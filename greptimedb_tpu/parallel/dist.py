"""Distributed query kernels: shard_map programs with explicit collectives.

The three distribution patterns of the reference, re-expressed over ICI
(SURVEY.md §2.7 mapping):

1. dist_segment_agg  — commutative aggregate push-down + merge: each series
   shard computes full-width partial aggregates, psum/pmin/pmax recombines
   (replaces MergeScanExec + frontend final-aggregate).
2. halo_exchange     — ring transfer of window-tail cells between adjacent
   time shards (replaces PartitionRange overlap handling; the sequence-
   parallel primitive for windows crossing block boundaries).
3. dist_topk         — per-shard top-k, all_gather, re-select (replaces
   frontend sort+limit over gathered partials).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from greptimedb_tpu.ops import segment as S
from greptimedb_tpu.parallel.mesh import AXIS_SHARD, AXIS_TIME


def dist_segment_agg(mesh: Mesh, op: str, num_segments: int):
    """Build a shard_map'd segmented aggregate: rows sharded over AXIS_SHARD,
    output replicated. op in {sum, count, min, max, mean}."""

    def local(values, seg, mask):
        if op == "sum":
            part = S.seg_sum(values, seg, mask, num_segments)
            return jax.lax.psum(part, AXIS_SHARD)
        if op == "count":
            part = S.seg_count(seg, mask, num_segments)
            return jax.lax.psum(part, AXIS_SHARD)
        if op == "min":
            part = S.seg_min(values, seg, mask, num_segments)
            return jax.lax.pmin(part, AXIS_SHARD)
        if op == "max":
            part = S.seg_max(values, seg, mask, num_segments)
            return jax.lax.pmax(part, AXIS_SHARD)
        if op == "mean":
            s = jax.lax.psum(S.seg_sum(values, seg, mask, num_segments),
                             AXIS_SHARD)
            c = jax.lax.psum(S.seg_count(seg, mask, num_segments), AXIS_SHARD)
            return s / jnp.maximum(c, 1).astype(s.dtype)
        raise ValueError(op)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(AXIS_SHARD), P(AXIS_SHARD), P(AXIS_SHARD)),
        out_specs=P(),
        check_rep=False,
    )


def _halo_prev(x: jax.Array, halo: int, axis_name: str, axis: int, fill):
    """Ring halo: prepend the last `halo` cells (along `axis`) of the
    PREVIOUS shard on `axis_name`; the first shard gets `fill`."""
    # jax.lax.axis_size was removed from current JAX; psum of a python
    # literal folds to the static axis size inside shard_map
    n = jax.lax.psum(1, axis_name)
    tail = jax.lax.slice_in_dim(x, x.shape[axis] - halo, x.shape[axis],
                                axis=axis)
    # ring shift: device i receives from i-1
    perm = [(i, (i + 1) % n) for i in range(n)]
    prev_tail = jax.lax.ppermute(tail, axis_name, perm)
    idx = jax.lax.axis_index(axis_name)
    prev_tail = jnp.where(idx == 0,
                          jnp.full_like(prev_tail, fill), prev_tail)
    return jnp.concatenate([prev_tail, x], axis=axis)


def halo_exchange_prev(x: jax.Array, halo: int, axis_name: str = AXIS_TIME):
    """Prepend the last `halo` cells of the previous time shard (zeros for
    the first shard). x is the local (S, T_local) block inside shard_map;
    returns (S, halo + T_local)."""
    return _halo_prev(x, halo, axis_name, axis=1, fill=0.0)


def dist_topk(mesh: Mesh, k: int, *, largest: bool = True,
              kernel: bool = False, interpret: bool | None = None):
    """Distributed top-k over a sharded 1-D value array: local top-k,
    all_gather the candidates, re-select. Returns (values, global_indices).

    With kernel=True the all-gather reselect is replaced by the Pallas
    merge-path ring (parallel/kernels/topk_merge): each shard still
    selects its local candidates, but only the accumulated (k,) winner
    planes walk the ring. Winner values and indices are identical to
    the all-gather path (the merge tie-break reproduces top_k's
    lower-concat-index rule); the one exception is slots beyond the
    real candidate count when a shard holds fewer than k rows, where
    the kernel path reports its -inf padding sentinel index."""

    ns = mesh.devices.size

    def local(values, mask):
        n_local = values.shape[0]
        fill = jnp.asarray(-jnp.inf if largest else jnp.inf, values.dtype)
        v = jnp.where(mask, values, fill)
        vv = v if largest else -v
        loc_v, loc_i = jax.lax.top_k(vv, min(k, n_local))
        shard = jax.lax.axis_index(AXIS_SHARD)
        glob_i = loc_i + shard * n_local
        if kernel:
            from greptimedb_tpu.parallel.kernels import (
                interpret_mode, ring_topk_merge,
            )

            interp = interpret_mode() if interpret is None else interpret
            top_v, _, top_i, _ = ring_topk_merge(
                loc_v[None, :], loc_v[None, :], glob_i[None, :],
                jnp.isfinite(loc_v)[None, :], k=k, ns=ns,
                interpret=interp,
            )
            top_v, top_i = top_v[0], top_i[0]
            if not largest:
                top_v = -top_v
            return top_v, top_i
        all_v = jax.lax.all_gather(loc_v, AXIS_SHARD).reshape(-1)
        all_i = jax.lax.all_gather(glob_i, AXIS_SHARD).reshape(-1)
        top_v, sel = jax.lax.top_k(all_v, k)
        if not largest:
            top_v = -top_v
        return top_v, all_i[sel]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(AXIS_SHARD), P(AXIS_SHARD)),
        out_specs=(P(), P()),
        check_rep=False,
    )


# ----------------------------------------------------------------------
# shard_map building blocks for the LIVE query path (query/reduce.py,
# query/device_range.py, promql/fast.py, query/window_fns.py). All cross
# -shard combines that touch f32 sums go through gather_blocks +
# left_fold so the addition order is identical to the single-device
# blocked fold (parallel/mesh.FOLD_BLOCKS) — sharded results match the
# unsharded path bit-for-bit for decomposable aggregates.
# ----------------------------------------------------------------------

def halo_prev_1d(x: jax.Array, halo: int, axis_name: str = AXIS_SHARD,
                 fill=0.0):
    """Prepend the last `halo` cells of the PREVIOUS shard of a 1-D
    row-sharded array (the first shard gets `fill`). The sliding-window
    primitive for frames crossing shard boundaries
    (query/window_fns.py ROWS k PRECEDING)."""
    return _halo_prev(x, halo, axis_name, axis=0, fill=fill)


def gather_blocks(partial: jax.Array, axis_name: str = AXIS_SHARD):
    """Concatenate per-shard partial blocks along axis 0 in shard order:
    (B_local, ...) -> (B_local * n_shards, ...). Pure data movement —
    exact."""
    return jax.lax.all_gather(partial, axis_name, axis=0, tiled=True)


def left_fold_sum(parts: jax.Array):
    """Sum over axis 0 as an explicit unrolled left fold. The static add
    chain is the contract: both the sharded (post-gather) and unsharded
    blocked folds run this exact sequence, so f32 results agree
    bit-for-bit across mesh sizes."""
    total = parts[0]
    for i in range(1, parts.shape[0]):
        total = total + parts[i]
    return total


def pext(x: jax.Array, axis_name: str = AXIS_SHARD, *,
         take_max: bool = True):
    """Cross-shard elementwise extreme (exact for any association)."""
    return (jax.lax.pmax if take_max else jax.lax.pmin)(x, axis_name)


class LocalFoldCtx:
    """Cross-shard hooks for blocked exact folds. This single-device
    instance is the identity; ShardFoldCtx recombines with collectives.
    Both fold the SAME per-block partials in the SAME left-fold order,
    so sharded and unsharded results agree bit-for-bit."""

    shards = 1

    def sid_base(self, s_local: int):
        return jnp.int32(0)

    def gather(self, partial):
        return partial

    def fold_blocks(self, partial):
        """Gather the per-shard partial blocks and run the canonical
        unrolled left fold — THE cross-shard sum seam. The kernel path
        (parallel/kernels/ring_fold.RingFoldCtx) overrides this with
        the sequential ring, preserving the same fold order."""
        return left_fold_sum(self.gather(partial))

    def pext(self, x, take_max: bool):
        return x

    def psum(self, x):
        return x


class ShardFoldCtx(LocalFoldCtx):
    """Collective fold hooks for code running INSIDE shard_map."""

    def __init__(self, shards: int):
        self.shards = shards

    def sid_base(self, s_local: int):
        return jax.lax.axis_index(AXIS_SHARD) * jnp.int32(s_local)

    def gather(self, partial):
        return gather_blocks(partial)

    def pext(self, x, take_max: bool):
        return pext(x, take_max=take_max)

    def psum(self, x):
        return jax.lax.psum(x, AXIS_SHARD)


def shard_rows_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for row-oriented scan outputs: rows split over AXIS_SHARD."""
    return NamedSharding(mesh, P(AXIS_SHARD))


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (S, T) grids: series over AXIS_SHARD, time over
    AXIS_TIME."""
    return NamedSharding(mesh, P(AXIS_SHARD, AXIS_TIME))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
