"""Distributed query kernels: shard_map programs with explicit collectives.

The three distribution patterns of the reference, re-expressed over ICI
(SURVEY.md §2.7 mapping):

1. dist_segment_agg  — commutative aggregate push-down + merge: each series
   shard computes full-width partial aggregates, psum/pmin/pmax recombines
   (replaces MergeScanExec + frontend final-aggregate).
2. halo_exchange     — ring transfer of window-tail cells between adjacent
   time shards (replaces PartitionRange overlap handling; the sequence-
   parallel primitive for windows crossing block boundaries).
3. dist_topk         — per-shard top-k, all_gather, re-select (replaces
   frontend sort+limit over gathered partials).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from greptimedb_tpu.ops import segment as S
from greptimedb_tpu.parallel.mesh import AXIS_SHARD, AXIS_TIME


def dist_segment_agg(mesh: Mesh, op: str, num_segments: int):
    """Build a shard_map'd segmented aggregate: rows sharded over AXIS_SHARD,
    output replicated. op in {sum, count, min, max, mean}."""

    def local(values, seg, mask):
        if op == "sum":
            part = S.seg_sum(values, seg, mask, num_segments)
            return jax.lax.psum(part, AXIS_SHARD)
        if op == "count":
            part = S.seg_count(seg, mask, num_segments)
            return jax.lax.psum(part, AXIS_SHARD)
        if op == "min":
            part = S.seg_min(values, seg, mask, num_segments)
            return jax.lax.pmin(part, AXIS_SHARD)
        if op == "max":
            part = S.seg_max(values, seg, mask, num_segments)
            return jax.lax.pmax(part, AXIS_SHARD)
        if op == "mean":
            s = jax.lax.psum(S.seg_sum(values, seg, mask, num_segments),
                             AXIS_SHARD)
            c = jax.lax.psum(S.seg_count(seg, mask, num_segments), AXIS_SHARD)
            return s / jnp.maximum(c, 1).astype(s.dtype)
        raise ValueError(op)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(AXIS_SHARD), P(AXIS_SHARD), P(AXIS_SHARD)),
        out_specs=P(),
        check_rep=False,
    )


def halo_exchange_prev(x: jax.Array, halo: int, axis_name: str = AXIS_TIME):
    """Prepend the last `halo` cells of the previous time shard (zeros for
    the first shard). x is the local (S, T_local) block inside shard_map;
    returns (S, halo + T_local)."""
    # jax.lax.axis_size was removed from current JAX; psum of a python
    # literal folds to the static axis size inside shard_map
    n = jax.lax.psum(1, axis_name)
    tail = x[:, -halo:]
    # ring shift: device i receives from i-1
    perm = [(i, (i + 1) % n) for i in range(n)]
    prev_tail = jax.lax.ppermute(tail, axis_name, perm)
    idx = jax.lax.axis_index(axis_name)
    prev_tail = jnp.where(idx == 0, jnp.zeros_like(prev_tail), prev_tail)
    return jnp.concatenate([prev_tail, x], axis=1)


def dist_topk(mesh: Mesh, k: int, *, largest: bool = True):
    """Distributed top-k over a sharded 1-D value array: local top-k,
    all_gather the candidates, re-select. Returns (values, global_indices)."""

    def local(values, mask):
        n_local = values.shape[0]
        fill = jnp.asarray(-jnp.inf if largest else jnp.inf, values.dtype)
        v = jnp.where(mask, values, fill)
        vv = v if largest else -v
        loc_v, loc_i = jax.lax.top_k(vv, min(k, n_local))
        shard = jax.lax.axis_index(AXIS_SHARD)
        glob_i = loc_i + shard * n_local
        all_v = jax.lax.all_gather(loc_v, AXIS_SHARD).reshape(-1)
        all_i = jax.lax.all_gather(glob_i, AXIS_SHARD).reshape(-1)
        top_v, sel = jax.lax.top_k(all_v, k)
        if not largest:
            top_v = -top_v
        return top_v, all_i[sel]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(AXIS_SHARD), P(AXIS_SHARD)),
        out_specs=(P(), P()),
        check_rep=False,
    )


def shard_rows_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for row-oriented scan outputs: rows split over AXIS_SHARD."""
    return NamedSharding(mesh, P(AXIS_SHARD))


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (S, T) grids: series over AXIS_SHARD, time over
    AXIS_TIME."""
    return NamedSharding(mesh, P(AXIS_SHARD, AXIS_TIME))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
