"""Hash-groupby shuffle: the blocked cross-shard fold as a ring kernel.

The XLA path (parallel/dist.ShardFoldCtx) all-gathers every shard's
(fb_local, g, nb) partial blocks to every shard and left-folds the
gathered (FOLD_BLOCKS, g, nb) tensor — (ns-1) * fb_local * g * nb
elements received per device. The ring path moves only the (g, nb)
accumulator: shard 0 folds its local blocks, the accumulator walks the
ring while each shard folds its blocks on top in shard order, and the
total walks once more so every shard ends with it — 2(ns-1) hops of
g * nb elements. The fold bodies are Pallas kernels (the same
unrolled static add chain as dist.left_fold_sum, so the bit-identity
contract across mesh 1/2/4/8 is preserved by construction); the hops
are ppermute (ICI collective-permute) in the interpret twin and
in-kernel async remote copies on the native TPU backend.
"""

from __future__ import annotations

import functools

from greptimedb_tpu.parallel.dist import ShardFoldCtx
from greptimedb_tpu.parallel.kernels.base import (
    interpret_mode,
    native_available,
    ring_comm_bytes,
    sequential_ring,
)


# ----------------------------------------------------------------------
# kernel bodies (shared by the interpret twin and the native variants)
# ----------------------------------------------------------------------

def _fold_seed_kernel(blocks_ref, out_ref):
    """Left fold of the local partial blocks. The accumulator STARTS at
    blocks[0] — never zeros + add: x + 0.0 maps -0.0 to +0.0, which
    would break bit-identity against dist.left_fold_sum."""
    acc = blocks_ref[0]
    for i in range(1, blocks_ref.shape[0]):
        acc = acc + blocks_ref[i]
    out_ref[...] = acc


def _fold_cont_kernel(acc_ref, blocks_ref, out_ref):
    """Continue the left fold: the ring accumulator (the prefix of all
    earlier shards' blocks) plus the local blocks, in block order."""
    acc = acc_ref[...]
    for i in range(blocks_ref.shape[0]):
        acc = acc + blocks_ref[i]
    out_ref[...] = acc


def _ext_max_kernel(a_ref, b_ref, out_ref):
    import jax.numpy as jnp

    out_ref[...] = jnp.maximum(a_ref[...], b_ref[...])


def _ext_min_kernel(a_ref, b_ref, out_ref):
    import jax.numpy as jnp

    out_ref[...] = jnp.minimum(a_ref[...], b_ref[...])


def _add_kernel(a_ref, b_ref, out_ref):
    out_ref[...] = a_ref[...] + b_ref[...]


def _call1(kernel, a, *, interpret):
    import jax
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
        interpret=interpret,
    )(a)


def _call2(kernel, a, b, *, interpret):
    import jax
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=interpret,
    )(a, b)


# ----------------------------------------------------------------------
# ring programs (called from inside shard_map bodies)
# ----------------------------------------------------------------------

def ring_fold_blocks(parts, ns: int, *, interpret: bool):
    """parts: the local (fb_local, g, nb) partial blocks of one shard.
    Returns the (g, nb) global fold, identical on every shard and
    bit-identical to dist.left_fold_sum(dist.gather_blocks(parts))."""
    if not interpret and native_available():
        return _tpu_ring_fold(parts, ns)
    seed = _call1(_fold_seed_kernel, parts, interpret=interpret)

    def cont(acc):
        return _call2(_fold_cont_kernel, acc, parts, interpret=interpret)

    return sequential_ring(seed, cont, ns)


def ring_pext(x, ns: int, *, take_max: bool, interpret: bool):
    """Cross-shard elementwise extreme around the ring. min/max are
    exactly associative, so the sequential order matches pmin/pmax
    bit-for-bit (NaN propagates through jnp.minimum/maximum exactly as
    through the XLA all-reduce)."""
    kernel = _ext_max_kernel if take_max else _ext_min_kernel

    def comb(acc):
        return _call2(kernel, acc, x, interpret=interpret)

    return sequential_ring(x, comb, ns)


def ring_psum_onehot(x, ns: int, *, interpret: bool):
    """Cross-shard sum around the ring for MASKED ONE-NONZERO payloads
    (the staged first/last winner extraction: per element, exactly one
    shard contributes the winner value, every other shard contributes
    +0.0). x + 0.0 is exact for every x except -0.0 -> +0.0 — and the
    psum path normalizes -0.0 the same way — so the sequential order is
    bit-identical to jax.lax.psum for this payload shape. NOT exact for
    general summands; those go through ring_fold_blocks."""

    def comb(acc):
        return _call2(_add_kernel, acc, x, interpret=interpret)

    return sequential_ring(x, comb, ns)


def fold_comm_bytes(ns: int, g: int, nb: int, passes: int = 1) -> int:
    """Declared inter-chip traffic of `passes` ring passes over a
    (g, nb) f32 accumulator."""
    return ring_comm_bytes(ns, 4 * int(g) * int(nb)) * max(int(passes), 1)


# ----------------------------------------------------------------------
# native TPU variant: the whole ring in one kernel via async remote
# copies (SNIPPETS.md [2] / pallas guide ring pattern). Gated on the
# Mosaic backend — jax 0.4.x interpret mode cannot trace
# make_async_remote_copy, so the CPU twin above expresses the hops as
# ppermute around the same fold kernel bodies.
# ----------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def _tpu_ring_fold_call(ns: int, fb_local: int, g: int, nb: int,
                        axis_name: str):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(parts_ref, out_ref, acc_ref, send_sem, recv_sem):
        my = jax.lax.axis_index(axis_name)
        right = jax.lax.rem(my + 1, ns)
        left = jax.lax.rem(my + ns - 1, ns)
        # neighbor barrier: both sides of each link must arrive before
        # any RDMA lands in the double buffer
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        pltpu.semaphore_wait(barrier, 2)
        # seed: local left fold (same body as _fold_seed_kernel)
        acc = parts_ref[0]
        for i in range(1, fb_local):
            acc = acc + parts_ref[i]
        acc_ref[0] = acc
        out_ref[...] = acc  # placeholder; every shard latches below
        for step in range(2 * ns - 2):
            send_slot = step % 2
            recv_slot = (step + 1) % 2
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[send_slot],
                dst_ref=acc_ref.at[recv_slot],
                send_sem=send_sem.at[send_slot],
                recv_sem=recv_sem.at[recv_slot],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            if step < ns - 1:
                # fold phase: the shard whose turn it is continues the
                # left fold; everyone else forwards what arrived
                cont = acc_ref[recv_slot]
                for i in range(fb_local):
                    cont = cont + parts_ref[i]
                turn = my == step + 1
                acc_ref[recv_slot] = jnp.where(
                    turn, cont, acc_ref[recv_slot]
                )
                if step == ns - 2:
                    # the last fold turn (shard ns-1) holds the total
                    out_ref[...] = jnp.where(
                        turn, acc_ref[recv_slot], out_ref[...]
                    )
            else:
                # broadcast phase: the total forwards around the ring,
                # each shard latching it as it passes by
                out_ref[...] = jnp.where(
                    my == step - (ns - 1), acc_ref[recv_slot],
                    out_ref[...],
                )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((g, nb), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, g, nb), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
        interpret=interpret_mode(),
    )


def _tpu_ring_fold(parts, ns: int):
    from greptimedb_tpu.parallel.mesh import AXIS_SHARD

    fb_local, g, nb = parts.shape
    return _tpu_ring_fold_call(ns, fb_local, g, nb, AXIS_SHARD)(parts)


# ----------------------------------------------------------------------
# the fold ctx the sharded query programs thread through _range_body
# ----------------------------------------------------------------------

class RingFoldCtx(ShardFoldCtx):
    """Kernel-path twin of dist.ShardFoldCtx: the same hooks the
    sharded query bodies thread (query/device_range._range_body,
    query/reduce._sharded_fused_program), with the ring kernels behind
    them. Each hook is bit-identical to its collective counterpart for
    the payload shapes those bodies produce (see the ring_* docstrings
    for the exactness argument per hook)."""

    def __init__(self, shards: int, *, interpret: bool | None = None):
        super().__init__(shards)
        self._interp = interpret_mode() if interpret is None else interpret

    def fold_blocks(self, partial):
        return ring_fold_blocks(partial, self.shards,
                                interpret=self._interp)

    def pext(self, x, take_max: bool):
        return ring_pext(x, self.shards, take_max=take_max,
                         interpret=self._interp)

    def psum(self, x):
        return ring_psum_onehot(x, self.shards, interpret=self._interp)
