"""Pallas TPU kernels for the shuffle- and merge-bound paths.

The device profiler's roofline verdicts (telemetry/device_programs)
say the cross-shard paths are communication-bound, not compute-bound:
the sharded query path moves cross-shard state through `gather_blocks`
+ host-ordered folds, and the compaction device merge computes only a
permutation on device and gathers every value column on the host. The
kernels here keep that state where the reduction runs:

- ring_fold    — hash-groupby shuffle: the blocked cross-shard group
  fold as a sequential ring (2(ns-1) neighbor hops of the (g, nb)
  accumulator) instead of an all_gather of every shard's partial
  blocks, folding in the canonical FOLD_BLOCKS left-fold order so the
  bit-identity contract across mesh 1/2/4/8 holds by construction.
- topk_merge   — distributed topk: per-shard candidate heaps merged
  pairwise around the ring by a merge-path k-selection kernel instead
  of all-gathering ns*k candidates to every shard.
- merge_gather — compaction fused merge-gather: the lexsort
  permutation/keep-mask/fill indices applied to uint32-packed value
  planes ON DEVICE, so compacted values cross the tunnel exactly once
  (readback = output columns only).

Kernel selection is planner-driven (query/planner.decide_kernel — the
`kernel=pallas|xla` dimension of decide_mesh_execution) and every
kernel ships an interpret-mode twin (`pl.pallas_call(interpret=True)`)
so tier-1 under JAX_PLATFORMS=cpu exercises the real kernel bodies and
the mesh-parity fuzz asserts bit-identity against the XLA path.
"""

from greptimedb_tpu.parallel.kernels.base import (
    interpret_mode,
    kernel_mode,
    kernels_enabled,
    native_available,
    ring_comm_bytes,
    sequential_ring,
)
from greptimedb_tpu.parallel.kernels.ring_fold import RingFoldCtx
from greptimedb_tpu.parallel.kernels.topk_merge import (
    ring_topk_merge,
    topk_comm_bytes,
)
from greptimedb_tpu.parallel.kernels import merge_gather

__all__ = [
    "RingFoldCtx",
    "interpret_mode",
    "kernel_mode",
    "kernels_enabled",
    "merge_gather",
    "native_available",
    "ring_comm_bytes",
    "ring_topk_merge",
    "sequential_ring",
    "topk_comm_bytes",
]
