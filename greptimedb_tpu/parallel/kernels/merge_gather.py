"""Fused compaction merge-gather: value columns reordered on device.

The classic compaction path (storage/device_merge) computes the lexsort
permutation / keep mask / backfill indices on device, reads *all three*
back, and then gathers every value column on the host — each input run
crosses the tunnel twice (once up as sort keys never, but every value
column comes back whole). The fused path keeps the permutation on
device: value columns are packed into uint32 bit planes (bit-exact for
every fixed-width dtype), a Pallas gather kernel applies the
device-resident source indices, and only the gathered output planes are
read back — readback == output bytes, regression-pinned.

Plane packing is pure bit movement (numpy views + zero-extension),
never value conversion, so reassembled columns are byte-identical to
the host gather for every payload including NaN bit patterns and -0.0.
Object/string columns have no fixed-width plane form and take the
classic host-gather path — the documented exception to the fused
readback contract.
"""

from __future__ import annotations

import functools

import numpy as np

from greptimedb_tpu.parallel.kernels.base import native_available


# ----------------------------------------------------------------------
# uint32 plane codecs (host side, bit-exact by construction)
# ----------------------------------------------------------------------

def packable(dtype) -> bool:
    """True when the dtype has a fixed-width uint32 plane form."""
    dt = np.dtype(dtype)
    return dt.kind in "biufmM" and dt.itemsize in (1, 2, 4, 8)


def _unsigned_twin(dt: np.dtype) -> np.dtype:
    """The same-width unsigned dtype a column is viewed through before
    zero-extension (views reinterpret bits; astype would convert)."""
    return np.dtype(f"u{dt.itemsize}")


def pack_planes(col: np.ndarray) -> np.ndarray:
    """Pack a 1-D fixed-width column into a (P, n) uint32 plane matrix:
    8-byte dtypes view as little-endian lo/hi uint32 pairs (P=2), 4-byte
    dtypes view directly (P=1), narrower dtypes view through their
    unsigned twin and zero-extend (P=1)."""
    dt = col.dtype
    col = np.ascontiguousarray(col)
    if dt.itemsize == 8:
        flat = col.view(np.uint32)
        return np.stack([flat[0::2], flat[1::2]])
    if dt.itemsize == 4:
        return col.view(np.uint32)[None, :]
    return col.view(_unsigned_twin(dt)).astype(np.uint32)[None, :]


def unpack_planes(planes: np.ndarray, dtype, n: int) -> np.ndarray:
    """Invert pack_planes: (P, >=n) uint32 planes back to a length-n
    column of `dtype`, byte-identical to the original rows."""
    dt = np.dtype(dtype)
    planes = np.asarray(planes, dtype=np.uint32)[:, :n]
    if dt.itemsize == 8:
        pair = np.empty(2 * n, dtype=np.uint32)
        pair[0::2] = planes[0]
        pair[1::2] = planes[1]
        return pair.view(dt)
    if dt.itemsize == 4:
        return np.ascontiguousarray(planes[0]).view(dt)
    narrow = planes[0].astype(_unsigned_twin(dt))
    return narrow.view(dt)


def plane_count(dtype) -> int:
    return 2 if np.dtype(dtype).itemsize == 8 else 1


def planes_bytes(p: int, n: int) -> int:
    """Readback size of a gathered (P, n) uint32 plane matrix."""
    return 4 * int(p) * int(n)


# ----------------------------------------------------------------------
# gather kernels
# ----------------------------------------------------------------------

def _take_kernel(planes_ref, src_ref, out_ref):
    """Interpret twin: whole-block gather along the row axis."""
    import jax.numpy as jnp

    out_ref[...] = jnp.take(planes_ref[...], src_ref[...], axis=1)


def _prefetch_gather_kernel(idx_ref, planes_ref, out_ref):
    """Native body: the scalar-prefetched index map already steered this
    grid step's (P, 1) input block to column idx[j]; copy it out."""
    del idx_ref
    out_ref[...] = planes_ref[...]


def gather_planes(planes, src, *, interpret: bool):
    """Apply device-resident source indices to a (P, n) uint32 plane
    matrix, producing the (P, n_out) gathered planes. `src` is int32 —
    the composed order/keep/fill permutation from the merge program.
    Traceable (call under jit / device_call)."""
    import jax
    from jax.experimental import pallas as pl

    p, _ = planes.shape
    n_out = src.shape[0]
    out_shape = jax.ShapeDtypeStruct((p, n_out), planes.dtype)
    if not interpret and native_available():
        from jax.experimental.pallas import tpu as pltpu

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_out,),
            in_specs=[pl.BlockSpec((p, 1), lambda j, idx: (0, idx[j]))],
            out_specs=pl.BlockSpec((p, 1), lambda j, idx: (0, j)),
        )
        # contract: the (P, 1) blocks are DELIBERATELY one lane wide —
        # the prefetched index picks one source column per grid step,
        # so a 128-lane block would gather 128 contiguous columns the
        # permutation does not have. Mosaic pads the lane dim; the
        # relayout cost is the price of a data-dependent gather and is
        # covered by the planner's size gate (decide_kernel).
        return pl.pallas_call(
            _prefetch_gather_kernel,
            grid_spec=grid_spec,  # gtlint: disable=GT023
            out_shape=out_shape,
            interpret=interpret,
        )(src, planes)
    return pl.pallas_call(
        _take_kernel,
        out_shape=out_shape,
        interpret=interpret,
    )(planes, src)


@functools.lru_cache(maxsize=32)
def gather_program(p: int, n: int, n_out: int, interpret: bool):
    """jit-compiled gather for a (P, n) plane matrix and n_out target
    rows, cached per shape (compaction buckets repeat heavily)."""
    import jax

    def run(planes, src):
        return gather_planes(planes, src, interpret=interpret)

    return jax.jit(run)
