"""Distributed topk: per-shard candidate heaps merged around the ring.

The XLA path all-gathers every shard's (J, kl) candidate planes to
every shard and reselects with top_k over the ns*kl concatenation. The
kernel path keeps candidates where they were selected: the accumulated
top-k walks the ring (2(ns-1) hops of (J, k) planes) and each shard
merges its local candidates on top with a merge-path k-selection
kernel. Tie-breaks favor the accumulator — i.e. the earlier shard,
i.e. the lower concatenation position — which is exactly
jax.lax.top_k's documented lower-index-wins rule over the shard-order
concatenation, so the selected winners (values, indices, presence)
match the all-gather path exactly.

Candidate planes are gathered through the merge positions as int32
bitcasts (jax.lax.bitcast_convert_type), never float arithmetic: a
one-hot float multiply would turn -inf * 0 into NaN, and a float
where+sum would normalize -0.0 — int selection is exact for every
payload including NaN bit patterns.
"""

from __future__ import annotations

from greptimedb_tpu.parallel.kernels.base import (
    ring_comm_bytes,
    sequential_ring,
)


def _merge_topk_kernel(a_key_ref, a_val_ref, a_idx_ref, a_pres_ref,
                       b_key_ref, b_val_ref, b_idx_ref, b_pres_ref,
                       o_key_ref, o_val_ref, o_idx_ref, o_pres_ref):
    """Stable merge of two descending candidate lists, truncated to the
    accumulator width. Merge-path ranks: a[i] lands at i + #(b > a[i]),
    b[j] at j + #(a >= b[j]) — `>=` gives equal keys to the
    accumulator, making the merge the stable order of the shard-order
    concatenation."""
    import jax
    import jax.numpy as jnp

    a_key = a_key_ref[...]                      # (J, kk) desc
    b_key = b_key_ref[...]                      # (J, kl) desc
    kk = a_key.shape[1]
    kl = b_key.shape[1]
    iota_a = jnp.arange(kk, dtype=jnp.int32)
    iota_b = jnp.arange(kl, dtype=jnp.int32)
    gt = b_key[:, None, :] > a_key[:, :, None]  # (J, kk, kl)
    # dtype pinned on every sum: under jax_enable_x64 an unpinned int32
    # sum widens to int64, which would break the int32 bitcast selects
    pos_a = iota_a[None, :] + jnp.sum(gt, axis=2, dtype=jnp.int32)
    ge = a_key[:, :, None] >= b_key[:, None, :]
    pos_b = iota_b[None, :] + jnp.sum(ge, axis=1, dtype=jnp.int32)
    slots = jnp.arange(kk, dtype=jnp.int32)

    def place(plane_a, plane_b):
        # each output slot < kk receives exactly one source element
        # (pos_a/pos_b enumerate the merged order); slots past kk fall
        # off the one-hot and are dropped
        hit_a = pos_a[:, :, None] == slots[None, None, :]
        hit_b = pos_b[:, :, None] == slots[None, None, :]
        zero = jnp.zeros((), jnp.int32)
        return (
            jnp.sum(jnp.where(hit_a, plane_a[:, :, None], zero),
                    axis=1, dtype=jnp.int32)
            + jnp.sum(jnp.where(hit_b, plane_b[:, :, None], zero),
                      axis=1, dtype=jnp.int32)
        )

    bits = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731
    f32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.float32)  # noqa: E731
    o_key_ref[...] = f32(place(bits(a_key), bits(b_key)))
    o_val_ref[...] = f32(place(bits(a_val_ref[...]), bits(b_val_ref[...])))
    o_idx_ref[...] = place(a_idx_ref[...], b_idx_ref[...])
    o_pres_ref[...] = place(
        a_pres_ref[...].astype(jnp.int32), b_pres_ref[...].astype(jnp.int32)
    ) > 0


def merge_candidates(acc, loc, *, interpret: bool):
    """One merge hop: acc/loc = (key, val, idx, pres) plane tuples of
    shapes (J, kk)/(J, kl); returns the merged (J, kk) planes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ak, av, ai, ap = acc
    bk, bv, bi, bp = loc
    out = pl.pallas_call(
        _merge_topk_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(ak.shape, jnp.float32),
            jax.ShapeDtypeStruct(ak.shape, jnp.float32),
            jax.ShapeDtypeStruct(ak.shape, jnp.int32),
            jax.ShapeDtypeStruct(ak.shape, jnp.bool_),
        ],
        interpret=interpret,
    )(ak, av, ai, ap, bk, bv, bi, bp)
    return tuple(out)


_IDX_SENTINEL = 2**31 - 1


def ring_topk_merge(l_key, l_val, l_idx, l_pres, *, k: int, ns: int,
                    interpret: bool):
    """Ring-merge per-shard candidate planes (J, kl) into the global
    (J, k) winners, identical on every shard and bit-identical (in the
    present slots) to top_k over the shard-order all_gather. kl may be
    below k (fewer local series than k): the seed pads with -inf keys /
    absent presence, which only ever tie with other absent candidates
    and are dropped by the caller's isfinite(key) presence check."""
    import jax.numpy as jnp

    j = l_key.shape[0]
    kl = l_key.shape[1]
    local = (l_key.astype(jnp.float32), l_val.astype(jnp.float32),
             l_idx.astype(jnp.int32), l_pres)
    if kl < k:
        pad = k - kl

        def ext(x, fill):
            return jnp.concatenate(
                [x, jnp.full((j, pad), fill, x.dtype)], axis=1
            )

        seed = (ext(local[0], -jnp.inf), ext(local[1], 0.0),
                ext(local[2], _IDX_SENTINEL), ext(local[3], False))
    else:
        seed = local

    def comb(acc):
        return merge_candidates(acc, local, interpret=interpret)

    return sequential_ring(seed, comb, ns)


def topk_comm_bytes(ns: int, j: int, k: int) -> int:
    """Declared inter-chip traffic of one topk ring: (J, k) key/val/idx
    f32+f32+int32 planes plus the bool presence plane, 2(ns-1) hops."""
    return ring_comm_bytes(ns, (4 + 4 + 4 + 1) * int(j) * int(k))
