"""Kernel availability, interpret-mode threading, and the ring harness.

jax imports stay inside functions: the storage layer reaches this
module through the fused compaction merge and must remain importable
in processes without a device runtime.
"""

from __future__ import annotations


def native_available() -> bool:
    """True when the Mosaic TPU compiler is behind pallas_call — the
    async-remote-copy kernel variants only lower there."""
    import jax

    return jax.default_backend() == "tpu"


def kernel_mode(opts) -> str:
    """The `[mesh] pallas_kernels` knob value ("auto"|"on"|"off") of a
    MeshOptions (or anything shaped like one; None -> "auto")."""
    mode = getattr(opts, "pallas_kernels", "auto") if opts is not None \
        else "auto"
    return mode if mode in ("auto", "on", "off") else "auto"


def kernels_enabled(opts) -> bool:
    """Should kernel program variants be considered at all? auto =
    native TPU backend only; on = everywhere, riding interpret mode off
    TPU (tests, the parity fuzz, CPU bench); off = never."""
    mode = kernel_mode(opts)
    if mode == "off":
        return False
    if mode == "on":
        return True
    return native_available()


def interpret_mode() -> bool:
    """`interpret=` value for every pallas_call in this package,
    threaded from the mesh config via the planner decision (gtlint
    GT022 rejects hard-coded literals): interpret exactly when the
    backend has no Mosaic compiler, so CPU tier-1 runs the real kernel
    bodies under the Pallas interpreter."""
    return not native_available()


def ring_comm_bytes(ns: int, plane_bytes: int) -> int:
    """Estimated inter-chip bytes of one sequential ring pass: the
    accumulator (plane_bytes) crosses 2(ns-1) neighbor hops — (ns-1)
    for the fold phase, (ns-1) for the latch broadcast."""
    return max(0, 2 * (int(ns) - 1)) * int(plane_bytes)


def sequential_ring(local, combine, ns: int, axis_name: str | None = None):
    """Sequential reduce-then-broadcast ring over `ns` shards.

    `local` (a pytree of per-shard arrays) is shard 0's seed
    accumulator; at hop s the accumulator moves to the right neighbor
    and shard s latches `combine(acc)` (its local contribution folded
    onto the prefix of shards 0..s-1). After ns-1 hops shard ns-1
    holds the total; ns-1 more hops broadcast it, each shard latching
    the value the moment it passes by. The combine order is therefore
    EXACTLY shard 0..ns-1 sequential — the same left fold the
    gather_blocks + left_fold_sum path runs — so results are
    bit-identical to the all-gather path by construction, while only
    2(ns-1) accumulator-sized messages cross the interconnect instead
    of (ns-1) full partial sets per shard.

    The latches are jnp.where selects (no arithmetic — a select never
    flips -0.0 or perturbs NaN payloads). ppermute is the hop
    primitive: on TPU it lowers to the ICI collective-permute (an
    async remote copy between neighbors); the in-kernel
    make_async_remote_copy variant lives in ring_fold and is gated on
    the native backend because interpret mode cannot express remote
    DMAs.
    """
    import jax
    import jax.numpy as jnp

    if axis_name is None:
        from greptimedb_tpu.parallel.mesh import AXIS_SHARD

        axis_name = AXIS_SHARD
    tree = jax.tree_util.tree_map
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % ns) for i in range(ns)]

    def hop(t):
        return tree(lambda a: jax.lax.ppermute(a, axis_name, perm), t)

    def latch(cond, new, old):
        return tree(lambda a, b: jnp.where(cond, a, b), new, old)

    acc = local
    for s in range(1, ns):
        acc = hop(acc)
        acc = latch(my == s, combine(acc), acc)
    result = latch(my == ns - 1, acc, tree(jnp.zeros_like, acc))
    for t in range(ns - 1):
        acc = hop(acc)
        result = latch(my == t, acc, result)
    return result
