"""Mesh, shardings and collective kernels — the TPU-native distributed
communication backend (SURVEY.md §2.7).

The reference scales out with region fan-out over Arrow Flight and merges
partial results at the frontend (MergeScanExec,
/root/reference/src/query/src/dist_plan/merge_scan.rs). Here the same roles
map onto a jax.sharding.Mesh:

- 'shard' axis: series/tag-space sharding — the analog of table regions
  placed on datanodes (data parallel over the series axis).
- 'time' axis: time-block sharding — the analog of PartitionRange splitting
  (sequence parallel over the time axis, with ring halo exchange for
  windows that cross block boundaries).

Partial per-shard aggregates recombine with psum/pmin/pmax over ICI instead
of Flight gather; cross-slice/host traffic stays on the RPC plane
(cluster/rpc.py).
"""

from greptimedb_tpu.parallel.mesh import AXIS_SHARD, AXIS_TIME, make_mesh

__all__ = ["AXIS_SHARD", "AXIS_TIME", "make_mesh"]
