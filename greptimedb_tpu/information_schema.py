"""information_schema virtual tables.

Counterpart of /root/reference/src/catalog/src/system_schema/
information_schema/: tables, columns, region_statistics, flows — generated
on demand from the catalog, then run through the normal query planner so
WHERE/ORDER BY/aggregates work on them.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.errors import TableNotFoundError
from greptimedb_tpu.query.executor import Col, DictSource, QueryResult
from greptimedb_tpu.query.expr import eval_expr
from greptimedb_tpu.query.planner import item_name, plan_select
from greptimedb_tpu.sql import ast as A


def _tables_doc(inst) -> dict[str, list]:
    rows = {
        "table_catalog": [], "table_schema": [], "table_name": [],
        "table_type": [], "table_id": [], "engine": [], "region_count": [],
    }
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(db)
            rows["table_name"].append(name)
            rows["table_type"].append("BASE TABLE")
            rows["table_id"].append(t.info.table_id)
            rows["engine"].append(t.info.engine)
            rows["region_count"].append(t.info.num_regions)
    return rows


def _columns_doc(inst) -> dict[str, list]:
    rows = {
        "table_catalog": [], "table_schema": [], "table_name": [],
        "column_name": [], "data_type": [], "semantic_type": [],
        "is_nullable": [],
    }
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            for c in t.schema.columns:
                rows["table_catalog"].append("greptime")
                rows["table_schema"].append(db)
                rows["table_name"].append(name)
                rows["column_name"].append(c.name)
                rows["data_type"].append(c.data_type.name)
                rows["semantic_type"].append(
                    "TIMESTAMP" if c.is_time_index
                    else ("TAG" if c.is_tag else "FIELD")
                )
                rows["is_nullable"].append("Yes" if c.nullable else "No")
    return rows


def _region_statistics_doc(inst) -> dict[str, list]:
    rows = {
        "region_id": [], "table_id": [], "region_rows": [],
        "memtable_size": [], "sst_size": [], "sst_num": [],
    }
    for t in inst.catalog.all_tables():
        for r in t.regions:
            rows["region_id"].append(r.meta.region_id)
            rows["table_id"].append(t.info.table_id)
            rows["region_rows"].append(
                r.memtable.rows + sum(m.rows for m in r.manifest.state.ssts)
            )
            rows["memtable_size"].append(r.memtable.bytes)
            rows["sst_size"].append(
                sum(m.size_bytes for m in r.manifest.state.ssts)
            )
            rows["sst_num"].append(len(r.manifest.state.ssts))
    return rows


def _schemata_doc(inst) -> dict[str, list]:
    names = inst.catalog.database_names()
    return {
        "catalog_name": ["greptime"] * len(names),
        "schema_name": names,
    }


def _flows_doc(inst) -> dict[str, list]:
    rows = {"flow_name": [], "source_table": [], "sink_table": [],
            "processed_rows": []}
    if inst.flows is not None:
        for f in inst.flows.flow_infos():
            rows["flow_name"].append(f["name"])
            rows["source_table"].append(f["source_table"])
            rows["sink_table"].append(f["sink_table"])
            rows["processed_rows"].append(f["processed_rows"])
    return rows


def _views_doc(inst) -> dict[str, list]:
    rows = {"table_catalog": [], "table_schema": [], "table_name": [],
            "view_definition": []}
    for db in inst.catalog.database_names():
        for name in inst.catalog.view_names(db):
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(db)
            rows["table_name"].append(name)
            rows["view_definition"].append(
                inst.catalog.maybe_view(db, name) or ""
            )
    return rows


def _key_column_usage_doc(inst) -> dict[str, list]:
    rows = {"constraint_catalog": [], "constraint_schema": [],
            "constraint_name": [], "table_catalog": [],
            "table_schema": [], "table_name": [], "column_name": [],
            "ordinal_position": []}
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            pos = {"PRIMARY": 0, "TIME INDEX": 0}  # 1-based PER constraint
            for c in t.schema.columns:
                if not (c.is_tag or c.is_time_index):
                    continue
                cname = "TIME INDEX" if c.is_time_index else "PRIMARY"
                pos[cname] += 1
                rows["constraint_catalog"].append("def")
                rows["constraint_schema"].append(db)
                rows["constraint_name"].append(cname)
                rows["table_catalog"].append("def")
                rows["table_schema"].append(db)
                rows["table_name"].append(name)
                rows["column_name"].append(c.name)
                rows["ordinal_position"].append(pos[cname])
    return rows


def _table_constraints_doc(inst) -> dict[str, list]:
    rows = {"constraint_catalog": [], "constraint_schema": [],
            "constraint_name": [], "table_schema": [], "table_name": [],
            "constraint_type": []}
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            for cname, ctype in (("TIME INDEX", "TIME INDEX"),
                                 ("PRIMARY", "PRIMARY KEY")):
                if cname == "PRIMARY" and not t.tag_names:
                    continue
                rows["constraint_catalog"].append("def")
                rows["constraint_schema"].append(db)
                rows["constraint_name"].append(cname)
                rows["table_schema"].append(db)
                rows["table_name"].append(name)
                rows["constraint_type"].append(ctype)
    return rows


def _partitions_doc(inst) -> dict[str, list]:
    rows = {"table_catalog": [], "table_schema": [], "table_name": [],
            "partition_name": [], "partition_expression": [],
            "greptime_partition_id": []}
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            rule = getattr(t, "partition_rule", None)
            exprs = rule.expr_texts if rule is not None else []
            for i, r in enumerate(t.regions):
                rows["table_catalog"].append("greptime")
                rows["table_schema"].append(db)
                rows["table_name"].append(name)
                rows["partition_name"].append(f"p{i}")
                rows["partition_expression"].append(
                    exprs[i] if i < len(exprs) else ""
                )
                rows["greptime_partition_id"].append(r.meta.region_id)
    return rows


def _region_peers_doc(inst) -> dict[str, list]:
    """Real routing + liveness per region: route/addr from the metasrv
    (dist) and status from the phi-accrual detector; local regions
    report their actual open/writable state — nothing is hardcoded."""
    rows = {"region_id": [], "table_id": [], "peer_id": [],
            "peer_addr": [], "is_leader": [], "status": []}
    routes: dict[int, int] = {}
    peers: dict[int, str] = {}
    statuses: dict[int, str] = {}
    meta = getattr(inst, "meta", None)
    if meta is not None and hasattr(meta, "routes"):
        try:
            routes = meta.routes()
            # ONE fleet-state round carries both the datanode addrs
            # and the phi verdicts (no separate peers() call)
            for n in meta.cluster().get("nodes") or []:
                statuses[n["node_id"]] = n["status"]
                if n.get("addr"):
                    peers[n["node_id"]] = n["addr"]
        except Exception as e:  # noqa: BLE001 - metasrv unreachable:
            # the table still answers with what the catalog knows
            import logging

            logging.getLogger("greptimedb_tpu.information_schema").debug(
                "region_peers metasrv lookup failed: %s", e
            )
    local_id = int(getattr(inst, "node_id", 0) or 0)
    local_addr = getattr(inst, "node_addr", "") or ""
    for t in inst.catalog.all_tables():
        for r in t.regions:
            rid = r.meta.region_id
            rows["region_id"].append(rid)
            rows["table_id"].append(t.info.table_id)
            if getattr(r, "remote", False):
                node = routes.get(rid, 0)
                rows["peer_id"].append(int(node))
                rows["peer_addr"].append(peers.get(node, ""))
                rows["is_leader"].append(
                    "Yes" if rid in routes else "No"
                )
                rows["status"].append(statuses.get(node, "UNKNOWN"))
            else:
                # locally-hosted region: this process is the peer, and
                # the region's own writability is its real state
                rows["peer_id"].append(local_id)
                rows["peer_addr"].append(local_addr)
                rows["is_leader"].append("Yes")
                rows["status"].append(
                    "ALIVE" if getattr(r, "writable", True)
                    else "DOWNGRADED"
                )
    return rows


def _runtime_metrics_doc(inst) -> dict[str, list]:
    from greptimedb_tpu.telemetry.metrics import global_registry

    rows = {"metric_name": [], "value": [], "labels": []}
    for line in global_registry.render().splitlines():
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        name, _, labels = head.partition("{")
        try:
            fval = float(val)
        except ValueError:
            continue
        rows["metric_name"].append(name)
        rows["value"].append(fval)
        rows["labels"].append(labels.rstrip("}"))
    return rows


def _cluster_info_doc(inst) -> dict[str, list]:
    """One row per fleet member from the metasrv peer book + heartbeat
    registry (dist) or the live local process (standalone): real
    addresses, real last-heartbeat activity, real phi-accrual status."""
    from greptimedb_tpu.dist import fleet
    from greptimedb_tpu.version import __version__

    rows = {"peer_id": [], "peer_type": [], "peer_addr": [],
            "version": [], "git_commit": [], "start_time_ms": [],
            "uptime_s": [], "active_time": [], "status": []}
    nodes = fleet.cluster_nodes(inst)
    standalone = (len(nodes) == 1
                  and nodes[0].get("role") == "standalone")
    for node in nodes:
        st = node.get("stats") or {}
        rows["peer_id"].append(int(node.get("node_id", 0)))
        rows["peer_type"].append(
            "STANDALONE" if standalone
            else str(node.get("role", "")).upper()
        )
        rows["peer_addr"].append(str(node.get("addr", "") or ""))
        rows["version"].append(str(st.get("version") or __version__))
        rows["git_commit"].append("")
        rows["start_time_ms"].append(int(st.get("start_ms", 0) or 0))
        rows["uptime_s"].append(float(st.get("uptime_s", 0.0) or 0.0))
        rows["active_time"].append(
            str(int(node.get("last_heartbeat_ms") or 0))
        )
        rows["status"].append(str(node.get("status", "UNKNOWN")))
    return rows


def _procedure_info_doc(inst) -> dict[str, list]:
    rows = {"procedure_id": [], "procedure_type": [], "status": [],
            "error": []}
    pm = getattr(inst, "procedure_manager", None)
    if pm is not None:
        for m in pm.list_procedures():
            rows["procedure_id"].append(m.proc_id)
            rows["procedure_type"].append(m.type_name)
            rows["status"].append(m.state)
            rows["error"].append(m.error or "")
    return rows


def _engines_doc(inst) -> dict[str, list]:
    names = ["tsdb", "metric", "file"]
    comments = [
        "TPU-native LSM time-series engine (mito analog)",
        "logical metric tables over the tsdb engine",
        "external tables over CSV/JSON/Parquet files",
    ]
    return {
        "engine": names,
        "support": ["DEFAULT", "YES", "YES"],
        "comment": comments,
        "transactions": ["NO"] * 3,
        "xa": ["NO"] * 3,
        "savepoints": ["NO"] * 3,
    }


def _build_info_doc(inst) -> dict[str, list]:
    from greptimedb_tpu.version import __version__

    return {
        "git_branch": [""], "git_commit": [""],
        "git_commit_short": [""], "git_clean": ["true"],
        "pkg_version": [__version__],
    }


def _character_sets_doc(inst) -> dict[str, list]:
    return {
        "character_set_name": ["utf8"],
        "default_collate_name": ["utf8_bin"],
        "description": ["UTF-8 Unicode"],
        "maxlen": [4],
    }


def _collations_doc(inst) -> dict[str, list]:
    return {
        "collation_name": ["utf8_bin"],
        "character_set_name": ["utf8"],
        "id": [1],
        "is_default": ["Yes"],
        "is_compiled": ["Yes"],
        "sortlen": [1],
    }


def _slow_queries_doc(inst) -> dict[str, list]:
    rows = {"cost_time_ms": [], "threshold_ms": [], "query": [],
            "schema_name": [], "channel": [], "timestamp": [],
            "trace_id": [], "fingerprint": []}
    log = getattr(inst, "slow_query_log", None)
    if log is not None:
        for e in log.entries():
            rows["cost_time_ms"].append(e["cost_ms"])
            rows["threshold_ms"].append(e["threshold_ms"])
            rows["query"].append(e["query"])
            rows["schema_name"].append(e["schema"])
            rows["channel"].append(e["channel"])
            rows["timestamp"].append(e["ts_ms"])
            rows["trace_id"].append(e.get("trace_id", ""))
            # joins the aggregate statement_statistics row for this
            # statement shape (see README "Statement statistics")
            rows["fingerprint"].append(e.get("fingerprint", ""))
    return rows


def _statement_statistics_doc(inst) -> dict[str, list]:
    """The process-wide statement-statistics registry
    (telemetry/stmt_stats.py), one row per (schema, fingerprint) —
    the pg_stat_statements face of the node. `last_trace_id` is an
    exemplar: join it against information_schema.traces (or
    /v1/traces?trace_id=) for one concrete execution of the shape."""
    import json as _json

    from greptimedb_tpu.telemetry.stmt_stats import global_stmt_stats

    cols = [
        "fingerprint", "schema_name", "tenant", "channel", "query",
        "calls", "errors", "errors_by_code", "rows_returned",
        "total_ms", "mean_ms", "p50_ms", "p99_ms", "queue_total_ms",
        "queue_p99_ms", "exec_path", "mesh_decision", "compile_count",
        "compile_cache_hits", "upload_bytes", "readback_full_bytes",
        "readback_delta_bytes", "session_hit_rate",
        "result_cache_hit_rate", "scan_cache_hit_rate", "shed_count",
        "deadline_count", "datanodes", "rpc_ms", "last_trace_id",
        "program_ids", "first_seen_ms", "last_seen_ms",
    ]
    rows: dict[str, list] = {c: [] for c in cols}
    for doc in global_stmt_stats.snapshot():
        for c in cols:
            v = doc.get(c)
            if c == "errors_by_code":
                v = _json.dumps(v or {})
            elif c == "program_ids":
                # joins information_schema.device_programs.program
                v = _json.dumps(v or [])
            rows[c].append(v)
    return rows


def _device_programs_doc(inst) -> dict[str, list]:
    """The process-wide device-program profiler
    (telemetry/device_programs.py), one row per compiled XLA program —
    the SQL face of /debug/prof/device. Consulting the table triggers
    the lazy XLA cost/memory analysis, so flops / roofline columns are
    populated for every analyzable program. `program` joins the
    statement_statistics `program_ids` column and the `program` attr
    on device.execute spans."""
    from greptimedb_tpu.telemetry.device_programs import global_programs

    cols = [
        "site", "program", "key", "calls", "errors", "compile_ms",
        "execute_ms_total", "execute_p50_ms", "execute_p99_ms",
        "device_ms_total", "upload_bytes", "readback_bytes",
        "collective", "comm_bytes",
        "dispatch_only", "analysis", "analysis_error", "flops",
        "bytes_accessed", "temp_bytes", "output_bytes",
        "argument_bytes", "aot_compile_ms", "achieved_gflops",
        "achieved_hbm_gbps", "bound", "pct_of_peak", "first_seen_ms",
        "last_seen_ms",
    ]
    rows: dict[str, list] = {c: [] for c in cols}
    for doc in global_programs.snapshot():
        for c in cols:
            v = doc.get(c)
            if c == "dispatch_only":
                v = 1 if v else 0
            rows[c].append(v)
    return rows


def _traces_doc(inst) -> dict[str, list]:
    """The in-memory trace ring, one row per span (the SQL-queryable
    face of /v1/traces: `SELECT * FROM information_schema.traces WHERE
    trace_id = ...` renders the same stitched spans)."""
    import json as _json

    from greptimedb_tpu.telemetry.tracing import global_traces

    rows = {"trace_id": [], "span_id": [], "parent_span_id": [],
            "span_name": [], "start_timestamp": [], "duration_ms": [],
            "attributes": []}
    for tr in global_traces.traces(limit=global_traces.cap or 256):
        for s in tr["spans"]:
            rows["trace_id"].append(tr["trace_id"])
            rows["span_id"].append(s["span_id"])
            rows["parent_span_id"].append(s["parent_id"] or "")
            rows["span_name"].append(s["name"])
            rows["start_timestamp"].append(int(s["start_ms"]))
            rows["duration_ms"].append(
                -1.0 if s["duration_ms"] is None else s["duration_ms"]
            )
            rows["attributes"].append(_json.dumps(s["attributes"]))
    return rows


def _memory_pools_doc(inst) -> dict[str, list]:
    """The process-wide memory accountant's ledger, one row per
    registered pool (telemetry/memory.py — the SQL face of
    /debug/prof/hbm). Device pools additionally carry their live-
    buffer-census bytes; the census residue rides the
    gtpu_mem_unaccounted_device_bytes gauge in runtime_metrics."""
    from greptimedb_tpu.telemetry import memory as _memory

    doc = _memory.hbm_report(top=0)
    rows = {"pool": [], "tier": [], "bytes": [], "entries": [],
            "budget_bytes": [], "max_entries": [], "hits": [],
            "misses": [], "evictions": [], "census_bytes": [],
            "instances": []}
    for p in doc["pools"]:
        rows["pool"].append(p["pool"])
        rows["tier"].append(p["tier"])
        rows["bytes"].append(p["bytes"])
        rows["entries"].append(p["entries"])
        rows["budget_bytes"].append(p["budget_bytes"])
        rows["max_entries"].append(p["max_entries"])
        rows["hits"].append(p["hits"])
        rows["misses"].append(p["misses"])
        rows["evictions"].append(p["evictions"])
        rows["census_bytes"].append(int(p.get("census_bytes", 0)))
        rows["instances"].append(p["instances"])
    return rows


def _autotune_decisions_doc(inst) -> dict[str, list]:
    """The control plane's audit log (autotune/knobs.py change log):
    one row per applied knob change — controller decisions AND
    operator ADMIN set_config calls ride the same single write path,
    so this table, gtpu_autotune_decisions_total and the knob-value
    gauges can never disagree."""
    rows = {"ts": [], "controller": [], "knob": [], "old_value": [],
            "new_value": [], "evidence": []}
    knobs = getattr(inst, "knobs", None)
    if knobs is None:
        return rows
    for ch in knobs.changes():
        doc = ch.to_doc()
        rows["ts"].append(int(doc["ts_ms"]))
        rows["controller"].append(doc["controller"])
        rows["knob"].append(doc["knob"])
        rows["old_value"].append(str(doc["old"]))
        rows["new_value"].append(str(doc["new"]))
        rows["evidence"].append(doc["evidence"])
    return rows


def _autotune_knobs_doc(inst) -> dict[str, list]:
    """Every registered runtime-mutable knob with its live value and
    declared bounds — what `ADMIN set_config` may touch."""
    rows = {"knob": [], "value": [], "kind": [], "lower_bound": [],
            "upper_bound": [], "pool": [], "doc": []}
    knobs = getattr(inst, "knobs", None)
    if knobs is None:
        return rows
    for d in knobs.snapshot():
        rows["knob"].append(d["knob"])
        rows["value"].append(str(d["value"]))
        rows["kind"].append(d["kind"])
        rows["lower_bound"].append(
            -1 if d["lo"] is None else int(d["lo"]))
        rows["upper_bound"].append(
            -1 if d["hi"] is None else int(d["hi"]))
        rows["pool"].append(d["pool"])
        rows["doc"].append(d["doc"])
    return rows


# ----------------------------------------------------------------------
# cluster-wide tables (dist/fleet.py): the per-node telemetry surfaces
# above, fanned out to every peer over the bounded node_telemetry
# Flight action and merged with peer/peer_status columns. A down node
# degrades to one status row instead of erroring the query.
# ----------------------------------------------------------------------

def _cluster_node_stats_doc(inst) -> dict[str, list]:
    """One row per fleet member from the heartbeat-carried node-stats
    payloads + the metasrv's phi-accrual verdict (standalone: the one
    local node)."""
    from greptimedb_tpu.dist import fleet

    return fleet.cluster_node_stats_doc(inst)


def _make_cluster_table(table: str):
    def provider(inst) -> dict[str, list]:
        from greptimedb_tpu.dist import fleet

        return fleet.cluster_table_doc(inst, table)

    provider.__name__ = f"_cluster_{table}_doc"
    return provider


_PROVIDERS = {
    "tables": _tables_doc,
    "columns": _columns_doc,
    "region_statistics": _region_statistics_doc,
    "schemata": _schemata_doc,
    "flows": _flows_doc,
    "views": _views_doc,
    "key_column_usage": _key_column_usage_doc,
    "table_constraints": _table_constraints_doc,
    "partitions": _partitions_doc,
    "region_peers": _region_peers_doc,
    "runtime_metrics": _runtime_metrics_doc,
    "cluster_info": _cluster_info_doc,
    "procedure_info": _procedure_info_doc,
    "engines": _engines_doc,
    "build_info": _build_info_doc,
    "character_sets": _character_sets_doc,
    "collations": _collations_doc,
    "slow_queries": _slow_queries_doc,
    "traces": _traces_doc,
    "memory_pools": _memory_pools_doc,
    "statement_statistics": _statement_statistics_doc,
    "device_programs": _device_programs_doc,
    "autotune_decisions": _autotune_decisions_doc,
    "autotune_knobs": _autotune_knobs_doc,
    "cluster_node_stats": _cluster_node_stats_doc,
    "cluster_runtime_metrics": _make_cluster_table("runtime_metrics"),
    "cluster_statement_statistics": _make_cluster_table(
        "statement_statistics"
    ),
    "cluster_device_programs": _make_cluster_table("device_programs"),
    "cluster_memory_pools": _make_cluster_table("memory_pools"),
}


# ----------------------------------------------------------------------
# pg_catalog shims (reference:
# /root/reference/src/catalog/src/system_schema/pg_catalog/): the
# queryable tables psql's \d / \dt and ORM introspection hit over the
# PG wire. OIDs are stable per name (crc32, masked positive) except
# pg_type's, which match the wire-protocol type OIDs.
# ----------------------------------------------------------------------

def _pg_oid(name: str) -> int:
    import zlib

    return (zlib.crc32(name.encode()) & 0x7FFFFFFF) or 1


def _pg_namespace_doc(inst) -> dict[str, list]:
    rows = {"oid": [], "nspname": []}
    for db in ["pg_catalog", "information_schema",
               *inst.catalog.database_names()]:
        rows["oid"].append(_pg_oid(f"ns:{db}"))
        rows["nspname"].append(db)
    return rows


def _pg_class_doc(inst) -> dict[str, list]:
    rows = {"oid": [], "relname": [], "relnamespace": [], "relkind": [],
            "relowner": []}
    for db in inst.catalog.database_names():
        ns = _pg_oid(f"ns:{db}")
        for name in inst.catalog.table_names(db):
            rows["oid"].append(_pg_oid(f"rel:{db}.{name}"))
            rows["relname"].append(name)
            rows["relnamespace"].append(ns)
            rows["relkind"].append("r")
            rows["relowner"].append(10)
        for vname in inst.catalog.view_names(db):
            rows["oid"].append(_pg_oid(f"rel:{db}.{vname}"))
            rows["relname"].append(vname)
            rows["relnamespace"].append(ns)
            rows["relkind"].append("v")
            rows["relowner"].append(10)
    return rows


def _pg_database_doc(inst) -> dict[str, list]:
    rows = {"oid": [], "datname": []}
    for db in inst.catalog.database_names():
        rows["oid"].append(_pg_oid(f"db:{db}"))
        rows["datname"].append(db)
    return rows


def _pg_type_doc(inst) -> dict[str, list]:
    # the ONE wire-type table lives next to the PG encoder
    from greptimedb_tpu.servers.postgres import PG_TYPES

    return {
        "oid": [oid for _n, oid, _l in PG_TYPES],
        "typname": [n for n, _o, _l in PG_TYPES],
        "typlen": [l for _n, _o, l in PG_TYPES],
    }


PG_CATALOG_TABLES = frozenset(
    {"pg_namespace", "pg_class", "pg_database", "pg_type"}
)
_PG_PROVIDERS = {
    "pg_namespace": _pg_namespace_doc,
    "pg_class": _pg_class_doc,
    "pg_database": _pg_database_doc,
    "pg_type": _pg_type_doc,
}


def query_pg_catalog(inst, stmt: A.Select, ctx) -> QueryResult:
    name = stmt.from_table
    if "." in name:
        name = name.split(".", 1)[1]
    name = name.lower()
    provider = _PG_PROVIDERS.get(name)
    if provider is None:
        raise TableNotFoundError(f"pg_catalog.{name}")
    return _query_system_doc(inst, stmt, provider(inst))


def query_information_schema(inst, stmt: A.Select, ctx) -> QueryResult:
    name = stmt.from_table
    if "." in name:
        name = name.split(".", 1)[1]
    name = name.lower()
    provider = _PROVIDERS.get(name)
    if provider is None:
        raise TableNotFoundError(f"information_schema.{name}")
    return _query_system_doc(inst, stmt, provider(inst))


def _query_system_doc(inst, stmt: A.Select, doc) -> QueryResult:
    cols = {}
    n = len(next(iter(doc.values()))) if doc else 0
    for k, vals in doc.items():
        if vals and isinstance(vals[0], bool):
            cols[k] = Col(np.asarray(vals, bool))
        elif vals and isinstance(vals[0], (int, np.integer)):
            cols[k] = Col(np.asarray(vals, np.int64))
        elif vals and isinstance(vals[0], (float, np.floating)):
            cols[k] = Col(np.asarray(vals, np.float64))
        else:
            cols[k] = Col(np.asarray(vals, object))
    src = DictSource(cols, n)

    plan = plan_select(stmt, ts_name=None, tag_names=[],
                       all_columns=list(doc.keys()))
    if plan.kind == "range":
        from greptimedb_tpu.errors import UnsupportedError

        raise UnsupportedError(
            "RANGE over system tables is not supported"
        )
    if plan.scan.residual is not None and n:
        cond = eval_expr(plan.scan.residual, src)
        mask = cond.values.astype(bool) & cond.valid_mask
        cols = {
            k: Col(c.values[mask],
                   None if c.validity is None else c.validity[mask])
            for k, c in cols.items()
        }
        src = DictSource(cols, int(mask.sum()))
    # system docs run through the normal executor paths (the reference
    # treats information_schema as ordinary DataFusion tables):
    # aggregates, window functions, DISTINCT/ORDER/LIMIT all included
    if plan.kind == "aggregate":
        return inst.query_engine._execute_aggregate(plan, src, None)
    return inst.query_engine._execute_plain(plan, src, None)
