"""information_schema virtual tables.

Counterpart of /root/reference/src/catalog/src/system_schema/
information_schema/: tables, columns, region_statistics, flows — generated
on demand from the catalog, then run through the normal query planner so
WHERE/ORDER BY/aggregates work on them.
"""

from __future__ import annotations

import numpy as np

from greptimedb_tpu.errors import TableNotFoundError
from greptimedb_tpu.query.executor import Col, DictSource, QueryResult
from greptimedb_tpu.query.expr import eval_expr
from greptimedb_tpu.query.planner import item_name, plan_select
from greptimedb_tpu.sql import ast as A


def _tables_doc(inst) -> dict[str, list]:
    rows = {
        "table_catalog": [], "table_schema": [], "table_name": [],
        "table_type": [], "table_id": [], "engine": [], "region_count": [],
    }
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(db)
            rows["table_name"].append(name)
            rows["table_type"].append("BASE TABLE")
            rows["table_id"].append(t.info.table_id)
            rows["engine"].append(t.info.engine)
            rows["region_count"].append(t.info.num_regions)
    return rows


def _columns_doc(inst) -> dict[str, list]:
    rows = {
        "table_catalog": [], "table_schema": [], "table_name": [],
        "column_name": [], "data_type": [], "semantic_type": [],
        "is_nullable": [],
    }
    for db in inst.catalog.database_names():
        for name in inst.catalog.table_names(db):
            t = inst.catalog.table(db, name)
            for c in t.schema.columns:
                rows["table_catalog"].append("greptime")
                rows["table_schema"].append(db)
                rows["table_name"].append(name)
                rows["column_name"].append(c.name)
                rows["data_type"].append(c.data_type.name)
                rows["semantic_type"].append(
                    "TIMESTAMP" if c.is_time_index
                    else ("TAG" if c.is_tag else "FIELD")
                )
                rows["is_nullable"].append("Yes" if c.nullable else "No")
    return rows


def _region_statistics_doc(inst) -> dict[str, list]:
    rows = {
        "region_id": [], "table_id": [], "region_rows": [],
        "memtable_size": [], "sst_size": [], "sst_num": [],
    }
    for t in inst.catalog.all_tables():
        for r in t.regions:
            rows["region_id"].append(r.meta.region_id)
            rows["table_id"].append(t.info.table_id)
            rows["region_rows"].append(
                r.memtable.rows + sum(m.rows for m in r.manifest.state.ssts)
            )
            rows["memtable_size"].append(r.memtable.bytes)
            rows["sst_size"].append(
                sum(m.size_bytes for m in r.manifest.state.ssts)
            )
            rows["sst_num"].append(len(r.manifest.state.ssts))
    return rows


def _schemata_doc(inst) -> dict[str, list]:
    names = inst.catalog.database_names()
    return {
        "catalog_name": ["greptime"] * len(names),
        "schema_name": names,
    }


def _flows_doc(inst) -> dict[str, list]:
    rows = {"flow_name": [], "source_table": [], "sink_table": [],
            "processed_rows": []}
    if inst.flows is not None:
        for f in inst.flows.flow_infos():
            rows["flow_name"].append(f["name"])
            rows["source_table"].append(f["source_table"])
            rows["sink_table"].append(f["sink_table"])
            rows["processed_rows"].append(f["processed_rows"])
    return rows


_PROVIDERS = {
    "tables": _tables_doc,
    "columns": _columns_doc,
    "region_statistics": _region_statistics_doc,
    "schemata": _schemata_doc,
    "flows": _flows_doc,
}


def query_information_schema(inst, stmt: A.Select, ctx) -> QueryResult:
    name = stmt.from_table
    if "." in name:
        name = name.split(".", 1)[1]
    name = name.lower()
    provider = _PROVIDERS.get(name)
    if provider is None:
        raise TableNotFoundError(f"information_schema.{name}")
    doc = provider(inst)
    cols = {}
    n = len(next(iter(doc.values()))) if doc else 0
    for k, vals in doc.items():
        if vals and isinstance(vals[0], (int, np.integer)):
            cols[k] = Col(np.asarray(vals, np.int64))
        else:
            cols[k] = Col(np.asarray(vals, object))
    src = DictSource(cols, n)

    plan = plan_select(stmt, ts_name=None, tag_names=[],
                       all_columns=list(doc.keys()))
    if plan.kind != "plain":
        raise TableNotFoundError(
            "aggregates over information_schema are not supported yet"
        )
    if plan.scan.residual is not None and n:
        cond = eval_expr(plan.scan.residual, src)
        mask = cond.values.astype(bool) & cond.valid_mask
        cols = {
            k: Col(c.values[mask],
                   None if c.validity is None else c.validity[mask])
            for k, c in cols.items()
        }
        src = DictSource(cols, int(mask.sum()))
    names = [nm for _, nm in plan.items]
    out = [eval_expr(e, src) for e, _ in plan.items]
    from greptimedb_tpu.query.executor import (
        _distinct_indices,
        _slice_result,
        _sort_indices,
    )

    # sort before distinct: _distinct_indices keeps first occurrences in
    # (sorted) row order, so the sort survives dedup
    if plan.order_by:
        order_cols = [eval_expr(o.expr, src) for o in plan.order_by]
        idx = _sort_indices(order_cols, [o.asc for o in plan.order_by],
                            [o.nulls_first for o in plan.order_by])
        out = _slice_result(out, idx)
    if plan.distinct:
        out = _slice_result(out, _distinct_indices(out))
    if plan.offset or plan.limit is not None:
        off = plan.offset or 0
        end = None if plan.limit is None else off + plan.limit
        out = _slice_result(out, slice(off, end))
    return QueryResult(names, out)
