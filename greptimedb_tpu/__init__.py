"""greptimedb_tpu: a TPU-native time-series database framework.

Capability surface modeled on GreptimeDB (see SURVEY.md): SQL + PromQL over
metrics/logs/events, LSM region storage with WAL + Parquet SSTs, a metadata
control plane with heartbeats/leases/failover, and streaming continuous
aggregation — with the columnar scan/aggregate/window hot path executed as
JAX/XLA/Pallas programs sharded over a TPU mesh.

Layering (top → bottom), mirroring the reference layer map (SURVEY.md §1):

    servers/   wire protocols (HTTP SQL, Prometheus, InfluxDB, ...)
    cluster/   role assembly: standalone, frontend, datanode, metasrv, flownode
    query/     SQL + PromQL planning and TPU-backed execution
    flow/      continuous aggregation with device-resident accumulators
    meta/      catalog, kv backend, procedures, failure detection
    storage/   LSM region engine: WAL, memtables, Parquet SSTs, compaction
    ops/       the device kernel library (segment/window/PromQL kernels)
    parallel/  mesh + sharding + collectives (the distributed backend)
    datatypes/ column types bridging Arrow <-> JAX
"""

from greptimedb_tpu.version import __version__

__all__ = ["__version__"]
