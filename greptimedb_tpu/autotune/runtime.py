"""Controller runtime: one low-frequency tick loop per process.

The loop rides the concurrency facade (gtsan-instrumentable thread +
event; no bare threading), wraps each tick in an ``autotune.tick``
span, and isolates controllers the way engine.run_maintenance isolates
regions: a controller whose sensor or actuator raises logs the error,
ticks ``gtpu_autotune_controller_errors_total{controller=...}``, and
the REMAINING controllers still run — one bad sensor never kills the
control plane.

Freeze semantics (`ADMIN autotune_freeze()` / `[autotune] enable`):
- disabled (`enable = false`): tick_once is a bit-for-bit no-op —
  no span, no sensor reads, no knob reads, zero decisions.
- frozen: the loop keeps ticking (span + counter, so operators can
  see it is alive) but no controller runs and no knob moves;
  ``gtpu_autotune_frozen`` reads 1. ADMIN set_config stays available —
  freezing hands control back to the operator, it does not take the
  update API away.
"""

from __future__ import annotations

import logging

from greptimedb_tpu import concurrency
from greptimedb_tpu.telemetry.metrics import global_registry

_log = logging.getLogger("greptimedb_tpu.autotune")

_FROZEN = global_registry.gauge(
    "gtpu_autotune_frozen",
    "1 while the control plane is frozen (ADMIN autotune_freeze)",
)
_TICKS = global_registry.counter(
    "gtpu_autotune_ticks_total",
    "controller-runtime ticks (frozen ticks included)",
)
_ERRORS = global_registry.counter(
    "gtpu_autotune_controller_errors_total",
    "controller ticks that raised (isolated; the loop continues)",
    labels=("controller",),
)


class AutotuneRuntime:
    """The per-process control loop over a controller set."""

    def __init__(self, knobs, controllers, *, interval_s: float = 5.0,
                 enabled: bool = False):
        self.knobs = knobs
        self.controllers = list(controllers)
        self.interval_s = float(interval_s)
        self.enabled = bool(enabled)
        self._frozen = False
        self._stop = concurrency.Event()
        self._thread = None

    # ---- configuration ------------------------------------------------
    def apply_options(self, section: dict | None) -> None:
        """Apply the `[autotune]` TOML section: master + per-controller
        enables, tick cadence, shared guardrails."""
        o = section or {}
        self.enabled = bool(o.get("enable", False))
        self.interval_s = float(o.get("tick_interval_s", self.interval_s))
        for c in self.controllers:
            c.enabled = bool(o.get(c.name, True))
            c.rails.step = float(o.get("step", c.rails.step))
            c.rails.band = float(o.get("band", c.rails.band))
            c.rails.cooldown_ticks = int(
                o.get("cooldown_ticks", c.rails.cooldown_ticks)
            )

    # ---- freeze -------------------------------------------------------
    def freeze(self, on: bool = True) -> None:
        self._frozen = bool(on)
        _FROZEN.set(1.0 if self._frozen else 0.0)

    @property
    def frozen(self) -> bool:
        return self._frozen

    # ---- the tick -----------------------------------------------------
    def tick_once(self) -> int:
        """One control tick; returns applied knob changes. Safe to
        call directly (tests, and the ADMIN surface could expose it)."""
        if not self.enabled:
            return 0
        from greptimedb_tpu.telemetry import tracing

        with tracing.span("autotune.tick", frozen=int(self._frozen),
                          controllers=len(self.controllers)) as sp:
            _TICKS.inc()
            if self._frozen:
                sp.attributes["decisions"] = 0
                return 0
            n = 0
            for c in self.controllers:
                try:
                    n += int(c.tick())
                except Exception:  # noqa: BLE001 - per-controller
                    # isolation: one raising sensor/actuator must not
                    # kill the loop or starve the other controllers
                    _ERRORS.labels(c.name).inc()
                    _log.warning("[autotune] controller %r failed "
                                 "this tick", c.name, exc_info=True)
            sp.attributes["decisions"] = n
            return n

    # ---- lifecycle ----------------------------------------------------
    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        # contract: the controller loop is a process-lifetime daemon
        # with no submitting request — every autotune.tick span is
        # DELIBERATELY its own root trace, not a child of whichever
        # request happened to call start()
        self._thread = concurrency.Thread(
            target=self._run,  # gtlint: disable=GT027
            name="gtpu-autotune", daemon=True,
        )
        self._thread.start()
        _log.info("[autotune] control loop started "
                  "(tick every %.1fs, controllers: %s)",
                  self.interval_s,
                  ", ".join(c.name for c in self.controllers
                            if c.enabled) or "none")

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                # anything (tracing teardown, interpreter shutdown
                # races); controller errors are already isolated above
                _log.warning("[autotune] tick failed", exc_info=True)

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # ---- audit --------------------------------------------------------
    def decisions(self) -> list[dict]:
        return [c.to_doc() for c in self.knobs.changes()]
