"""gtune — the adaptive control plane closing the observability loop.

PRs 10-15 built the measurement plane (per-fingerprint statement
statistics, per-program rooflines, the per-pool HBM ledger, fleet node
stats); this package closes the loop in the tf.data-AUTOTUNE mold: a
sensor layer over those existing in-memory surfaces, feedback
controllers with hard guardrails, and a knob registry that is the
single sanctioned writer for every runtime-mutable knob.

Layout:
- knobs.py        KnobRegistry + the standard knob set (the validated
                  update API `ADMIN set_config` also rides)
- sensors.py      read-only signal extraction from telemetry surfaces
- controllers.py  admission concurrency, planner shard thresholds,
                  HBM budget reallocation, compaction pacing
- runtime.py      the per-process tick loop, freeze, audit surfaces

Off by default (`[autotune] enable = false`): a process that never
enables it gets a registry (so `ADMIN set_config` and the
information_schema surfaces work) and nothing else — no thread, no
sensor reads, bit-for-bit identical knob values.
"""

from __future__ import annotations

from greptimedb_tpu.autotune.controllers import (
    AdmissionConcurrencyController,
    CompactionPacingController,
    Controller,
    Guardrails,
    HbmBudgetController,
    PlannerThresholdController,
)
from greptimedb_tpu.autotune.knobs import (
    KnobChange,
    KnobRegistry,
    KnobSpec,
    build_registry,
)
from greptimedb_tpu.autotune.runtime import AutotuneRuntime
from greptimedb_tpu.autotune.sensors import (
    AdmissionSensor,
    CompactionSensor,
    HbmSensor,
    PlannerSensor,
)

__all__ = [
    "AdmissionConcurrencyController",
    "AdmissionSensor",
    "AutotuneRuntime",
    "CompactionPacingController",
    "CompactionSensor",
    "Controller",
    "Guardrails",
    "HbmBudgetController",
    "HbmSensor",
    "KnobChange",
    "KnobRegistry",
    "KnobSpec",
    "PlannerSensor",
    "PlannerThresholdController",
    "build_registry",
    "build_runtime",
]


def build_runtime(inst, section: dict | None = None
                  ) -> tuple[KnobRegistry, AutotuneRuntime]:
    """Wire the standard control plane over a Standalone instance:
    the full knob set, the four controllers on their real sensors,
    and a (not yet started) runtime. `section` is the `[autotune]`
    TOML dict; without it everything is registered but disabled."""
    o = section or {}
    registry = build_registry(inst, history=int(o.get("history", 256)))
    baseline_workers = 1
    try:
        baseline_workers = int(inst.engine.compaction.opts.workers)
    except (AttributeError, TypeError, ValueError):
        pass  # engine not fully wired (tests): keep the default of 1
    controllers = [
        AdmissionConcurrencyController(
            registry, AdmissionSensor(inst)),
        PlannerThresholdController(registry, PlannerSensor(inst)),
        HbmBudgetController(registry, HbmSensor(registry)),
        CompactionPacingController(
            registry, CompactionSensor(inst),
            baseline_workers=baseline_workers),
    ]
    runtime = AutotuneRuntime(registry, controllers)
    runtime.apply_options(o)
    return registry, runtime
