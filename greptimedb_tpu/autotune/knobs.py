"""Runtime-mutable knob registry: the single sanctioned write path.

Every knob the control plane may move at runtime registers here with a
typed bound and a pair of closures over the live object (read the
current value / apply a validated one). ``KnobRegistry.set`` is the ONE
write path — the autotune controllers (autotune/controllers.py) and
``ADMIN set_config('<section>.<knob>', <value>)`` both go through it,
so every change is validated against the declared bounds, lands in the
change log (the ``information_schema.autotune_decisions`` surface), and
publishes on ``gtpu_autotune_knob_value{knob=...}``. gtlint GT021 keeps
everything else out: a direct assignment to a registered knob attribute
outside the owning object / this package is a lint finding, so two
tuners can never fight over the same knob.

Deliberately NOT here: durability/correctness knobs (WAL backend,
manifest cadence, merge modes, recovery options). Autotune moves
performance trade-offs only; anything that can lose or corrupt data
stays frozen at process start.
"""

from __future__ import annotations

import json
import time

from collections import deque
from dataclasses import dataclass, field

from greptimedb_tpu import concurrency
from greptimedb_tpu.errors import InvalidArgumentError
from greptimedb_tpu.telemetry.metrics import global_registry

_KNOB_VALUE = global_registry.gauge(
    "gtpu_autotune_knob_value",
    "current value of each registered runtime-mutable knob",
    labels=("knob",),
)
_DECISIONS = global_registry.counter(
    "gtpu_autotune_decisions_total",
    "applied knob changes (controller label: which tuner, or 'admin')",
    labels=("controller",),
)


@dataclass
class KnobSpec:
    """One runtime-mutable knob: dotted path, type, bounds, accessors."""

    path: str                  # "scheduler.max_concurrency"
    kind: type                 # int | float | bool
    lo: float | None
    hi: float | None
    doc: str
    getter: object             # () -> current value
    setter: object             # (validated value) -> None
    # pool name in the memory accountant for byte-budget knobs (the
    # HBM reallocation controller maps pool pressure -> knob)
    pool: str | None = None


@dataclass
class KnobChange:
    """One applied change — the audit-log row."""

    ts_ms: int
    controller: str            # "admin" or the controller name
    knob: str
    old: object
    new: object
    evidence: dict = field(default_factory=dict)

    def to_doc(self) -> dict:
        return {
            "ts_ms": self.ts_ms, "controller": self.controller,
            "knob": self.knob, "old": self.old, "new": self.new,
            "evidence": json.dumps(self.evidence, sort_keys=True,
                                   default=str),
        }


class KnobRegistry:
    """Validated update API over the registered knob set.

    All mutation rides ``set``: type coercion, bound check, apply via
    the spec's setter, change-log append, metric publish — under one
    lock so concurrent ADMIN/controller writers serialize."""

    def __init__(self, history: int = 256):
        self._lock = concurrency.Lock()
        self._specs: dict[str, KnobSpec] = {}
        self._changes: deque[KnobChange] = deque(maxlen=max(history, 1))

    # ---- registration -------------------------------------------------
    def register(self, spec: KnobSpec) -> None:
        with self._lock:
            self._specs[spec.path] = spec
        try:
            _KNOB_VALUE.labels(spec.path).set(float(spec.getter()))
        except (AttributeError, TypeError, ValueError):
            pass  # live object not wired yet; gauge appears on first set

    def paths(self) -> list[str]:
        with self._lock:
            return sorted(self._specs)

    def spec(self, path: str) -> KnobSpec | None:
        with self._lock:
            return self._specs.get(path)

    # ---- read ---------------------------------------------------------
    def get(self, path: str):
        spec = self.spec(path)
        if spec is None:
            raise InvalidArgumentError(
                f"unknown runtime-mutable knob {path!r}; "
                f"known: {', '.join(self.paths())}"
            )
        return spec.getter()

    def _coerce(self, spec: KnobSpec, value):
        if spec.kind is bool:
            if isinstance(value, bool):
                return value
            if isinstance(value, (int, float)) and value in (0, 1):
                return bool(value)
            if isinstance(value, str) and value.lower() in (
                    "true", "false", "0", "1"):
                return value.lower() in ("true", "1")
            raise InvalidArgumentError(
                f"knob {spec.path!r} expects a boolean, got {value!r}"
            )
        if isinstance(value, bool):
            raise InvalidArgumentError(
                f"knob {spec.path!r} expects {spec.kind.__name__}, "
                f"got a boolean"
            )
        if isinstance(value, str):
            try:
                value = float(value) if spec.kind is float else int(value)
            except ValueError:
                raise InvalidArgumentError(
                    f"knob {spec.path!r} expects "
                    f"{spec.kind.__name__}, got {value!r}"
                ) from None
        if spec.kind is int:
            if isinstance(value, float) and not value.is_integer():
                raise InvalidArgumentError(
                    f"knob {spec.path!r} expects an integer, "
                    f"got {value!r}"
                )
            try:
                return int(value)
            except (TypeError, ValueError):
                raise InvalidArgumentError(
                    f"knob {spec.path!r} expects an integer, "
                    f"got {value!r}"
                ) from None
        try:
            return float(value)
        except (TypeError, ValueError):
            raise InvalidArgumentError(
                f"knob {spec.path!r} expects a number, got {value!r}"
            ) from None

    # ---- the single write path ---------------------------------------
    def set(self, path: str, value, *, source: str = "admin",
            evidence: dict | None = None):
        """Validate and apply one knob change. Returns (old, new).
        Raises InvalidArgumentError on unknown knob / type mismatch /
        out-of-bounds value. A no-op write (new == old) is applied but
        NOT logged — hysteresis lives with the callers; the audit log
        records actual movement."""
        spec = self.spec(path)
        if spec is None:
            raise InvalidArgumentError(
                f"unknown runtime-mutable knob {path!r}; "
                f"known: {', '.join(self.paths())}"
            )
        new = self._coerce(spec, value)
        if spec.lo is not None and new < spec.lo:
            raise InvalidArgumentError(
                f"knob {path!r}: {new!r} below the lower bound "
                f"{spec.lo:g}"
            )
        if spec.hi is not None and new > spec.hi:
            raise InvalidArgumentError(
                f"knob {path!r}: {new!r} above the upper bound "
                f"{spec.hi:g}"
            )
        with self._lock:
            old = spec.getter()
            if new == old:
                return old, new
            spec.setter(new)
            change = KnobChange(
                ts_ms=int(time.time() * 1000), controller=source,
                knob=path, old=old, new=new,
                evidence=dict(evidence or {}),
            )
            self._changes.append(change)
        _KNOB_VALUE.labels(path).set(float(new))
        _DECISIONS.labels(source).inc()
        return old, new

    # ---- audit surfaces ----------------------------------------------
    def changes(self) -> list[KnobChange]:
        with self._lock:
            return list(self._changes)

    def decision_count(self) -> int:
        with self._lock:
            return len(self._changes)

    def snapshot(self) -> list[dict]:
        """Current value + declared bounds per knob (the
        information_schema.autotune_knobs surface)."""
        out = []
        for path in self.paths():
            spec = self.spec(path)
            if spec is None:
                continue
            try:
                value = spec.getter()
            except Exception:  # noqa: BLE001 - live object torn down
                value = None
            out.append({
                "knob": path, "value": value,
                "kind": spec.kind.__name__,
                "lo": spec.lo, "hi": spec.hi,
                "pool": spec.pool or "", "doc": spec.doc,
            })
        return out


# ----------------------------------------------------------------------
# the standard knob set over a Standalone instance
# ----------------------------------------------------------------------

def build_registry(inst, history: int = 256) -> KnobRegistry:
    """Register every runtime-mutable knob the controllers may move.

    Accessors close over ``inst`` by attribute lookup at call time, so
    cli.py swapping in the [scheduler]/[result_cache]-configured
    objects AFTER Standalone.__init__ is picked up transparently.
    Bounds are wide operator-sanity rails, not tuning targets — the
    controllers add their own step clamps on top."""
    from greptimedb_tpu.parallel import mesh as mesh_mod
    from greptimedb_tpu.query import sessions as sessions_mod

    reg = KnobRegistry(history=history)

    def _mesh_opts():
        return (getattr(inst.query_engine, "mesh_opts", None)
                or mesh_mod.global_mesh_opts()
                or mesh_mod.MeshOptions())

    def _set_mesh(**kw):
        new = mesh_mod.update_shard_thresholds(base=_mesh_opts(), **kw)
        inst.query_engine.mesh_opts = new

    reg.register(KnobSpec(
        "scheduler.max_concurrency", int, 0, 65536,
        "global execution slots (0 = unlimited)",
        getter=lambda: inst.scheduler.config.max_concurrency,
        setter=lambda v: inst.scheduler.set_max_concurrency(v),
    ))
    reg.register(KnobSpec(
        "mesh.shard_min_series", int, 1, 1 << 24,
        "grids below this series count replicate instead of shard",
        getter=lambda: _mesh_opts().shard_min_series,
        setter=lambda v: _set_mesh(shard_min_series=v),
    ))
    reg.register(KnobSpec(
        "mesh.shard_min_rows", int, 1, 1 << 30,
        "row reductions below this row count replicate",
        getter=lambda: _mesh_opts().shard_min_rows,
        setter=lambda v: _set_mesh(shard_min_rows=v),
    ))
    reg.register(KnobSpec(
        "sessions.hbm_bytes", int, 0, 1 << 40,
        "HBM byte budget for persistent session result buffers",
        getter=lambda: sessions_mod.global_sessions.max_bytes,
        setter=lambda v: sessions_mod.global_sessions.set_max_bytes(v),
        pool="sessions",
    ))
    reg.register(KnobSpec(
        "result_cache.bytes", int, 0, 1 << 40,
        "frontend result-set cache byte budget",
        getter=lambda: inst.result_cache.max_bytes,
        setter=lambda v: inst.result_cache.set_max_bytes(v),
        pool="result_cache",
    ))
    reg.register(KnobSpec(
        "compaction.workers", int, 1, 64,
        "bounded merge pool width",
        getter=lambda: inst.engine.compaction.opts.workers,
        setter=lambda v: inst.engine.compaction.set_workers(v),
    ))
    reg.register(KnobSpec(
        "compaction.l1_trigger_files", int, 2, 256,
        "L1 -> L2 promotion file-count trigger",
        getter=lambda: inst.engine.compaction.opts.l1_trigger_files,
        setter=lambda v: inst.engine.compaction.set_trigger_files(v),
    ))
    # datanode merged-scan cache, present only on roles that own a
    # region server (dist datanode; standalone has no Flight scan path)
    rs = getattr(inst, "region_server", None)
    if rs is not None and getattr(rs, "scan_cache", None) is not None:
        reg.register(KnobSpec(
            "dist_query.scan_cache_bytes", int, 0, 1 << 40,
            "datanode merged-scan cache byte budget",
            getter=lambda: inst.region_server.scan_cache.max_bytes,
            setter=lambda v: inst.region_server.scan_cache.set_max_bytes(
                v
            ),
            pool="scan_cache",
        ))
    return reg
