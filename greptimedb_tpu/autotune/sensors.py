"""Sensor layer: read-only views over the existing telemetry surfaces.

Sensors read ONLY in-memory state the observability plane already
maintains — the statement-statistics registry (queue/latency
percentiles per fingerprint), the admission controller's live
queue/running counts, the memory accountant's per-pool ledger, the
device-program profiler, and the compaction read-amplification /
ingest-rate counters. No sensor touches storage, dispatches a program,
or takes a lock the hot path contends on; every callable returns a
plain dict (or None for "no signal this tick") that doubles as the
decision's evidence payload.

Rate-style sensors (cache hit deltas, ingest rows/s) are CLASSES
holding the previous counter snapshot: the controllers stay pure
functions of the current signal, which is what lets
tests/test_autotune.py drive them with simulated sensors.
"""

from __future__ import annotations


def _metric_total(name: str) -> float:
    """Sum of every label child of a registered counter/gauge; 0.0
    when the owning module never registered it in this process."""
    from greptimedb_tpu.telemetry.metrics import global_registry

    try:
        metric = global_registry.get(name)
    except KeyError:
        return 0.0
    return float(sum(c.value for _k, c in metric._snapshot()))


# ----------------------------------------------------------------------
# admission: cost-aware concurrency
# ----------------------------------------------------------------------

class AdmissionSensor:
    """Live queue pressure + per-fingerprint statement cost.

    The cost estimate comes from the stmt_stats registry: the
    call-weighted mean latency is the 'service time' the controller
    normalizes queue wait against, and the top fingerprints by total
    time ride along as evidence."""

    def __init__(self, inst):
        self._inst = inst

    def __call__(self) -> dict | None:
        sched = self._inst.scheduler
        if not getattr(sched.config, "enable", False):
            return None
        snap = sched.snapshot()
        sig = {
            "running": int(snap.get("running", 0)),
            "queued": int(snap.get("queued", 0)),
            "mean_cost_ms": None,
            "queue_p99_ms": None,
            "shed_total": 0,
            "top": [],
        }
        from greptimedb_tpu.telemetry import stmt_stats

        if stmt_stats.enabled():
            calls = 0
            cost = 0.0
            qp99 = 0.0
            shed = 0
            rows = stmt_stats.global_stmt_stats.snapshot()
            for doc in rows:
                c = int(doc.get("calls") or 0)
                calls += c
                cost += float(doc.get("mean_ms") or 0.0) * c
                qp99 = max(qp99, float(doc.get("queue_p99_ms") or 0.0))
                shed += int(doc.get("shed_count") or 0)
            if calls:
                sig["mean_cost_ms"] = cost / calls
                sig["queue_p99_ms"] = qp99
                sig["shed_total"] = shed
                top = sorted(
                    rows,
                    key=lambda d: (float(d.get("mean_ms") or 0.0)
                                   * int(d.get("calls") or 0)),
                    reverse=True,
                )[:3]
                sig["top"] = [
                    {"fingerprint": d.get("fingerprint"),
                     "calls": d.get("calls"),
                     "mean_ms": round(float(d.get("mean_ms") or 0.0), 3),
                     "queue_p99_ms": round(
                         float(d.get("queue_p99_ms") or 0.0), 3)}
                    for d in top
                ]
        return sig


# ----------------------------------------------------------------------
# planner: measured shard-vs-replicate scaling
# ----------------------------------------------------------------------

class PlannerSensor:
    """Call-weighted latency of sharded vs replicated statements.

    Coarse by design: it compares the measured mean latency of
    fingerprints the planner sent down each path (stmt_stats
    mesh_decision attribution), not a controlled A/B of one statement —
    the hysteresis band absorbs the cross-statement noise, and the
    sensor stays silent (None) without a multi-device mesh or enough
    samples on BOTH paths."""

    MIN_CALLS = 8

    def __init__(self, inst):
        self._inst = inst

    def __call__(self) -> dict | None:
        from greptimedb_tpu.parallel.mesh import global_mesh, shard_count

        if shard_count(global_mesh()) <= 1:
            return None
        from greptimedb_tpu.telemetry import stmt_stats

        if not stmt_stats.enabled():
            return None
        shard_ms = shard_calls = 0.0
        repl_ms = repl_calls = 0.0
        for doc in stmt_stats.global_stmt_stats.snapshot():
            dec = str(doc.get("mesh_decision") or "")
            c = int(doc.get("calls") or 0)
            m = float(doc.get("mean_ms") or 0.0)
            if dec.startswith("shard"):
                shard_calls += c
                shard_ms += m * c
            elif dec.startswith("replicate"):
                repl_calls += c
                repl_ms += m * c
        if shard_calls < self.MIN_CALLS or repl_calls < self.MIN_CALLS:
            return None
        return {
            "shard_ms": shard_ms / shard_calls,
            "replicate_ms": repl_ms / repl_calls,
            "shard_calls": int(shard_calls),
            "replicate_calls": int(repl_calls),
        }


# ----------------------------------------------------------------------
# HBM: hit-rate-per-byte across the budgeted pools
# ----------------------------------------------------------------------

class HbmSensor:
    """Per-tick hit/miss/eviction DELTAS for every pool whose byte
    budget is a registered knob (KnobSpec.pool links them). Budgets
    come from the knob registry (the accountant reports 0 for a
    disabled pool, which would hide a resizable budget)."""

    def __init__(self, knobs):
        self._knobs = knobs
        self._prev: dict[str, tuple] = {}

    def __call__(self) -> list[dict] | None:
        from greptimedb_tpu.telemetry import memory as _memory

        by_pool = {}
        for p in _memory.global_accountant.snapshot():
            by_pool.setdefault(p.name, p)
        out = []
        for path in self._knobs.paths():
            spec = self._knobs.spec(path)
            if spec is None or not spec.pool:
                continue
            p = by_pool.get(spec.pool)
            if p is None:
                continue
            prev = self._prev.get(spec.pool, (0, 0, 0))
            cur = (int(p.hits), int(p.misses), int(p.evictions))
            self._prev[spec.pool] = cur
            out.append({
                "knob": path, "pool": spec.pool,
                "budget": int(self._knobs.get(path)),
                "bytes": int(p.bytes),
                "hits_d": max(0, cur[0] - prev[0]),
                "misses_d": max(0, cur[1] - prev[1]),
                "evictions_d": max(0, cur[2] - prev[2]),
            })
        return out or None


# ----------------------------------------------------------------------
# compaction: read-amplification vs ingest rate
# ----------------------------------------------------------------------

class CompactionSensor:
    """Live read-amp over the engine's open regions + the ingest-row
    rate since the previous tick (gtpu_ingest_rows_total delta over a
    monotonic interval)."""

    def __init__(self, inst):
        self._inst = inst
        self._prev_rows: float | None = None
        self._prev_t: float | None = None

    def __call__(self) -> dict | None:
        import time as _time

        from greptimedb_tpu.storage.compaction import read_amplification

        engine = self._inst.engine
        regions = engine.regions()
        read_amp = max(
            (read_amplification(r) for r in regions), default=0
        )
        rows = _metric_total("gtpu_ingest_rows_total")
        now = _time.monotonic()
        rps = 0.0
        if self._prev_t is not None and now > self._prev_t:
            rps = max(0.0, rows - self._prev_rows) / (now - self._prev_t)
        self._prev_rows, self._prev_t = rows, now
        return {
            "read_amp": int(read_amp),
            "ingest_rows_per_s": round(rps, 1),
            "regions": len(regions),
        }
