"""Feedback controllers: sensor signal -> guarded knob movement.

Each controller is a pure function of its sensor's current signal
(tests drive them with simulated sensors), wrapped in the shared
guardrails:

- step clamp    — one decision moves a knob at most ``step`` of its
                  current value (never to/through zero)
- hysteresis    — a target inside ``band`` of the current value is
                  noise, not a decision
- cooldown      — after a decision the controller holds for
                  ``cooldown_ticks`` ticks so the system's response
                  lands in the sensors before the next move
- freeze/enable — the runtime skips frozen/disabled controllers
                  entirely (zero decisions, zero knob reads)

All writes go through KnobRegistry.set (bounds re-checked, change
logged, metrics published) — controllers never touch a live object
directly (gtlint GT021)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Guardrails:
    step: float = 0.25        # max relative movement per decision
    band: float = 0.15        # hysteresis dead-band (relative)
    cooldown_ticks: int = 2   # ticks to hold after a decision


class Controller:
    """Base: cooldown/enable bookkeeping + the guarded step helper."""

    name = "base"

    def __init__(self, knobs, sense, *, enabled: bool = True,
                 rails: Guardrails | None = None):
        self.knobs = knobs
        self.sense = sense
        self.enabled = bool(enabled)
        self.rails = rails or Guardrails()
        self._tick = 0
        self._last_change_tick: int | None = None

    def tick(self) -> int:
        """One control step. Returns the number of applied knob
        changes (0 while disabled, cooling down, or signal-less)."""
        self._tick += 1
        if not self.enabled:
            return 0
        if (self._last_change_tick is not None
                and self._tick - self._last_change_tick
                < self.rails.cooldown_ticks):
            return 0
        sig = self.sense()
        if not sig:
            return 0
        applied = self.decide(sig)
        if applied:
            self._last_change_tick = self._tick
        return applied

    def decide(self, sig) -> int:  # pragma: no cover - subclass hook
        raise NotImplementedError

    # ---- the guarded actuation primitive ------------------------------
    def _move(self, knob: str, target: float, evidence: dict) -> int:
        """Step the knob toward ``target``: hysteresis-banded, step-
        clamped, bound-clamped, applied through the registry. Returns
        1 when a change landed, 0 when the move was absorbed."""
        cur = self.knobs.get(knob)
        r = self.rails
        if abs(target - cur) <= r.band * abs(cur):
            return 0
        lo_step = cur * (1.0 - r.step)
        hi_step = cur * (1.0 + r.step)
        new = min(max(float(target), lo_step), hi_step)
        spec = self.knobs.spec(knob)
        if spec is not None and spec.kind is int:
            new = int(round(new))
            if new == cur:
                # integer knobs always move at least one notch once
                # the target cleared the hysteresis band
                new = cur + (1 if target > cur else -1)
        if spec is not None:
            if spec.lo is not None:
                new = max(new, spec.kind(spec.lo))
            if spec.hi is not None:
                new = min(new, spec.kind(spec.hi))
        if new == cur:
            return 0
        self.knobs.set(knob, new, source=self.name, evidence=evidence)
        return 1


# ----------------------------------------------------------------------
# admission: cost-aware concurrency
# ----------------------------------------------------------------------

class AdmissionConcurrencyController(Controller):
    """Sizes `[scheduler] max_concurrency` from measured statement
    cost instead of a hand-picked constant.

    Raise: statements are queued AND the queue-wait p99 is large
    relative to the per-fingerprint mean cost (waiting dominates
    working — slots, not capacity, are the bottleneck).
    Lower: the queue has been empty and the running count sits well
    under the limit — shrink toward the observed need so a later load
    spike degrades gradually (queue first) instead of thrashing.
    A limit of 0 means the operator chose 'unlimited': the controller
    never turns admission control on by itself."""

    name = "admission"
    # queue wait above this multiple of mean statement cost = pressure
    QUEUE_COST_RATIO = 1.0

    def decide(self, sig: dict) -> int:
        knob = "scheduler.max_concurrency"
        cur = int(self.knobs.get(knob))
        if cur <= 0:
            return 0
        queued = int(sig.get("queued") or 0)
        running = int(sig.get("running") or 0)
        mean_cost = sig.get("mean_cost_ms")
        qp99 = sig.get("queue_p99_ms")
        evidence = {k: sig[k] for k in
                    ("running", "queued", "mean_cost_ms", "queue_p99_ms",
                     "shed_total") if k in sig}
        evidence["top"] = sig.get("top") or []
        evidence["limit"] = cur
        if queued > 0:
            pressured = True
            if mean_cost and qp99 is not None:
                pressured = (qp99
                             >= self.QUEUE_COST_RATIO * float(mean_cost))
            if pressured:
                return self._move(knob, cur * (1.0 + self.rails.step),
                                  evidence)
            return 0
        if running < cur * (1.0 - self.rails.band):
            # idle headroom: one slot above the observed concurrency
            return self._move(knob, max(1, running + 1), evidence)
        return 0


# ----------------------------------------------------------------------
# planner: shard/replicate thresholds
# ----------------------------------------------------------------------

class PlannerThresholdController(Controller):
    """Moves `[mesh] shard_min_series` / `shard_min_rows` from the
    MEASURED shard-vs-replicate latency ratio: when sharded
    statements run faster than replicated ones, work near the
    threshold is being left on the single-device path — lower it;
    when replicate wins (shard overhead dominating at the current
    margin), raise it. Both thresholds move by the same relative
    factor so the grid and row paths stay consistent."""

    name = "planner"

    def decide(self, sig: dict) -> int:
        shard_ms = float(sig.get("shard_ms") or 0.0)
        repl_ms = float(sig.get("replicate_ms") or 0.0)
        if shard_ms <= 0.0 or repl_ms <= 0.0:
            return 0
        speedup = repl_ms / shard_ms
        band = self.rails.band
        if abs(speedup - 1.0) <= band:
            return 0
        factor = ((1.0 - self.rails.step) if speedup > 1.0
                  else (1.0 + self.rails.step))
        evidence = dict(sig)
        evidence["shard_speedup"] = round(speedup, 3)
        n = 0
        for knob in ("mesh.shard_min_series", "mesh.shard_min_rows"):
            cur = self.knobs.get(knob)
            n += self._move(knob, cur * factor, evidence)
        return n


# ----------------------------------------------------------------------
# HBM: budget reallocation across the cache pools
# ----------------------------------------------------------------------

class HbmBudgetController(Controller):
    """Shifts byte budget between the registered cache pools
    (sessions / result / scan) toward the pool with the highest miss
    pressure per budget byte. Conservative by construction: the total
    budget is CONSERVED (one donor shrinks by exactly what one
    receiver gains), a transfer needs an actively evicting receiver,
    and the donor must be measurably colder than the receiver (the
    hysteresis band) so two warm pools never see-saw."""

    name = "hbm"
    # smallest transfer worth the churn (a starved pool near zero
    # budget still gets off the ground)
    MIN_TRANSFER = 64 * 1024

    @staticmethod
    def _pressure(p: dict) -> float:
        return p["misses_d"] / max(float(p["budget"]), 1.0)

    def decide(self, pools: list[dict]) -> int:
        if len(pools) < 2:
            return 0
        recv = max(pools, key=self._pressure)
        if recv["misses_d"] <= 0 or recv["evictions_d"] <= 0:
            return 0  # nobody is budget-starved
        donors = [p for p in pools if p is not recv]
        donor = min(donors, key=self._pressure)
        if (self._pressure(donor) * (1.0 + self.rails.band)
                >= self._pressure(recv)):
            return 0  # not enough contrast to act on
        # exact byte swap, step-clamped against the SMALLER budget so
        # neither pool moves more than `step` of itself in one decision
        delta = max(
            self.MIN_TRANSFER,
            int(min(donor["budget"], recv["budget"]) * self.rails.step),
        )
        dspec = self.knobs.spec(donor["knob"])
        floor = int(dspec.lo or 0) if dspec is not None else 0
        delta = min(delta, max(0, donor["budget"] - floor))
        if delta <= 0:
            return 0
        evidence = {"receiver": dict(recv), "donor": dict(donor),
                    "transfer_bytes": delta}
        self.knobs.set(donor["knob"], donor["budget"] - delta,
                       source=self.name, evidence=evidence)
        self.knobs.set(recv["knob"], recv["budget"] + delta,
                       source=self.name, evidence=evidence)
        return 2


# ----------------------------------------------------------------------
# compaction: pacing from read-amp vs ingest rate
# ----------------------------------------------------------------------

class CompactionPacingController(Controller):
    """Paces merges against the measured read/write balance: read-amp
    past the L1 trigger means scans are paying for deferred merges —
    tighten the trigger first (cheap), widen the pool when the
    trigger is already at its floor (parallel merges). Read-amp well
    under the trigger with the pool widened means merging outran
    ingest — give the width back so merge threads don't sit on the
    thread budget. The trigger is never relaxed past its configured
    start (write-amp guard), and the pool never shrinks below 1."""

    name = "compaction"

    def __init__(self, knobs, sense, *, baseline_workers: int = 1,
                 **kw):
        super().__init__(knobs, sense, **kw)
        self.baseline_workers = max(1, int(baseline_workers))

    def decide(self, sig: dict) -> int:
        trigger = int(self.knobs.get("compaction.l1_trigger_files"))
        workers = int(self.knobs.get("compaction.workers"))
        read_amp = int(sig.get("read_amp") or 0)
        evidence = dict(sig)
        evidence.update({"l1_trigger_files": trigger,
                         "workers": workers})
        spec = self.knobs.spec("compaction.l1_trigger_files")
        floor = int(spec.lo) if spec and spec.lo is not None else 2
        if read_amp > trigger * (1.0 + self.rails.band):
            if trigger > floor:
                return self._move("compaction.l1_trigger_files",
                                  trigger * (1.0 - self.rails.step),
                                  evidence)
            return self._move("compaction.workers", workers + 1,
                              evidence)
        if (read_amp < trigger * (1.0 - self.rails.band)
                and workers > self.baseline_workers):
            return self._move("compaction.workers",
                              max(self.baseline_workers, workers - 1),
                              evidence)
        return 0
